"""MobileNet variants for CIFAR-10.

Capability parity with the reference's FasterMobileNet
(fedstellar/learning/pytorch/cifar10/models/fastermobilenet.py) and
SimpleMobileNetV1 (simplemobilenet.py): depthwise-separable conv
stacks. GroupNorm for federated friendliness; NHWC bfloat16.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from p2pfl_tpu.models.base import register_model


class DepthwiseSeparable(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_feats = x.shape[-1]
        x = nn.Conv(in_feats, (3, 3), strides=(self.strides,) * 2, padding="SAME",
                    feature_group_count=in_feats, use_bias=False,
                    dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, in_feats), dtype=self.dtype,
                         param_dtype=self.param_dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False,
                    dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, self.features), dtype=self.dtype,
                         param_dtype=self.param_dtype)(x)
        return nn.relu(x)


class MobileNet(nn.Module):
    """Stem conv + depthwise-separable blocks + linear head."""

    blocks: Sequence[tuple[int, int]] = ((64, 1), (128, 2), (128, 1), (256, 2))
    stem: int = 32
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(self.stem, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, self.stem), dtype=self.dtype,
                         param_dtype=self.param_dtype)(x)
        x = nn.relu(x)
        for feats, strides in self.blocks:
            x = DepthwiseSeparable(feats, strides=strides, dtype=self.dtype,
                                   param_dtype=self.param_dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)


@register_model("fastermobilenet")
def FasterMobileNet(num_classes: int = 10, **kw) -> MobileNet:
    """Small 4-block variant (fastermobilenet.py analog)."""
    return MobileNet(blocks=((64, 1), (128, 2), (128, 1), (256, 2)),
                     num_classes=num_classes, **kw)


@register_model("simplemobilenet", "simplemobilenetv1")
def SimpleMobileNet(num_classes: int = 10, **kw) -> MobileNet:
    """Fuller MobileNetV1-style stack (simplemobilenet.py analog)."""
    return MobileNet(
        blocks=((64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
                (512, 1), (512, 1)),
        num_classes=num_classes, **kw)
