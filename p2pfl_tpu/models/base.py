"""Model registry.

Successor of the reference's if/elif model factory in
fedstellar/node_start.py:46-85 (model chosen by string from
``model_args.model``): an explicit registry keyed by
``(dataset, model)`` aliases, returning constructed flax modules.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn

_REGISTRY: dict[str, Callable[..., nn.Module]] = {}


def register_model(*names: str):
    """Decorator registering a model factory under one or more names."""

    def deco(fn: Callable[..., nn.Module]):
        for name in names:
            key = name.lower()
            if key in _REGISTRY:
                raise ValueError(f"model name {name!r} already registered")
            _REGISTRY[key] = fn
        return fn

    return deco


def get_model(name: str, **kwargs) -> nn.Module:
    """Build a model by registry name.

    Names mirror the reference's ``model_args.model`` strings
    (node_start.py:46-85): e.g. ``mlp``/``mnist-mlp``, ``mnist-cnn``,
    ``femnist-cnn``, ``resnet9``, ``simplemobilenet``.
    """
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def build_model(model_cfg) -> nn.Module:
    """Construct a model from a ``ModelConfig``, honoring its dtype
    knobs: ``compute_dtype`` feeds the modules' ``dtype`` and
    ``param_dtype`` their parameter storage. ``None`` (the default)
    keeps each model's own choice — important for the one-class SVM,
    which computes in f32 on purpose. Explicit ``kwargs`` entries win
    so a scenario can still override per-model."""
    import jax.numpy as jnp

    kwargs = dict(model_cfg.kwargs)
    if model_cfg.compute_dtype is not None:
        kwargs.setdefault("dtype", jnp.dtype(model_cfg.compute_dtype))
    if model_cfg.param_dtype is not None:
        kwargs.setdefault("param_dtype", jnp.dtype(model_cfg.param_dtype))
    return get_model(model_cfg.model, **kwargs)
