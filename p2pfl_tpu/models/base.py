"""Model registry.

Successor of the reference's if/elif model factory in
fedstellar/node_start.py:46-85 (model chosen by string from
``model_args.model``): an explicit registry keyed by
``(dataset, model)`` aliases, returning constructed flax modules.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn

_REGISTRY: dict[str, Callable[..., nn.Module]] = {}

# per-model LoRA adapter-target metadata (learning.lora): default
# target patterns plus each pattern's (out_axes, base_ndim) kernel
# view — how many trailing axes are outputs and how many axes the
# unscanned kernel has (extra leading axes broadcast, e.g. nn.scan's
# depth axis). Registered next to the factory because the split is a
# property of the architecture, not of any one scenario.
_LORA_TARGETS: dict[str, tuple[tuple[str, ...], dict[str, tuple[int, int]]]] = {}


def register_lora_targets(*names: str, default: tuple[str, ...],
                          specs: dict[str, tuple[int, int]] | None = None
                          ) -> None:
    """Register a model's default LoRA targets + kernel axis specs."""
    entry = (tuple(default), dict(specs or {}))
    for name in names:
        _LORA_TARGETS[name.lower()] = entry


def default_lora_targets(name: str) -> tuple[str, ...]:
    """A model's registered default adapter targets. Loud when the
    model registers none — silently adapting nothing (or guessing
    kernels) would report a fine-tune that never ran; the scenario
    must then set ``lora.targets`` explicitly."""
    entry = _LORA_TARGETS.get(name.lower())
    if entry is None or not entry[0]:
        raise ValueError(
            f"model {name!r} registers no default lora targets "
            f"(have {sorted(_LORA_TARGETS)}); set lora.targets "
            "explicitly"
        )
    return entry[0]


def lora_axis_specs(name: str) -> dict[str, tuple[int, int]]:
    """Per-pattern (out_axes, base_ndim) kernel views; patterns absent
    here fall back to the plain 2-D ``(..., d_in, d_out)`` view."""
    entry = _LORA_TARGETS.get(name.lower())
    return dict(entry[1]) if entry else {}


def register_model(*names: str):
    """Decorator registering a model factory under one or more names."""

    def deco(fn: Callable[..., nn.Module]):
        for name in names:
            key = name.lower()
            if key in _REGISTRY:
                raise ValueError(f"model name {name!r} already registered")
            _REGISTRY[key] = fn
        return fn

    return deco


def get_model(name: str, **kwargs) -> nn.Module:
    """Build a model by registry name.

    Names mirror the reference's ``model_args.model`` strings
    (node_start.py:46-85): e.g. ``mlp``/``mnist-mlp``, ``mnist-cnn``,
    ``femnist-cnn``, ``resnet9``, ``simplemobilenet``.
    """
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def build_model(model_cfg) -> nn.Module:
    """Construct a model from a ``ModelConfig``, honoring its dtype
    knobs: ``compute_dtype`` feeds the modules' ``dtype`` and
    ``param_dtype`` their parameter storage. ``None`` (the default)
    keeps each model's own choice — important for the one-class SVM,
    which computes in f32 on purpose. Explicit ``kwargs`` entries win
    so a scenario can still override per-model."""
    import jax.numpy as jnp

    kwargs = dict(model_cfg.kwargs)
    if model_cfg.compute_dtype is not None:
        kwargs.setdefault("dtype", jnp.dtype(model_cfg.compute_dtype))
    if model_cfg.param_dtype is not None:
        kwargs.setdefault("param_dtype", jnp.dtype(model_cfg.param_dtype))
    return get_model(model_cfg.model, **kwargs)
