"""ResNets for CIFAR-10.

Capability parity with the reference's CIFAR10ModelResNet
(fedstellar/learning/pytorch/cifar10/models/resnet.py:23-36,174-201 —
a hand-built resnet9 plus resnet18/34/50 via a classifier dict).

TPU-first choices: NHWC, bfloat16 compute, GroupNorm instead of
BatchNorm (pure param pytree; robust under non-IID federated data).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from p2pfl_tpu.models.base import register_model
from p2pfl_tpu.models.cnn import PATCH_CONV_MAX_CONTRACTION, PatchConv


def _gn(groups: int, dtype, param_dtype):
    return nn.GroupNorm(num_groups=groups, dtype=dtype, param_dtype=param_dtype)


class ConvBlock(nn.Module):
    features: int
    pool: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.shape[-1] * 9 <= PATCH_CONV_MAX_CONTRACTION:
            # the RGB stem (contraction 27) under the vmapped
            # federation lowers to a degenerate grouped conv — same
            # fix as the LEAF CNN's conv1 (models/cnn.py PatchConv);
            # name="Conv_0" keeps pre-PatchConv checkpoints loadable
            x = PatchConv(self.features, (3, 3), use_bias=False,
                          dtype=self.dtype,
                          param_dtype=self.param_dtype,
                          name="Conv_0")(x)
        else:
            x = nn.Conv(self.features, (3, 3), padding="SAME",
                        use_bias=False, dtype=self.dtype,
                        param_dtype=self.param_dtype)(x)
        x = _gn(min(32, self.features), self.dtype, self.param_dtype)(x)
        x = nn.relu(x)
        if self.pool:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x


class Residual(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = ConvBlock(self.features, dtype=self.dtype,
                      param_dtype=self.param_dtype)(x)
        y = ConvBlock(self.features, dtype=self.dtype,
                      param_dtype=self.param_dtype)(y)
        return x + y


class ResNet9(nn.Module):
    """The fast CIFAR ResNet9: prep → 2×(conv-pool + residual) → head."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        x = ConvBlock(64, **kw)(x)
        x = ConvBlock(128, pool=True, **kw)(x)
        x = Residual(128, **kw)(x)
        x = ConvBlock(256, pool=True, **kw)(x)
        x = ConvBlock(512, pool=True, **kw)(x)
        x = Residual(512, **kw)(x)
        x = jnp.max(x, axis=(1, 2))  # global max pool
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32) * 0.125  # resnet9 logit scaling


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kw = dict(use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype)
        y = nn.Conv(self.features, (3, 3), strides=(self.strides,) * 2,
                    padding="SAME", **kw)(x)
        y = _gn(min(32, self.features), self.dtype, self.param_dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", **kw)(y)
        y = _gn(min(32, self.features), self.dtype, self.param_dtype)(y)
        if x.shape != y.shape:
            x = nn.Conv(self.features, (1, 1), strides=(self.strides,) * 2, **kw)(x)
            x = _gn(min(32, self.features), self.dtype, self.param_dtype)(x)
        return nn.relu(x + y)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kw = dict(use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype)
        out = self.features * 4
        y = nn.Conv(self.features, (1, 1), **kw)(x)
        y = _gn(min(32, self.features), self.dtype, self.param_dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), strides=(self.strides,) * 2,
                    padding="SAME", **kw)(y)
        y = _gn(min(32, self.features), self.dtype, self.param_dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(out, (1, 1), **kw)(y)
        y = _gn(min(32, out), self.dtype, self.param_dtype)(y)
        if x.shape != y.shape:
            x = nn.Conv(out, (1, 1), strides=(self.strides,) * 2, **kw)(x)
            x = _gn(min(32, out), self.dtype, self.param_dtype)(x)
        return nn.relu(x + y)


class ResNet(nn.Module):
    """Generic CIFAR-style ResNet-{18,34,50} (3×3 stem, no max-pool)."""

    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    bottleneck: bool = False
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = _gn(32, self.dtype, self.param_dtype)(x)
        x = nn.relu(x)
        block = Bottleneck if self.bottleneck else BasicBlock
        for stage, n_blocks in enumerate(self.stage_sizes):
            feats = 64 * (2**stage)
            for b in range(n_blocks):
                strides = 2 if (stage > 0 and b == 0) else 1
                x = block(feats, strides=strides, dtype=self.dtype,
                          param_dtype=self.param_dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)


@register_model("resnet9", "cifar10-resnet9", "cifar10modelresnet")
def _resnet9(num_classes: int = 10, **kw) -> ResNet9:
    return ResNet9(num_classes=num_classes, **kw)


@register_model("resnet18", "cifar10-resnet18")
def _resnet18(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, **kw)


@register_model("resnet34", "cifar10-resnet34")
def _resnet34(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw)


@register_model("resnet50", "cifar10-resnet50")
def _resnet50(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), bottleneck=True,
                  num_classes=num_classes, **kw)


def CIFAR10ModelResNet(depth: int = 9, **kw) -> nn.Module:
    """Factory matching the reference's classifier-dict style
    (cifar10/models/resnet.py:23-36)."""
    factories = {9: _resnet9, 18: _resnet18, 34: _resnet34, 50: _resnet50}
    return factories[depth](**kw)
