"""MLP family.

Covers the reference's MNISTModelMLP
(fedstellar/learning/pytorch/mnist/models/mlp.py:144-146 — 784→256→128→10),
SyscallModelMLP (syscall/models/mlp.py) and WADIModelMLP
(wadi/models/mlp.py), as one parameterized flax module.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from p2pfl_tpu.models.base import register_model


class MLP(nn.Module):
    """Flatten → stack of Dense+ReLU → logits.

    Compute in ``dtype`` (bfloat16 by default → MXU), params in
    ``param_dtype``.
    """

    features: Sequence[int] = (256, 128)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for f in self.features:
            x = nn.Dense(f, dtype=self.dtype, param_dtype=self.param_dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype
        )(x)
        return x.astype(jnp.float32)


@register_model("mlp", "mnist-mlp", "mnistmodelmlp")
def MNISTModelMLP(num_classes: int = 10, **kw) -> MLP:
    """784→256→128→10, matching the reference's MNIST MLP shape."""
    return MLP(features=(256, 128), num_classes=num_classes, **kw)


@register_model("syscall-mlp", "syscallmodelmlp")
def SyscallModelMLP(in_features: int = 17, num_classes: int = 9, **kw) -> MLP:
    """Tabular syscall-trace classifier (syscall/models/mlp.py analog)."""
    return MLP(features=(64, 64), num_classes=num_classes, **kw)


@register_model("wadi-mlp", "wadimodelmlp")
def WADIModelMLP(in_features: int = 123, num_classes: int = 2, **kw) -> MLP:
    """WADI anomaly-detection MLP (wadi/models/mlp.py analog)."""
    return MLP(features=(128, 64, 32), num_classes=num_classes, **kw)
