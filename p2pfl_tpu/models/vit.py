"""ViT for federated fine-tuning (BASELINE.json stretch config:
"ViT-Tiny federated fine-tune, 32 nodes, Krum/trimmed-mean").

No counterpart exists in the reference (its largest model is ResNet —
SURVEY.md §2.9); this is the attention workload that exercises the
sequence-parallel path in p2pfl_tpu.ops.ring_attention: set
``seq_axis`` to a mesh axis name and the attention runs blockwise over
sequence shards with ``ppermute`` K/V rotation over ICI.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from p2pfl_tpu.models.base import register_lora_targets, register_model


class TransformerBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: str | None = None  # mesh axis for ring attention

    def _qkv(self, y):
        head = (self.heads, self.dim // self.heads)
        return tuple(
            nn.DenseGeneral(head, dtype=self.dtype,
                            param_dtype=self.param_dtype, name=name)(y)
            for name in ("query", "key", "value")
        )

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        if self.seq_axis is not None:
            from p2pfl_tpu.ops.ring_attention import ring_self_attention

            attn = lambda q, k, v: ring_self_attention(
                q, k, v, axis_name=self.seq_axis
            )
            y = attn(*self._qkv(y))
            y = nn.DenseGeneral(self.dim, axis=(-2, -1), dtype=self.dtype,
                                param_dtype=self.param_dtype, name="out")(y)
        else:
            y = nn.MultiHeadDotProductAttention(
                num_heads=self.heads, dtype=self.dtype,
                param_dtype=self.param_dtype)(y, y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        y = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype,
                     param_dtype=self.param_dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype, param_dtype=self.param_dtype)(y)
        return x + y


class _BlockStep(nn.Module):
    """``nn.scan`` adapter: ``(carry, _) -> (carry, None)`` around one
    (optionally rematted) TransformerBlock."""

    remat: bool = False
    block_kw: Any = None

    @nn.compact
    def __call__(self, x, _):
        cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        return cls(**(self.block_kw or {}))(x), None


class ViT(nn.Module):
    """ViT-Tiny by default: patch 4 (CIFAR-scale), dim 192, 12 layers."""

    patch: int = 4
    dim: int = 192
    depth: int = 12
    heads: int = 3
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    seq_axis: str | None = None
    remat: bool = False  # jax.checkpoint each block: trade recompute
    # for ~depth x less activation memory — lets a federation of many
    # ViT replicas (vmapped per-node weights) fit a single chip's HBM
    scan_layers: bool = False  # nn.scan over depth: XLA compiles ONE
    # block instead of `depth` unrolled copies (params gain a leading
    # [depth] axis) — cuts compile time ~depth x for deep stacks

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), dtype=self.dtype,
                    param_dtype=self.param_dtype, name="patch_embed")(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, h * w, c), self.param_dtype)
        x = x + pos.astype(self.dtype)
        block_cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        block_kw = dict(dim=self.dim, heads=self.heads, dtype=self.dtype,
                        param_dtype=self.param_dtype,
                        seq_axis=self.seq_axis)
        if self.scan_layers:
            scanned = nn.scan(
                _BlockStep,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=self.depth,
            )
            x, _ = scanned(remat=self.remat, block_kw=block_kw,
                           name="blocks")(x, None)
        else:
            for _ in range(self.depth):
                x = block_cls(**block_kw)(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = jnp.mean(x, axis=1)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)


@register_model("vit-tiny", "vit")
def _vit_tiny(num_classes: int = 10, **kw) -> ViT:
    return ViT(num_classes=num_classes, **kw)


# Adapter targets (learning.lora): default is the classic q/v pair —
# the smallest split that fine-tunes attention. Axis specs give each
# kernel's (out_axes, base_ndim) view: q/k/v kernels are
# [dim, heads, head_dim] (two output axes), the out projection is
# [heads, head_dim, dim], MLP Dense kernels are plain [d_in, d_out],
# patch_embed is a Conv [kh, kw, cin, cout]. Under scan_layers every
# block kernel gains a leading [depth] axis, which the lora matmul
# broadcasts over — per-layer adapters in one contraction.
register_lora_targets(
    "vit-tiny", "vit",
    default=("query", "value"),
    specs={"query": (2, 3), "key": (2, 3), "value": (2, 3),
           "out": (1, 3), "Dense": (1, 2), "patch_embed": (1, 4)},
)
