"""Small convnets for MNIST / FEMNIST.

Capability parity with the reference's MNISTModelCNN
(fedstellar/learning/pytorch/mnist/models/cnn.py) and FEMNISTModelCNN
(femnist/models/cnn.py — the LEAF CNN: two 5×5 conv blocks + 2048-wide
dense, 62 classes). NHWC layout (XLA's native conv layout on TPU),
bfloat16 compute.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from p2pfl_tpu.models.base import register_model


class SmallCNN(nn.Module):
    """conv(k×k,c1) → pool → conv(k×k,c2) → pool → dense(hidden) → logits."""

    channels: tuple[int, int] = (32, 64)
    kernel: int = 5
    hidden: int = 2048
    num_classes: int = 62
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]  # HW → HWC
        x = x.astype(self.dtype)
        k = (self.kernel, self.kernel)
        for c in self.channels:
            x = nn.Conv(c, k, padding="SAME", dtype=self.dtype,
                        param_dtype=self.param_dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)


@register_model("mnist-cnn", "cnn", "mnistmodelcnn")
def MNISTModelCNN(num_classes: int = 10, hidden: int = 512, **kw) -> SmallCNN:
    return SmallCNN(channels=(32, 64), kernel=3, hidden=hidden,
                    num_classes=num_classes, **kw)


@register_model("femnist-cnn", "femnistmodelcnn")
def FEMNISTModelCNN(num_classes: int = 62, hidden: int = 2048, **kw) -> SmallCNN:
    """The LEAF FEMNIST CNN shape — the north-star workload
    (BASELINE.json: 64-node FEMNIST-CNN federation)."""
    return SmallCNN(channels=(32, 64), kernel=5, hidden=hidden,
                    num_classes=num_classes, **kw)
