"""Small convnets for MNIST / FEMNIST.

Capability parity with the reference's MNISTModelCNN
(fedstellar/learning/pytorch/mnist/models/cnn.py) and FEMNISTModelCNN
(femnist/models/cnn.py — the LEAF CNN: two 5×5 conv blocks + 2048-wide
dense, 62 classes). NHWC layout (XLA's native conv layout on TPU),
bfloat16 compute.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from p2pfl_tpu.models.base import register_model
from p2pfl_tpu.ops import pallas_gemm

#: contraction size (C_in * k * k) at or below which a conv runs as
#: patches + matmul instead of lax.conv. The federation vmaps per-node
#: conv weights, which XLA lowers to feature_group_count=n_nodes
#: grouped convolutions; for tiny per-group contractions (conv1 of the
#: LEAF CNN: C_in=1, 5x5 -> 25) that lowering runs at <1% of the MXU
#: (measured: 13.2 ms fwd + 22 ms bwd vs 6.9 + 12 for the patches
#: form at n=64, b=224 — scripts/exp_op_breakdown.py). Patches cost a
#: contraction-fold memory inflation, so only small contractions
#: qualify (conv2's 800-wide patches sank whole-model im2col,
#: scripts/exp_im2col.py).
PATCH_CONV_MAX_CONTRACTION = 64


class PatchConv(nn.Module):
    """nn.Conv-compatible conv expressed as im2col patches + matmul.

    Same parameter tree as ``nn.Conv`` (``kernel`` [kh, kw, cin, f] +
    ``bias`` [f]) so checkpoints, aggregators, and param-shape checks
    see no difference; only the lowering changes.
    """

    features: int
    kernel_size: tuple[int, int]
    use_bias: bool = True
    dtype: jnp.dtype | None = None  # None = inherit x.dtype (nn.Conv
    # semantics — a drop-in must not silently downcast f32 inputs)
    param_dtype: jnp.dtype = jnp.float32
    # which measured gate kind owns the GEMM: "patches" (conv1's
    # small-contraction class — this module asks the gate itself) or
    # "conv2" (round 17: big contractions, where SmallCNN asks the
    # gate BEFORE instantiating — the XLA incumbent there is the
    # grouped-conv lowering, not an XLA patches matmul, so the
    # fallback lives outside this module)
    gate_kind: str = "patches"

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        cin = x.shape[-1]
        dtype = self.dtype or x.dtype
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (kh, kw, cin, self.features), self.param_dtype)
        patches = jax.lax.conv_general_dilated_patches(
            x.astype(dtype), (kh, kw), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # [..., H, W, cin*kh*kw], channel-major patch order
        # patches order the feature dim as (cin, kh, kw); HWIO kernels
        # are (kh, kw, cin) -> transpose before flattening to match
        wf = (w.astype(dtype)
              .transpose(2, 0, 1, 3).reshape(cin * kh * kw, self.features))
        # the GEMM itself routes through the measured gate: Pallas
        # streams M over a VMEM-stationary [K, N] weight tile (fwd,
        # dgrad, wgrad — docs/perf.md §6.4), XLA otherwise. Bias and
        # the downstream relu/pool stay XLA either way: they fuse into
        # the pooling pass, so the kernel saves nothing by absorbing
        # them.
        flat = patches.reshape(-1, cin * kh * kw)
        if self.gate_kind == "conv2":
            # the gate already chose pallas upstream (SmallCNN measures
            # patches+kernel against the grouped conv end to end);
            # dgrad stays XLA inside conv2_matmul's VJP — §6.2 has it
            # at its floor
            out = pallas_gemm.conv2_matmul(flat, wf)
        elif pallas_gemm.choose("patches", (flat.shape, wf.shape),
                                dtype) == "pallas":
            out = pallas_gemm.patches_matmul(flat, wf)
        else:
            out = flat @ wf
        out = out.reshape(patches.shape[:-1] + (self.features,))
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros,
                           (self.features,), self.param_dtype)
            out = out + b.astype(dtype)
        return out


class GatedDense(nn.Module):
    """nn.Dense-compatible layer whose BACKWARD routes through the
    measured Pallas gate.

    Same parameter tree, init and forward math as ``nn.Dense`` (XLA
    forward — it sits near its floor); when the gate picks Pallas the
    backward runs the fused dgrad+wgrad kernel (one streaming pass
    over activations and weight, cotangent VMEM-stationary) instead of
    XLA's two independent GEMMs — the dense1 half of perf.md §6.4.
    """

    features: int
    dtype: jnp.dtype | None = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        dtype = self.dtype or x.dtype
        k = self.param("kernel", nn.initializers.lecun_normal(),
                       (x.shape[-1], self.features), self.param_dtype)
        b = self.param("bias", nn.initializers.zeros,
                       (self.features,), self.param_dtype)
        x, k = x.astype(dtype), k.astype(dtype)
        if pallas_gemm.choose("dense_bwd", (x.shape, k.shape),
                              dtype) == "pallas":
            out = pallas_gemm.dense_matmul(x, k)
        else:
            out = x @ k
        return out + b.astype(dtype)


class SmallCNN(nn.Module):
    """conv(k×k,c1) → pool → conv(k×k,c2) → pool → dense(hidden) → logits."""

    channels: tuple[int, int] = (32, 64)
    kernel: int = 5
    hidden: int = 2048
    num_classes: int = 62
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]  # HW → HWC
        x = x.astype(self.dtype)
        k = (self.kernel, self.kernel)
        for i, c in enumerate(self.channels):
            # explicit name= keeps the param tree keyed Conv_N exactly
            # as nn.Conv auto-naming did, so pre-PatchConv checkpoints
            # still resume (the two modules share param shapes)
            contraction = x.shape[-1] * self.kernel ** 2
            if contraction <= PATCH_CONV_MAX_CONTRACTION:
                x = PatchConv(c, k, dtype=self.dtype,
                              param_dtype=self.param_dtype,
                              name=f"Conv_{i}")(x)
            elif pallas_gemm.choose(
                "conv2",
                ((math.prod(x.shape[:-1]), contraction),
                 (contraction, c), tuple(x.shape), k),
                self.dtype,
            ) == "pallas":
                # big-contraction convs (conv2 of the LEAF CNN: K=800)
                # whose grouped-conv lowering the gate MEASURED as
                # slower than patches + the streamed Pallas GEMM end to
                # end (including the 25× im2col inflation — the reason
                # this is a measured gate, not a threshold). Same param
                # tree either way, so init/apply taking different
                # branches at different batch sizes is checkpoint-safe.
                x = PatchConv(c, k, dtype=self.dtype,
                              param_dtype=self.param_dtype,
                              gate_kind="conv2",
                              name=f"Conv_{i}")(x)
            else:
                x = nn.Conv(c, k, padding="SAME", dtype=self.dtype,
                            param_dtype=self.param_dtype,
                            name=f"Conv_{i}")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        # explicit name= keeps the tree keyed Dense_0/Dense_1 as the
        # nn.Dense auto-naming did (same rationale as Conv_N above);
        # dense1's backward is the gated Pallas hot path
        x = GatedDense(self.hidden, dtype=self.dtype,
                       param_dtype=self.param_dtype, name="Dense_0")(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="Dense_1")(x)
        return x.astype(jnp.float32)


@register_model("mnist-cnn", "cnn", "mnistmodelcnn")
def MNISTModelCNN(num_classes: int = 10, hidden: int = 512, **kw) -> SmallCNN:
    return SmallCNN(channels=(32, 64), kernel=3, hidden=hidden,
                    num_classes=num_classes, **kw)


@register_model("femnist-cnn", "femnistmodelcnn")
def FEMNISTModelCNN(num_classes: int = 62, hidden: int = 2048, **kw) -> SmallCNN:
    """The LEAF FEMNIST CNN shape — the north-star workload
    (BASELINE.json: 64-node FEMNIST-CNN federation)."""
    return SmallCNN(channels=(32, 64), kernel=5, hidden=hidden,
                    num_classes=num_classes, **kw)
