"""Model zoo: flax modules for every workload family the reference ships.

Reference inventory (SURVEY.md §2.4, fedstellar/learning/pytorch/*):
MNIST MLP/CNN, FEMNIST CNN, CIFAR10 ResNet9/18/34/50 + two MobileNets,
SYSCALL MLP/Autoencoder/One-class-SVM, WADI MLP — plus ViT-Tiny for the
stretch config in BASELINE.json.

TPU-first design notes:
- Normalization is **GroupNorm**, not BatchNorm: batch statistics are
  known-pathological under non-IID federated data, and GroupNorm keeps
  the model a *pure* param pytree (no mutable batch_stats collection to
  gossip separately), which keeps every federated collective a single
  fixed-shape tree op.
- All modules take ``dtype`` (compute) and ``param_dtype`` so the MXU
  path runs bfloat16 with float32 params by default.
"""

from p2pfl_tpu.models.base import get_model, list_models, register_model
from p2pfl_tpu.models.mlp import MLP, MNISTModelMLP, SyscallModelMLP, WADIModelMLP
from p2pfl_tpu.models.cnn import FEMNISTModelCNN, MNISTModelCNN
from p2pfl_tpu.models.resnet import CIFAR10ModelResNet, ResNet
from p2pfl_tpu.models.mobilenet import FasterMobileNet, SimpleMobileNet
from p2pfl_tpu.models.syscall import SyscallModelAutoencoder, SyscallModelOneClassSVM
from p2pfl_tpu.models.vit import ViT

__all__ = [
    "get_model",
    "list_models",
    "register_model",
    "MLP",
    "MNISTModelMLP",
    "SyscallModelMLP",
    "WADIModelMLP",
    "MNISTModelCNN",
    "FEMNISTModelCNN",
    "ResNet",
    "CIFAR10ModelResNet",
    "FasterMobileNet",
    "SimpleMobileNet",
    "SyscallModelAutoencoder",
    "SyscallModelOneClassSVM",
    "ViT",
]
