"""SYSCALL behavioral-fingerprinting models.

Capability parity with the reference's SyscallModelAutoencoder
(fedstellar/learning/pytorch/syscall/models/autoencoder.py) and
SyscallModelSGDOneClassSVM (svm.py). The MLP classifier lives in
p2pfl_tpu.models.mlp.

The one-class SVM is the linear ν-OCSVM trained by SGD: score
``w·x − ρ``; its loss (see p2pfl_tpu.learning.objectives.ocsvm_loss)
is ``½‖w‖² + 1/ν · mean(max(0, ρ − w·x)) − ρ`` — the same objective
sklearn's SGDOneClassSVM optimizes in the reference.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from p2pfl_tpu.models.base import register_model


class Autoencoder(nn.Module):
    """Dense autoencoder; anomaly score = reconstruction error."""

    in_features: int = 17
    encoder: Sequence[int] = (64, 16)
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for f in self.encoder:
            x = nn.relu(nn.Dense(f, dtype=self.dtype,
                                 param_dtype=self.param_dtype)(x))
        for f in reversed(self.encoder[:-1]):
            x = nn.relu(nn.Dense(f, dtype=self.dtype,
                                 param_dtype=self.param_dtype)(x))
        x = nn.Dense(self.in_features, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)


class OneClassSVM(nn.Module):
    """Linear one-class SVM head: returns decision scores ``w·x − ρ``."""

    in_features: int = 17
    # compute dtype defaults to f32 (a 17-wide dot has no MXU win and
    # the margin comparison is precision-sensitive); accepted so the
    # ModelConfig.compute_dtype knob applies uniformly
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        w = self.param("w", nn.initializers.zeros, (self.in_features,),
                       self.param_dtype)
        rho = self.param("rho", nn.initializers.zeros, (), self.param_dtype)
        return (x @ w.astype(self.dtype) - rho.astype(self.dtype)).astype(
            jnp.float32
        )


@register_model("syscall-autoencoder", "syscallmodelautoencoder")
def SyscallModelAutoencoder(in_features: int = 17, **kw) -> Autoencoder:
    return Autoencoder(in_features=in_features, **kw)


@register_model("syscall-svm", "syscallmodelsgdoneclasssvm")
def SyscallModelOneClassSVM(in_features: int = 17, **kw) -> OneClassSVM:
    return OneClassSVM(in_features=in_features, **kw)
