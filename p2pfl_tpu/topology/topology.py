"""Federation topologies as adjacency matrices.

TPU-native re-design of the reference's TopologyManager
(fedstellar/utils/topologymanager.py): the same four families —
fully-connected (:303-318), ring with optional random "convergence"
extra edges (:213-228), random symmetric/asymmetric (:230-301), and
star for CFL (:121-125) — produced as numpy boolean adjacency matrices.

The TPU twist: an adjacency matrix is also a **communication schedule**.
``Topology.mixing_matrix`` turns it into a row-stochastic weight matrix
W so one gossip round is ``params' = W @ params`` — executed on device
as a masked all-gather + einsum, or decomposed into ``ppermute`` steps
(see p2pfl_tpu.parallel.transport). Metropolis-Hastings weights make W
doubly stochastic, which is the standard convergence guarantee for
decentralized averaging that the reference's ad-hoc gossip lacks.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class Topology:
    """An undirected-or-directed federation graph over ``n`` nodes."""

    adjacency: np.ndarray  # [n, n] bool, no self-loops
    kind: str = "custom"

    def __post_init__(self):
        a = np.asarray(self.adjacency, dtype=bool)
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"adjacency must be square, got {a.shape}")
        a = a.copy()
        np.fill_diagonal(a, False)
        object.__setattr__(self, "adjacency", a)

    @property
    def n(self) -> int:
        return self.adjacency.shape[0]

    def neighbors(self, i: int) -> list[int]:
        """Out-neighbors of node i (topologymanager.py:188-211 equivalent)."""
        return [int(j) for j in np.flatnonzero(self.adjacency[i])]

    def degree(self) -> np.ndarray:
        return self.adjacency.sum(axis=1)

    def is_symmetric(self) -> bool:
        return bool((self.adjacency == self.adjacency.T).all())

    def is_connected(self) -> bool:
        """Connectivity of the *communication* graph.

        Symmetric graphs: BFS over the edges. Directed graphs: strong
        connectivity (every node reachable from 0 following edges, and 0
        reachable from every node) — a weakly-connected directed gossip
        graph can still starve a node of incoming models.
        """
        if self.is_symmetric():
            return self._reachable_all(self.adjacency)
        return self._reachable_all(self.adjacency) and self._reachable_all(
            self.adjacency.T
        )

    def _reachable_all(self, a: np.ndarray) -> bool:
        seen = np.zeros(self.n, dtype=bool)
        frontier = [0]
        seen[0] = True
        while frontier:
            nxt = []
            for i in frontier:
                for j in np.flatnonzero(a[i]):
                    if not seen[j]:
                        seen[j] = True
                        nxt.append(int(j))
            frontier = nxt
        return bool(seen.all())

    def mixing_matrix(self, scheme: str = "metropolis") -> np.ndarray:
        """Row-stochastic gossip weight matrix (incl. self-loop weights).

        - ``metropolis``: W_ij = 1/(1+max(d_i,d_j)) for edges; doubly
          stochastic on symmetric graphs.
        - ``uniform``: average self with all neighbors equally — the
          reference's implicit FedAvg-over-neighborhood behavior
          (node.py:411-422 train_set = neighbors + self).
        """
        a = self.adjacency
        n = self.n
        if scheme == "metropolis":
            d = a.sum(axis=1)
            w = np.zeros((n, n), dtype=np.float64)
            ii, jj = np.nonzero(a)
            w[ii, jj] = 1.0 / (1.0 + np.maximum(d[ii], d[jj]))
            np.fill_diagonal(w, 1.0 - w.sum(axis=1))
        elif scheme == "uniform":
            w = a.astype(np.float64)
            np.fill_diagonal(w, 1.0)
            w = w / w.sum(axis=1, keepdims=True)
        else:
            raise ValueError(f"unknown mixing scheme {scheme!r}")
        return w

    def to_dict(self) -> dict:
        """JSON-able export (3-D topology export analog,
        topologymanager.py:320-355)."""
        return {
            "kind": self.kind,
            "n": self.n,
            "edges": [[int(i), int(j)] for i, j in zip(*np.nonzero(self.adjacency))],
        }

    def to_3d(self, seed: int = 0, geo: "np.ndarray | None" = None) -> dict:
        """3-D topology export (topologymanager.py:320-355): nodes on a
        unit sphere (deterministic Fibonacci lattice — uniform without
        randomness) plus optional geo coordinates, edges as index
        pairs. Rendered by the dashboard or any three.js-style viewer."""
        n = self.n
        k = np.arange(n, dtype=np.float64) + 0.5
        phi = np.arccos(1.0 - 2.0 * k / n)
        theta = np.pi * (1.0 + 5.0**0.5) * k
        xyz = np.stack(
            [np.sin(phi) * np.cos(theta), np.sin(phi) * np.sin(theta),
             np.cos(phi)],
            axis=1,
        )
        out = {
            "kind": self.kind,
            "n": n,
            "nodes": [
                {"id": int(i), "x": round(float(x), 4),
                 "y": round(float(y), 4), "z": round(float(z), 4)}
                for i, (x, y, z) in enumerate(xyz)
            ],
            "edges": [
                [int(i), int(j)]
                for i, j in zip(*np.nonzero(self.adjacency)) if i < j
            ],
        }
        if geo is None:
            geo = geo_coordinates(n, seed=seed)
        for node, (lat, lon) in zip(out["nodes"], geo):
            node["lat"] = round(float(lat), 4)
            node["lon"] = round(float(lon), 4)
        return out

    @staticmethod
    def from_dict(d: dict) -> "Topology":
        a = np.zeros((d["n"], d["n"]), dtype=bool)
        for i, j in d["edges"]:
            a[i, j] = True
        return Topology(a, kind=d.get("kind", "custom"))


#: named lat/lon boxes for random node placement — the reference drops
#: participants into Spain or Switzerland for its monitoring map
#: (topologymanager.py:151-173)
GEO_BOUNDS = {
    "spain": (36.0, 43.5, -9.0, 3.0),
    "switzerland": (45.9, 47.8, 6.0, 10.5),
}


def geo_coordinates(n: int, seed: int = 0,
                    region: str = "spain") -> np.ndarray:
    """Random-but-deterministic per-node geo coordinates ``[n, 2]``
    (lat, lon) inside a named region (topologymanager.py:151-173's
    random Spain/Switzerland coordinates, seeded for reproducibility)."""
    if region not in GEO_BOUNDS:
        raise ValueError(
            f"unknown region {region!r}; have {sorted(GEO_BOUNDS)}"
        )
    lat0, lat1, lon0, lon1 = GEO_BOUNDS[region]
    rng = np.random.default_rng(seed)
    lat = rng.uniform(lat0, lat1, size=n)
    lon = rng.uniform(lon0, lon1, size=n)
    return np.stack([lat, lon], axis=1)


def fully_connected(n: int) -> Topology:
    a = np.ones((n, n), dtype=bool)
    return Topology(a, kind="fully")


def ring(n: int, convergence_edges: int = 0, seed: int = 0) -> Topology:
    """Bidirectional ring, optionally with extra random chords.

    Mirrors topologymanager.py:213-228 (watts_strogatz(n, 2, 0) == a
    ring; plus random convergence edges).
    """
    a = np.zeros((n, n), dtype=bool)
    idx = np.arange(n)
    a[idx, (idx + 1) % n] = True
    a[(idx + 1) % n, idx] = True
    free = n * (n - 1) // 2 - int(np.triu(a, 1).sum())  # non-edges available
    if convergence_edges > free:
        raise ValueError(
            f"ring(n={n}) can take at most {free} extra edges, "
            f"asked for {convergence_edges}"
        )
    rng = np.random.default_rng(seed)
    added = 0
    while added < convergence_edges:
        i, j = rng.integers(0, n, size=2)
        if i != j and not a[i, j]:
            a[i, j] = a[j, i] = True
            added += 1
    return Topology(a, kind="ring")


def random_topology(
    n: int, prob: float = 0.5, symmetric: bool = True, seed: int = 0
) -> Topology:
    """Erdős–Rényi-style random graph, retried until connected
    (topologymanager.py:230-301 semantics: symmetric or directed)."""
    rng = np.random.default_rng(seed)
    for _ in range(1000):
        a = rng.random((n, n)) < prob
        np.fill_diagonal(a, False)
        if symmetric:
            a = np.triu(a, 1)
            a = a | a.T
        t = Topology(a, kind="random")
        if t.is_connected():
            return t
    raise RuntimeError(f"could not draw a connected random topology (n={n}, p={prob})")


def star(n: int, center: int = 0) -> Topology:
    """Hub-and-spoke for CFL; node ``center`` is the server
    (topologymanager.py:121-125)."""
    a = np.zeros((n, n), dtype=bool)
    a[center, :] = True
    a[:, center] = True
    a[center, center] = False
    return Topology(a, kind="star")


def generate_topology(kind: str, n: int, **kwargs) -> Topology:
    """Factory by name — mirrors the controller CLI's
    ``--topology {fully,ring,random,star}`` (app/main.py:11-40)."""
    kinds = {
        "fully": fully_connected,
        "ring": ring,
        "random": random_topology,
        "star": star,
    }
    key = kind.lower()
    if key not in kinds:
        raise ValueError(f"unknown topology {kind!r}; have {sorted(kinds)}")
    return kinds[key](n, **kwargs)


def metropolis_weights(adjacency: np.ndarray) -> np.ndarray:
    """Standalone helper: Metropolis-Hastings mixing weights."""
    return Topology(adjacency).mixing_matrix("metropolis")
