from p2pfl_tpu.topology.topology import (
    Topology,
    fully_connected,
    generate_topology,
    metropolis_weights,
    random_topology,
    ring,
    star,
)

__all__ = [
    "Topology",
    "fully_connected",
    "generate_topology",
    "metropolis_weights",
    "random_topology",
    "ring",
    "star",
]
