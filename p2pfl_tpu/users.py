"""User accounts for the dashboard — the reference's users table and
role model (webserver/database.py:54-120: user/password/role rows,
scrypt-hashed, admin vs user) as a single JSON file, stdlib-only.

Storage: ``users.json`` mapping username -> {salt, hash, role}, where
``hash`` is PBKDF2-HMAC-SHA256(password, salt, 200k iters). Writes are
atomic (tmp + replace) so a crashed CRUD call cannot truncate the
store, matching the framework's filesystem-as-database discipline.
"""

from __future__ import annotations

import contextlib
import hashlib
import hmac
import json
import os
import pathlib
import secrets

ROLES = ("admin", "user")
_ITERS = 200_000


def _hash(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, _ITERS)


class UserStore:
    """CRUD + verification over the on-disk user file.

    The file is re-read on every call: the webapp's management CLI and
    a running server may touch the same store, and user CRUD is far
    too rare to justify a cache with an invalidation story.
    """

    def __init__(self, path: str | pathlib.Path):
        self.path = pathlib.Path(path)

    @contextlib.contextmanager
    def _locked(self):
        """Advisory lock around read-modify-write: concurrent CRUD
        (ThreadingHTTPServer handlers, or the --add-user CLI beside a
        running server) must not lose updates to a last-writer-wins
        race. flock covers both threads and processes on this OS; if
        it is unavailable the RMW proceeds unlocked (rare-platform
        degradation, not a failure)."""
        self.path.parent.mkdir(parents=True, exist_ok=True)
        lock_path = self.path.with_suffix(".lock")
        # only the import is guarded: a try around the yield would
        # swallow an ImportError raised inside the locked BODY and
        # yield a second time ("generator didn't stop after throw")
        try:
            import fcntl
        except ImportError:
            yield
            return
        with open(lock_path, "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lk, fcntl.LOCK_UN)

    def _load(self) -> dict:
        if not self.path.is_file():
            return {}
        try:
            data = json.loads(self.path.read_text())
            return data if isinstance(data, dict) else {}
        except ValueError:
            return {}

    def _save(self, data: dict) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps(data, indent=1))
        os.replace(tmp, self.path)

    def add(self, user: str, password: str, role: str | None = None) -> None:
        """Create or update a user (the reference's add/update rows,
        database.py:88-112). ``role=None`` preserves an existing
        user's role on update (a password reset must not silently
        demote an admin) and defaults new users to "user"."""
        if role is not None and role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}")
        if not user or not password:
            raise ValueError("user and password must be non-empty")
        with self._locked():
            data = self._load()
            if role is None:
                role = data.get(user, {}).get("role", "user")
            salt = secrets.token_bytes(16)
            data[user] = {
                "salt": salt.hex(),
                "hash": _hash(password, salt).hex(),
                "role": role,
            }
            self._save(data)

    def remove(self, user: str) -> bool:
        with self._locked():
            data = self._load()
            if user not in data:
                return False
            del data[user]
            self._save(data)
            return True

    def verify(self, user: str, password: str) -> str | None:
        """Role on success, None on unknown user or bad password.
        Constant-time digest compare; unknown users still burn a hash
        so a timing probe cannot enumerate usernames."""
        data = self._load()
        rec = data.get(user)
        if rec is None:
            _hash(password, b"\x00" * 16)
            return None
        try:
            salt = bytes.fromhex(rec["salt"])
            want = bytes.fromhex(rec["hash"])
        except (KeyError, ValueError):
            return None
        if hmac.compare_digest(_hash(password, salt), want):
            return rec.get("role", "user")
        return None

    def list(self) -> dict[str, str]:
        """username -> role (no secrets leave the store)."""
        return {u: rec.get("role", "user")
                for u, rec in sorted(self._load().items())}
