"""Wire protocol: two-segment frames — msgpack header + raw payload.

Message-type parity with the reference's grammar
(communication_protocol.py:37-54): gossiped (hash-deduped) BEAT /
ROLE / START_LEARNING / STOP_LEARNING / VOTE_TRAIN_SET / METRICS and
direct CONNECT / STOP / PARAMS / MODELS_READY / MODELS_AGGREGATED /
MODEL_INITIALIZED / TRANSFER_LEADERSHIP — minus the parsing hazards:
no text tokenization, no fixed-size padding, no collapse/incomplete
reassembly (:497-530), because frames carry explicit lengths and the
PARAMS payload is the safe envelope from p2pfl_tpu.core.serialize.

Wire format v2 (round 7). v1 embedded the payload INSIDE the msgpack
frame (``"p": payload``) and then prepended the length — two full
copies of a tens-of-MB PARAMS blob per encode. v2 frames are::

    magic "P2W2" | >I header_len | msgpack header | payload bytes

The header carries the payload's length (``pl``) and content digest
(``ph``); the payload itself rides as a separate length-delimited
segment AFTER the header, so the send path can hand the original
``bytes`` object to ``StreamWriter.writelines`` untouched and the
receive path carves it with one ``readexactly`` straight into the
object handed to ``serialize.unpack``. At most ONE host-side copy of
the payload exists per hop (the socket read), and the SHA-256 the
origin signature covers is computed once per message lifetime
(cached), not once per encode — a relay re-frames without re-hashing.

Version skew is refused loudly in both directions: a v2 reader sees a
v1 frame's length prefix where the magic belongs and raises; a v1
reader interprets the v2 magic as a > MAX_FRAME length announcement
and raises. Neither side can silently misparse the other.

Gossip dedup keeps the reference's at-most-once contract
(:146-160, :451-461): every gossipable message carries a random
``msg_id``; receivers keep a bounded ring of seen ids.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import hashlib
import secrets
import struct
from collections import OrderedDict
from typing import Any

import msgpack

_LEN = struct.Struct(">I")
#: v2 preamble. First byte 0x50 ('P') makes a v1 reader's length field
#: read as ~1.3 GB > MAX_FRAME — v1 rejects v2 frames loudly too.
WIRE_MAGIC = b"P2W2"
WIRE_VERSION = 2
MAX_FRAME = 1 << 30  # 1 GiB — a payload is at most one model blob
MAX_HEADER = 1 << 24  # 16 MiB of control metadata is already absurd


class MsgType(enum.Enum):
    # gossiped control messages (flooded, deduped by msg_id)
    BEAT = "beat"
    ROLE = "role"
    START_LEARNING = "start_learning"
    STOP_LEARNING = "stop_learning"
    VOTE_TRAIN_SET = "vote_train_set"
    METRICS = "metrics"
    # these ride the gossip flood: on multi-hop overlays (and through
    # PROXY relays) every node needs the leadership token and every
    # node's round-progress state, not just direct peers' — the
    # reference gets the same effect from its full-mesh assumption
    TRANSFER_LEADERSHIP = "transfer_leadership"
    MODELS_READY = "models_ready"
    MODELS_AGGREGATED = "models_aggregated"
    MODEL_INITIALIZED = "model_initialized"
    # "node X left" must reach everyone, not just direct peers, or
    # multi-hop members stall at the round barrier until the timeout
    STOP = "stop"
    # secure-aggregation dropout recovery (privacy.secagg): a survivor
    # reveals its per-round pair seed against an evicted member so
    # every aggregator can reconstruct and subtract the dead pair's
    # mask streams at quorum close. Flooded: every aggregator needs
    # every survivor's share, relays included. Reveals nothing about
    # any surviving pair (Bonawitz reveal semantics).
    SECAGG_SHARE = "secagg_share"
    # direct messages
    CONNECT = "connect"
    PARAMS = "params"
    # round 11 live join: an established node answers a joiner's
    # CONNECT hello (body["jr"] — joining, knows round N) with the
    # current global model in CHECKPOINT format
    # (federation.checkpoint.pack_model), so the join path and the
    # restart-from-disk path share one serialization. Direct, never
    # relayed: the payload is a full model and the joiner asked one
    # specific peer.
    STATE_SYNC = "state_sync"


GOSSIPED = frozenset(
    {
        MsgType.BEAT,
        MsgType.ROLE,
        MsgType.START_LEARNING,
        MsgType.STOP_LEARNING,
        MsgType.VOTE_TRAIN_SET,
        MsgType.METRICS,
        MsgType.TRANSFER_LEADERSHIP,
        MsgType.MODELS_READY,
        MsgType.MODELS_AGGREGATED,
        MsgType.MODEL_INITIALIZED,
        MsgType.STOP,
        MsgType.SECAGG_SHARE,
    }
)

#: flooded types that are re-sent PERIODICALLY (heartbeats + the role
#: refresh riding every 2nd beat): a lost copy is replaced by the next
#: beat, so on a launcher-declared full mesh their epidemic re-relay
#: can be suppressed (node.py _dispatch) — the origin's direct
#: broadcast already reached every node. Everything else — including
#: per-ROUND progress frames (MODELS_READY/AGGREGATED/INITIALIZED),
#: which are one-shot within their round, not periodic — is ALWAYS
#: relayed: delivery must survive a single broken link that the
#: relaying node cannot observe locally.
PERIODIC_FLOODS = frozenset(
    {
        MsgType.BEAT,
        MsgType.ROLE,
    }
)


@dataclasses.dataclass
class Message:
    """One frame. ``sender`` is the originating node index; ``body`` is
    msgpack-able metadata; ``payload`` carries binary blobs (PARAMS)."""

    type: MsgType
    sender: int
    body: dict[str, Any] = dataclasses.field(default_factory=dict)
    payload: bytes = b""
    msg_id: str = ""
    # origin authentication (TLS federations only): ECDSA signature
    # over signing_bytes() + the originator's PEM certificate. Relays
    # forward both untouched so multi-hop receivers can verify the
    # ORIGIN, not the relaying connection (see p2p.tls).
    sig: bytes = b""
    cert: bytes = b""
    # causal trace context (round 18): (trace_id, parent_span_id,
    # send_wall_ns), stamped by the sender ONLY when its tracer is
    # enabled. None keeps the encoded header byte-identical to the
    # pre-tc format, so legacy peers and the untraced path are
    # unchanged; the key is outside signing_bytes() — observability
    # metadata, not authenticated content.
    tc: tuple | None = None
    # framed-header memo: a broadcast/relay writes the SAME message to
    # up to n-1 peers, and per-peer re-encoding was ~10% of the socket
    # federation's CPU (scripts/exp_socket_profile.py). Set on first
    # encode; _sign() (the only post-construction mutation on the send
    # path) invalidates it.
    _head: bytes | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # payload-digest memo: the SHA-256 the origin signature covers.
    # Computed at most once per message lifetime — the signer fills it,
    # the verifier recomputes it from the received bytes (never trusts
    # the header's copy), and every relay/re-encode reuses it.
    _payload_digest: bytes | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # total frame size as it crossed the wire (preamble + header +
    # payload), stamped by read_message — what the receive-side byte
    # counters (obs tracing, node.bytes_in) account, so rx and tx
    # totals are comparable without re-encoding
    _wire_bytes: int = dataclasses.field(
        default=0, repr=False, compare=False)
    # shm-slot backing (aggregation sidecar): when read_message's
    # slot_sink diverted the payload into a shared-memory slot,
    # ``payload`` is b"" and these name the leased slot + the payload
    # length that landed there. The receiver owns the lease.
    _slot: int | None = dataclasses.field(
        default=None, repr=False, compare=False)
    _slot_len: int = dataclasses.field(
        default=0, repr=False, compare=False)

    def __post_init__(self):
        if not self.msg_id and self.type in GOSSIPED:
            self.msg_id = secrets.token_hex(8)  # :536-548 hash analog

    def payload_digest(self) -> bytes:
        """SHA-256 of the payload, computed once and cached (empty for
        payload-less messages)."""
        if not self.payload:
            return b""
        if self._payload_digest is None:
            self._payload_digest = hashlib.sha256(self.payload).digest()
        return self._payload_digest

    def signing_bytes(self) -> bytes:
        """Canonical bytes the origin signature covers. msgpack of a
        dict is deterministic across pack→unpack→pack (insertion order
        is preserved), so signer and verifier derive identical bytes.
        The payload enters as a digest: PARAMS blobs are tens of MB and
        ECDSA hashes its input anyway. Verifiers must call this only
        with ``_payload_digest`` derived from the RECEIVED payload
        (decode never seeds it on signed messages)."""
        return msgpack.packb(
            {
                "t": self.type.value,
                "s": self.sender,
                "b": self.body,
                "ph": self.payload_digest(),
                "i": self.msg_id,
            },
            use_bin_type=True,
        )

    def wire_segments(self) -> list:
        """The frame as writev-ready segments: one small bytes object
        (magic + header length + msgpack header) plus, for non-empty
        payloads, a ``memoryview`` of the ORIGINAL payload object —
        the payload is never copied on the send path.

        The header's digest field carries the cached digest when one
        exists (signing computes it) — it is NOT computed here:
        plaintext federations never hash payloads at all (the
        serialize envelope's CRC32 covers integrity), and a measured
        ~0.7 s/round of the 24-node uncapped round was exactly this
        hash when it was unconditional."""
        if self._head is None:
            ph = self._payload_digest
            if ph is None and self.sig:
                ph = self.payload_digest()  # signed: digest is canonical
            head_obj = {
                "v": WIRE_VERSION,
                "t": self.type.value,
                "s": self.sender,
                "b": self.body,
                "i": self.msg_id,
                "g": self.sig,
                "c": self.cert,
                "pl": len(self.payload),
                "ph": ph or b"",
            }
            if self.tc is not None:
                # appended last so a tc-less message encodes to the
                # exact pre-tc byte sequence (pinned by test)
                head_obj["tc"] = list(self.tc)
            header = msgpack.packb(head_obj, use_bin_type=True)
            if len(header) > MAX_HEADER:
                raise ValueError(f"header too large: {len(header)} bytes")
            if len(self.payload) > MAX_FRAME:
                raise ValueError(
                    f"payload too large: {len(self.payload)} bytes")
            self._head = WIRE_MAGIC + _LEN.pack(len(header)) + header
        if not self.payload:
            return [self._head]
        return [self._head, memoryview(self.payload)]

    def encode(self) -> bytes:
        """The full frame as one bytes object. Test/diagnostic helper —
        the socket send path uses ``wire_segments()`` so the payload is
        not copied into a contiguous frame."""
        return b"".join(self.wire_segments())

    def wire_size(self) -> int:
        """Bytes this frame occupies on the wire. Free after a send
        (the header memo already exists); builds the memo otherwise."""
        if self._head is None:
            self.wire_segments()
        return len(self._head) + len(self.payload)

    @staticmethod
    def _from_header(obj: dict, payload: bytes) -> "Message":
        if obj.get("v") != WIRE_VERSION:
            raise ValueError(
                f"unsupported wire version {obj.get('v')!r} "
                f"(this node speaks v{WIRE_VERSION})"
            )
        tc = obj.get("tc")
        msg = Message(
            type=MsgType(obj["t"]),
            sender=int(obj["s"]),
            body=obj.get("b", {}),
            payload=payload,
            msg_id=obj.get("i", ""),
            sig=obj.get("g", b""),
            cert=obj.get("c", b""),
            # absent on legacy/untraced frames → None, parsed unchanged
            tc=tuple(tc) if tc else None,
        )
        # Seed the digest cache from the header ONLY for unsigned
        # messages (plaintext federations): it saves a relay hash and
        # there is no authenticity to protect. A SIGNED message's
        # digest must be recomputed from the received payload by the
        # verifier — trusting the header's copy would let a relay swap
        # the payload under a valid signature.
        ph = obj.get("ph", b"")
        if ph and payload and not msg.sig:
            msg._payload_digest = ph
        return msg

    @staticmethod
    def decode(frame: bytes) -> "Message":
        """Parse one full v2 frame (as produced by ``encode``)."""
        mv = memoryview(frame)
        if bytes(mv[: len(WIRE_MAGIC)]) != WIRE_MAGIC:
            raise ValueError(
                "unrecognized wire preamble (legacy v1 or foreign frame)"
            )
        off = len(WIRE_MAGIC)
        (hlen,) = _LEN.unpack_from(mv, off)
        off += _LEN.size
        if hlen > MAX_HEADER:
            raise ValueError(f"oversized header: {hlen}")
        obj = msgpack.unpackb(mv[off: off + hlen], raw=False)
        off += hlen
        pl = int(obj.get("pl", 0))
        if pl < 0 or pl > MAX_FRAME or off + pl > len(frame):
            raise ValueError(f"bad payload length: {pl}")
        payload = bytes(mv[off: off + pl]) if pl else b""
        return Message._from_header(obj, payload)


async def write_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    """Frame ``msg`` onto the stream. ``writelines`` hands the payload
    memoryview to the transport as-is — no contiguous-frame copy."""
    writer.writelines(msg.wire_segments())
    await writer.drain()


#: _read_into fallback chunk: bounds the transient copies the public
#: StreamReader API forces when the internal buffer is empty
_INTO_CHUNK = 1 << 18


async def _read_into(reader: asyncio.StreamReader, mv: memoryview,
                     n: int) -> None:
    """Land exactly ``n`` stream bytes into ``mv`` without ever
    materializing a contiguous n-byte object on the heap.

    asyncio's StreamReader has no public readinto, so this drains the
    reader's internal buffer by direct memcpy when it holds data
    (``_buffer`` is a documented-stable bytearray in CPython; gated by
    getattr so an exotic reader just takes the fallback), and falls
    back to bounded ``read()`` chunks — transient copies of at most
    _INTO_CHUNK bytes each, never the full payload — otherwise."""
    buf = getattr(reader, "_buffer", None)
    resume = getattr(reader, "_maybe_resume_transport", None)
    got = 0
    while got < n:
        if isinstance(buf, bytearray) and len(buf):
            take = min(len(buf), n - got)
            with memoryview(buf) as bmv:
                mv[got: got + take] = bmv[:take]
            del buf[:take]
            if resume is not None:
                resume()
            got += take
            continue
        # buffer empty/unavailable: wait for data (bounded chunk copy)
        chunk = await reader.read(min(n - got, _INTO_CHUNK))
        if not chunk:
            raise asyncio.IncompleteReadError(bytes(mv[:got]), n)
        mv[got: got + len(chunk)] = chunk
        got += len(chunk)


async def read_message(reader: asyncio.StreamReader,
                       slot_sink=None) -> Message:
    """Read one frame; raises IncompleteReadError on EOF and ValueError
    (loudly, never a misparse) on version skew or bogus lengths.

    ``slot_sink`` (aggregation sidecar) is consulted once the header is
    parsed, as ``slot_sink(header_dict, payload_len)``. Returning
    ``(slot, memoryview, release)`` diverts the payload bytes straight
    into that shared-memory view via ``_read_into`` — the returned
    Message then carries ``_slot``/``_slot_len`` and an EMPTY
    ``payload``; a failed read releases the lease before re-raising.
    Returning None keeps the normal heap-bytes path."""
    # one read for magic + header length: control frames dominate the
    # frame count (~400k per 24-node round pair), so awaits-per-frame
    # are a measured cost
    pre = await reader.readexactly(len(WIRE_MAGIC) + _LEN.size)
    if pre[: len(WIRE_MAGIC)] != WIRE_MAGIC:
        raise ValueError(
            f"unrecognized wire preamble {pre[:4]!r}: peer speaks a "
            f"different wire version (v1 frames are refused, not parsed)"
        )
    (hlen,) = _LEN.unpack_from(pre, len(WIRE_MAGIC))
    if hlen > MAX_HEADER:
        raise ValueError(f"peer announced oversized header: {hlen}")
    obj = msgpack.unpackb(await reader.readexactly(hlen), raw=False)
    pl = int(obj.get("pl", 0))
    if pl < 0 or pl > MAX_FRAME:
        raise ValueError(f"peer announced bad payload length: {pl}")
    if pl and slot_sink is not None:
        lease = slot_sink(obj, pl)
        if lease is not None:
            slot, dst, on_error = lease
            try:
                await _read_into(reader, dst, pl)
            except BaseException:
                on_error(slot)
                raise
            msg = Message._from_header(obj, b"")
            msg._slot = slot
            msg._slot_len = pl
            msg._wire_bytes = len(pre) + hlen + pl
            return msg
    # the ONE host-side copy of the payload on the receive path: the
    # socket read itself. The returned bytes object is handed to
    # serialize.unpack without further slicing.
    payload = await reader.readexactly(pl) if pl else b""
    msg = Message._from_header(obj, payload)
    msg._wire_bytes = len(pre) + hlen + pl
    return msg


class DedupRing:
    """Bounded set of seen gossip msg_ids (AMOUNT_LAST_MESSAGES_SAVED
    = 100 ring, communication_protocol.py:146-160)."""

    def __init__(self, capacity: int = 100):
        self.capacity = capacity
        self._seen: OrderedDict[str, None] = OrderedDict()

    def seen(self, msg_id: str) -> bool:
        """Peek: has this id been processed? (No registration — lets a
        receiver authenticate a frame BEFORE marking its id seen, so a
        forgery can never shadow the genuine message's id.)"""
        return not msg_id or msg_id in self._seen

    def check_and_add(self, msg_id: str) -> bool:
        """True if the id is new (message should be processed)."""
        if self.seen(msg_id):
            return False
        self._seen[msg_id] = None
        while len(self._seen) > self.capacity:
            self._seen.popitem(last=False)
        return True
