"""Wire protocol: length-prefixed msgpack frames.

Message-type parity with the reference's grammar
(communication_protocol.py:37-54): gossiped (hash-deduped) BEAT /
ROLE / START_LEARNING / STOP_LEARNING / VOTE_TRAIN_SET / METRICS and
direct CONNECT / STOP / PARAMS / MODELS_READY / MODELS_AGGREGATED /
MODEL_INITIALIZED / TRANSFER_LEADERSHIP — minus the parsing hazards:
no text tokenization, no fixed-size padding, no collapse/incomplete
reassembly (:497-530), because frames carry an explicit length and the
PARAMS payload is the safe envelope from p2pfl_tpu.core.serialize.

Gossip dedup keeps the reference's at-most-once contract
(:146-160, :451-461): every gossipable message carries a random
``msg_id``; receivers keep a bounded ring of seen ids.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import hashlib
import secrets
import struct
from collections import OrderedDict
from typing import Any

import msgpack

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30  # 1 GiB — a frame is at most one model payload


class MsgType(enum.Enum):
    # gossiped control messages (flooded, deduped by msg_id)
    BEAT = "beat"
    ROLE = "role"
    START_LEARNING = "start_learning"
    STOP_LEARNING = "stop_learning"
    VOTE_TRAIN_SET = "vote_train_set"
    METRICS = "metrics"
    # these ride the gossip flood: on multi-hop overlays (and through
    # PROXY relays) every node needs the leadership token and every
    # node's round-progress state, not just direct peers' — the
    # reference gets the same effect from its full-mesh assumption
    TRANSFER_LEADERSHIP = "transfer_leadership"
    MODELS_READY = "models_ready"
    MODELS_AGGREGATED = "models_aggregated"
    MODEL_INITIALIZED = "model_initialized"
    # "node X left" must reach everyone, not just direct peers, or
    # multi-hop members stall at the round barrier until the timeout
    STOP = "stop"
    # direct messages
    CONNECT = "connect"
    PARAMS = "params"


GOSSIPED = frozenset(
    {
        MsgType.BEAT,
        MsgType.ROLE,
        MsgType.START_LEARNING,
        MsgType.STOP_LEARNING,
        MsgType.VOTE_TRAIN_SET,
        MsgType.METRICS,
        MsgType.TRANSFER_LEADERSHIP,
        MsgType.MODELS_READY,
        MsgType.MODELS_AGGREGATED,
        MsgType.MODEL_INITIALIZED,
        MsgType.STOP,
    }
)

#: flooded types that are re-sent PERIODICALLY (heartbeats + the role
#: refresh riding every 2nd beat): a lost copy is replaced by the next
#: beat, so on a launcher-declared full mesh their epidemic re-relay
#: can be suppressed (node.py _dispatch) — the origin's direct
#: broadcast already reached every node. Everything else — including
#: per-ROUND progress frames (MODELS_READY/AGGREGATED/INITIALIZED),
#: which are one-shot within their round, not periodic — is ALWAYS
#: relayed: delivery must survive a single broken link that the
#: relaying node cannot observe locally.
PERIODIC_FLOODS = frozenset(
    {
        MsgType.BEAT,
        MsgType.ROLE,
    }
)


@dataclasses.dataclass
class Message:
    """One frame. ``sender`` is the originating node index; ``body`` is
    msgpack-able metadata; ``payload`` carries binary blobs (PARAMS)."""

    type: MsgType
    sender: int
    body: dict[str, Any] = dataclasses.field(default_factory=dict)
    payload: bytes = b""
    msg_id: str = ""
    # origin authentication (TLS federations only): ECDSA signature
    # over signing_bytes() + the originator's PEM certificate. Relays
    # forward both untouched so multi-hop receivers can verify the
    # ORIGIN, not the relaying connection (see p2p.tls).
    sig: bytes = b""
    cert: bytes = b""
    # framed-bytes memo: a broadcast/relay writes the SAME message to
    # up to n-1 peers, and per-peer re-encoding was ~10% of the socket
    # federation's CPU (scripts/exp_socket_profile.py). Set on first
    # encode; _sign() (the only post-construction mutation on the send
    # path) invalidates it.
    _wire: bytes | None = dataclasses.field(
        default=None, repr=False, compare=False)

    def __post_init__(self):
        if not self.msg_id and self.type in GOSSIPED:
            self.msg_id = secrets.token_hex(8)  # :536-548 hash analog

    def signing_bytes(self) -> bytes:
        """Canonical bytes the origin signature covers. msgpack of a
        dict is deterministic across pack→unpack→pack (insertion order
        is preserved), so signer and verifier derive identical bytes.
        The payload enters as a digest: PARAMS blobs are tens of MB and
        ECDSA hashes its input anyway."""
        return msgpack.packb(
            {
                "t": self.type.value,
                "s": self.sender,
                "b": self.body,
                "ph": hashlib.sha256(self.payload).digest()
                if self.payload else b"",
                "i": self.msg_id,
            },
            use_bin_type=True,
        )

    def encode(self) -> bytes:
        if self._wire is not None:
            return self._wire
        frame = msgpack.packb(
            {
                "t": self.type.value,
                "s": self.sender,
                "b": self.body,
                "p": self.payload,
                "i": self.msg_id,
                "g": self.sig,
                "c": self.cert,
            },
            use_bin_type=True,
        )
        if len(frame) > MAX_FRAME:
            raise ValueError(f"frame too large: {len(frame)} bytes")
        self._wire = _LEN.pack(len(frame)) + frame
        return self._wire

    @staticmethod
    def decode(frame: bytes) -> "Message":
        obj = msgpack.unpackb(frame, raw=False)
        return Message(
            type=MsgType(obj["t"]),
            sender=int(obj["s"]),
            body=obj.get("b", {}),
            payload=obj.get("p", b""),
            msg_id=obj.get("i", ""),
            sig=obj.get("g", b""),
            cert=obj.get("c", b""),
        )


async def write_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    writer.write(msg.encode())
    await writer.drain()


async def read_message(reader: asyncio.StreamReader) -> Message:
    """Read one frame; raises IncompleteReadError on EOF."""
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"peer announced oversized frame: {length}")
    frame = await reader.readexactly(length)
    return Message.decode(frame)


class DedupRing:
    """Bounded set of seen gossip msg_ids (AMOUNT_LAST_MESSAGES_SAVED
    = 100 ring, communication_protocol.py:146-160)."""

    def __init__(self, capacity: int = 100):
        self.capacity = capacity
        self._seen: OrderedDict[str, None] = OrderedDict()

    def seen(self, msg_id: str) -> bool:
        """Peek: has this id been processed? (No registration — lets a
        receiver authenticate a frame BEFORE marking its id seen, so a
        forgery can never shadow the genuine message's id.)"""
        return not msg_id or msg_id in self._seen

    def check_and_add(self, msg_id: str) -> bool:
        """True if the id is new (message should be processed)."""
        if self.seen(msg_id):
            return False
        self._seen[msg_id] = None
        while len(self._seen) > self.capacity:
            self._seen.popitem(last=False)
        return True
