"""Aggregation sidecar: payload decode + fuse in a separate process.

perf.md §7b/§7c left the uncapped 24-node socket federation floored by
payload movement — every ~800 KB PARAMS blob was received, decoded and
accumulated on the same asyncio loop that runs the node's control
plane. This module is the smart-NIC FL-server analog (PAPERS.md): one
``aggd`` process per host owns a ``multiprocessing.shared_memory``
arena of payload slots; the protocol reader lands raw payload bytes
straight into a leased slot (protocol.read_message's ``slot_sink``)
and the event loop forwards only a small descriptor. Decode and the
§7b numpy weighted-FedAvg accumulate happen in the sidecar; the fused
result comes back through one shared result slot per session.

Lifetime design (the part that makes /dev/shm leaks impossible):

- the CLIENT creates the arena under a recognizable ``p2pfl_aggd_*``
  name and the worker attaches by name;
- the moment the worker confirms attachment, the client **unlinks the
  name** while both sides keep their mappings. The kernel frees the
  memory when the last mapping closes — even if every process involved
  is SIGKILLed, nothing is left under /dev/shm;
- both sides still unlink defensively at exit (suppressed
  FileNotFoundError) for the window before the handshake lands.

Slot accounting lives entirely in the client (single event loop +
drain thread, one lock): the worker never allocates, it only reads the
slots a fuse request names and writes the result slot the client
leased for that request. A fuse whose reply never arrives (worker
killed) falls back to in-process aggregation — loud flight event, no
round lost.
"""

from __future__ import annotations

import asyncio
import dataclasses
import gc
import itertools
import multiprocessing
import os
import secrets
import threading
from contextlib import suppress
from multiprocessing import shared_memory
from typing import Any

import jax
import numpy as np

from p2pfl_tpu.obs import flight
from p2pfl_tpu.obs.trace import get_tracer

#: arena names carry this prefix so tests (and operators) can audit
#: /dev/shm for residue after crash/chaos runs
SHM_PREFIX = "p2pfl_aggd_"
#: slot-size floor — the arena is sized lazily from the first leased
#: payload, and a tiny first frame must not wedge later full models
_MIN_SLOT_BYTES = 1 << 16
#: worker-side queue poll period; each timeout re-checks for orphaning
_WORKER_POLL_S = 5.0


def fuse_numpy(trees, weights) -> tuple[Any, float]:
    """The §7b numpy weighted-FedAvg kernel, extracted from
    ``AggregationSession._aggregate_numpy`` (round 7) so the inline
    session and the sidecar worker share ONE implementation — the
    tolerance-0 parity gate between the two planes is anchored on this
    sharing, not on two copies staying in sync by discipline.

    Returns ``(fused_tree, total_weight)``.
    """
    weights = np.asarray(weights, np.float32)
    total = float(weights.sum())
    if total > 0:
        wn = weights / total
    else:  # tree_weighted_mean degenerate-case parity
        wn = np.full_like(weights, 1.0 / len(trees))
        total = float(len(trees))
    trees = [jax.tree.map(np.asarray, p) for p in trees]

    def leaf(*xs):
        acc = np.asarray(xs[0], np.float32) * wn[0]
        for wi, x in zip(wn[1:], xs[1:]):
            acc += np.asarray(x, np.float32) * wi
        return acc.astype(np.asarray(xs[0]).dtype)

    return jax.tree.map(leaf, *trees), total


@dataclasses.dataclass(frozen=True)
class SlotEntry:
    """Marker a SidecarSession stores in place of a decoded tree: the
    payload lives undecoded in the shared arena at ``slot``."""

    slot: int
    length: int


def _sidecar_main(shm_name: str, n_slots: int, slot_bytes: int,
                  desc_q, done_q) -> None:
    """Worker entry (spawn context — never forks live asyncio/JAX
    state). Attaches to the client's arena, confirms (which triggers
    the client's early unlink), then serves fuse requests until a stop
    sentinel, queue EOF, or orphaning (parent gone)."""
    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.core.serialize import decode_parameters, encode_parameters

    parent = os.getppid()
    try:
        shm = shared_memory.SharedMemory(name=shm_name)
    except FileNotFoundError:
        return  # client died before we attached; nothing to serve
    done_q.put(("attached",))

    def view(slot: int, length: int) -> memoryview:
        off = slot * slot_bytes
        return shm.buf[off: off + length]

    try:
        while True:
            try:
                item = desc_q.get(timeout=_WORKER_POLL_S)
            except Exception:  # Empty on timeout; EOF/OSError on close
                if os.getppid() != parent:
                    break  # orphaned: client is gone, exit
                continue
            if item is None or item[0] == "stop":
                break
            if item[0] != "fuse":
                continue
            _, req_id, entries, result_slot = item
            try:
                bytes_in = 0
                if len(entries) == 1 and entries[0][0] == "s":
                    # single-entry short-circuit mirrors _aggregate's
                    # n==1 return-as-is: the envelope IS the result
                    _, slot, length, _w = entries[0]
                    view(result_slot, length)[:] = view(slot, length)
                    done_q.put(("done", req_id, length,
                                {"entries": 1, "bytes_in": length}))
                    continue
                trees, weights = [], []
                for e in entries:
                    if e[0] == "s":
                        _, slot, length, w = e
                        blob: Any = view(slot, length)
                    else:
                        _, blob, w = e
                    bytes_in += len(blob)
                    trees.append(decode_parameters(blob).params)
                    weights.append(float(w))
                fused, total = fuse_numpy(trees, weights)
                out = encode_parameters(fused, (), max(1, int(total)))
                if len(out) > slot_bytes:
                    raise ValueError(
                        f"fused blob {len(out)} B > slot {slot_bytes} B")
                view(result_slot, len(out))[:] = out
                done_q.put(("done", req_id, len(out),
                            {"entries": len(entries), "bytes_in": bytes_in}))
            except Exception as e:  # reply, never die — the client
                # treats a missing reply as a crash and falls back
                done_q.put(("err", req_id, f"{type(e).__name__}: {e}"[:300]))
    finally:
        with suppress(BufferError):
            shm.close()
        with suppress(FileNotFoundError):
            shm.unlink()  # no-op normally: client unlinked on attach


class SidecarClient:
    """Per-host handle to one aggd worker + its shared-memory arena.

    One client serves every node packed into the host process; slots
    are leased/released on the event-loop thread and reclaimed from the
    done-queue drain thread, so all free-list state sits behind one
    lock. The arena is sized lazily from the first lease (2x the first
    payload, floored) — callers must treat a ``None`` lease as "stay on
    the inline path" (arena exhausted, payload oversized, or /dev/shm
    unavailable), never as an error.
    """

    def __init__(self, n_slots: int = 16, lane: str | None = None):
        self.n_slots = max(2, int(n_slots))
        self.slot_bytes = 0
        self._shm: shared_memory.SharedMemory | None = None
        self._proc = None
        self._desc_q = None
        self._done_q = None
        self._drain: threading.Thread | None = None
        self._lock = threading.Lock()
        self._free: list[int] = []
        self._leased: set[int] = set()
        # req_id -> (loop, event, reply box) for in-flight fuses, and
        # req_id -> result slot so an abandoned (timed-out) request's
        # slot is reclaimed only once the worker stops writing to it
        self._waiters: dict[int, tuple] = {}
        self._pending_result: dict[int, int] = {}
        self._req_ids = itertools.count(1)
        self._closed = False
        self._unlinked = False
        self._lane = lane
        self._tracer = get_tracer()
        #: payload bytes landed into leased slots (event-loop bypass)
        self.bytes_ingested = 0
        #: slots returned to the free list over the client's lifetime
        self.slot_releases = 0
        #: fuses answered by the worker / fallen back to in-process
        self.fused_rounds = 0
        self.fallbacks = 0

    # -- lifecycle ------------------------------------------------------
    def _ensure(self, nbytes: int) -> bool:
        if self._closed:
            return False
        if self._shm is not None:
            return True
        self.slot_bytes = max(_MIN_SLOT_BYTES, 2 * int(nbytes))
        name = SHM_PREFIX + secrets.token_hex(6)
        try:
            self._shm = shared_memory.SharedMemory(
                name=name, create=True,
                size=self.n_slots * self.slot_bytes)
        except OSError:
            self._closed = True  # no /dev/shm: permanent inline path
            flight.record("aggd.error", lane=self._lane,
                          error="shared memory unavailable")
            return False
        self._free = list(range(self.n_slots - 1, -1, -1))
        ctx = multiprocessing.get_context("spawn")
        self._desc_q = ctx.Queue()
        self._done_q = ctx.Queue()
        self._proc = ctx.Process(
            target=_sidecar_main,
            args=(name, self.n_slots, self.slot_bytes,
                  self._desc_q, self._done_q),
            daemon=True, name="p2pfl-aggd")
        self._proc.start()
        self._drain = threading.Thread(
            target=self._drain_loop, daemon=True, name="aggd-drain")
        self._drain.start()
        flight.record("aggd.spawn", lane=self._lane, pid=self._proc.pid,
                      n_slots=self.n_slots, slot_bytes=self.slot_bytes)
        return True

    def alive(self) -> bool:
        return (not self._closed and self._proc is not None
                and self._proc.is_alive())

    def queue_depth(self) -> int:
        """Outstanding descriptor-queue entries (health plane)."""
        if self._desc_q is None:
            return 0
        with suppress(NotImplementedError, OSError):
            return int(self._desc_q.qsize())
        return 0

    def close(self) -> None:
        """Stop the worker, reap the drain thread, drop the mapping.
        Idempotent; safe even if the worker was already killed. The
        arena name was unlinked at attach time, so this only closes
        our mapping — the kernel frees the memory with the last map."""
        self._closed = True
        if self._desc_q is not None:
            with suppress(Exception):
                self._desc_q.put(("stop",))
        if self._proc is not None:
            self._proc.join(timeout=3.0)
            if self._proc.is_alive():
                self._proc.terminate()
                self._proc.join(timeout=3.0)
        if self._done_q is not None:
            with suppress(Exception):
                self._done_q.put(None)  # wake + retire the drain thread
        if self._drain is not None:
            self._drain.join(timeout=3.0)
            self._drain = None
        if self._shm is not None:
            self._unlink()
            # dangling slot views (exported memoryview slices a caller
            # dropped without releasing) keep the mmap pinned; collect
            # them now so neither close() nor the eventual __del__
            # trips BufferError on exported pointers
            gc.collect()
            with suppress(BufferError):
                self._shm.close()
            self._shm = None
        flight.record("aggd.close", lane=self._lane,
                      fused_rounds=self.fused_rounds,
                      fallbacks=self.fallbacks,
                      bytes_ingested=self.bytes_ingested)

    def _unlink(self) -> None:
        if not self._unlinked and self._shm is not None:
            self._unlinked = True
            with suppress(FileNotFoundError):
                self._shm.unlink()

    # -- slots ----------------------------------------------------------
    def lease(self, nbytes: int):
        """Lease one slot for an ``nbytes`` payload. Returns
        ``(slot, memoryview)`` sized to the payload, or None when the
        caller must stay inline (no arena, exhausted, or oversized)."""
        if nbytes <= 0 or not self._ensure(nbytes):
            return None
        if nbytes > self.slot_bytes:
            return None
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._leased.add(slot)
        self.bytes_ingested += int(nbytes)
        if self._tracer.enabled:
            self._tracer.count("aggd_bytes_ingested", int(nbytes))
        return slot, self.view(slot, nbytes)

    def view(self, slot: int, length: int) -> memoryview:
        off = slot * self.slot_bytes
        return self._shm.buf[off: off + length]

    def release(self, slot: int) -> None:
        """Return a slot to the free list. No-op for slots not
        currently leased, so teardown paths can release defensively."""
        with self._lock:
            if slot not in self._leased:
                return
            self._leased.discard(slot)
            self._free.append(slot)
        self.slot_releases += 1

    # -- fuse -----------------------------------------------------------
    async def fuse(self, entries, timeout_s: float = 60.0):
        """Ship one fuse request: ``entries`` is a list of
        ``("s", slot, length, weight)`` / ``("b", blob, weight)``
        tuples (weights are the session's EFFECTIVE weights — staleness
        and reputation already folded in). Returns
        ``(result_slot, length, stats)`` — the caller decodes the
        result slot and releases it — or None, meaning fall back to
        in-process aggregation (worker dead/stalled/errored)."""
        if self._closed or self._shm is None or not self.alive():
            return None
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._leased.add(slot)
        req_id = next(self._req_ids)
        loop = asyncio.get_running_loop()
        ev = asyncio.Event()
        box: list = []
        with self._lock:
            self._waiters[req_id] = (loop, ev, box)
            self._pending_result[req_id] = slot
        try:
            self._desc_q.put(("fuse", req_id, list(entries), slot))
        except Exception:
            with self._lock:
                self._waiters.pop(req_id, None)
                self._pending_result.pop(req_id, None)
            self.release(slot)
            return None
        deadline = loop.time() + max(1.0, float(timeout_s))
        while True:
            with suppress(asyncio.TimeoutError):
                await asyncio.wait_for(ev.wait(), timeout=0.25)
            if ev.is_set():
                break
            if loop.time() > deadline or not self.alive():
                worker_dead = not self.alive()
                with self._lock:
                    self._waiters.pop(req_id, None)
                    if worker_dead:
                        # nobody will ever write the result slot again
                        self._pending_result.pop(req_id, None)
                    # else: leave it pending — the drain thread
                    # reclaims the slot when the late reply lands
                if worker_dead:
                    self.release(slot)
                return None
        with self._lock:
            self._pending_result.pop(req_id, None)
        item = box[0]
        if item[0] == "err":
            flight.record("aggd.error", lane=self._lane, error=item[2])
            self.release(slot)
            return None
        _, _, length, stats = item
        self.fused_rounds += 1
        return slot, int(length), stats

    def _drain_loop(self) -> None:
        """Done-queue pump (plain thread, not a task: the reply arrives
        from another process and must not depend on loop liveness).
        Resolves fuse waiters via call_soon_threadsafe."""
        while True:
            try:
                item = self._done_q.get()
            except Exception:
                break
            if item is None:
                break
            if item[0] == "attached":
                # both mappings exist from here on: unlink the name so
                # /dev/shm is clean even under SIGKILL
                self._unlink()
                continue
            req_id = item[1]
            with self._lock:
                waiter = self._waiters.pop(req_id, None)
                abandoned = (self._pending_result.pop(req_id, None)
                             if waiter is None else None)
            if waiter is None:
                # timed-out request: the worker is done writing, so its
                # result slot is finally safe to reuse
                if abandoned is not None:
                    self.release(abandoned)
                continue
            loop, ev, box = waiter
            box.append(item)
            with suppress(RuntimeError):  # loop closed at teardown
                loop.call_soon_threadsafe(ev.set)
