"""Multi-process federation launcher — the "real mode" controller.

Parity with the reference's deployment path (controller.py:456-485
start_nodes_cmd: one OS process per participant reading its stamped
JSON; node_start.py:28-120 per-process entry), minus the fixed 30 s +
5 s/neighbor sleeps: nodes retry-connect until their neighbors' ports
listen.

Usage (also what ``python -m p2pfl_tpu.p2p.launch scenario.json``
does): the parent stamps per-node JSON configs with assigned ports,
spawns N ``node_main`` processes, waits, and aggregates their result
lines. Each process trains with the same JaxLearner; on a multi-host
deployment you run ``node_main`` yourself on each host with the same
scenario file and per-host node indices.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import dataclasses
import json
import math
import pathlib
import socket
import subprocess
import sys
import time

from p2pfl_tpu.config.schema import ScenarioConfig
from p2pfl_tpu.core.aggregators import get_aggregator
from p2pfl_tpu.p2p.aggd import SidecarClient
from p2pfl_tpu.datasets import FederatedDataset
from p2pfl_tpu.learning import JaxLearner
from p2pfl_tpu.models.base import build_model
from p2pfl_tpu.obs import flight
from p2pfl_tpu.obs import trace as obs_trace
from p2pfl_tpu.p2p.node import P2PNode
from p2pfl_tpu.topology.topology import generate_topology


def _trace_setup(cfg: ScenarioConfig) -> obs_trace.Tracer:
    """Per-process obs wiring: the recompile listener plus the tracer,
    enabled by P2PFL_TRACE and exporting into ``<log_dir>/<name>/trace``
    — the same directory convention as the status dir, so traceview
    finds every process of a federation under one root."""
    obs_trace.install_xla_listener()
    if cfg.log_dir:
        # flight postmortems land next to the status/trace dirs; the
        # recorder itself is always on (P2PFL_FLIGHT=0 to disable)
        flight.configure(
            dump_dir=pathlib.Path(cfg.log_dir) / cfg.name / "flight"
        )
    return obs_trace.configure_from_env(
        default_dir=(pathlib.Path(cfg.log_dir) / cfg.name / "trace")
        if cfg.log_dir else None,
    )


def _adversary_setup(cfg: ScenarioConfig):
    """(malicious mask, AttackSpec | None, reputation on?) — derived
    from config alone so every process of a multi-process federation
    (and the SPMD Scenario) computes the SAME cohort and transforms."""
    adv = cfg.adversary
    if not (adv.active or adv.reputation):
        return None, None, False
    import numpy as np

    from p2pfl_tpu.adversary import AttackSpec, malicious_indices

    mask = (
        malicious_indices(cfg.n_nodes, adv.fraction, adv.seed,
                          tuple(adv.nodes))
        if adv.active else np.zeros(cfg.n_nodes, bool)
    )
    spec = (
        AttackSpec(kind=adv.kind, scale=adv.scale, seed=adv.seed)
        if adv.active else None
    )
    return mask, spec, adv.reputation


def _poison_shard(data: FederatedDataset, idx: int) -> None:
    """Label-flip data poisoning for one node's TRAIN shard (the
    stacked SPMD path flips the same rows — Scenario.__init__)."""
    from p2pfl_tpu.adversary import flip_labels

    nd = data.nodes[idx]
    data.nodes[idx] = dataclasses.replace(
        nd, y=flip_labels(nd.y, data.num_classes)
    )


def _node_adversary_kwargs(cfg: ScenarioConfig, idx: int, data, setup):
    """Per-node P2PNode attack/reputation kwargs (+ shard poisoning as
    a side effect on ``data``) from one _adversary_setup tuple."""
    mask, spec, want_rep = setup
    if mask is None:
        return {}
    if spec is not None and spec.kind == "labelflip" and mask[idx]:
        _poison_shard(data, idx)
    out = {"attack": spec if (spec is not None and mask[idx]) else None}
    if want_rep:
        from p2pfl_tpu.adversary import ReputationMonitor

        # one monitor PER NODE: trust is each node's local view in a
        # decentralized deployment — no shared state between processes
        out["reputation"] = ReputationMonitor(
            cfg.n_nodes, alpha=cfg.adversary.reputation_alpha,
            cutoff=cfg.adversary.reputation_cutoff,
        )
    return out


def _node_privacy_kwargs(cfg: ScenarioConfig, idx: int,
                         tls_dir: str | None = None) -> dict:
    """Per-node P2PNode dp/masker kwargs — derived from config alone
    (like _node_adversary_kwargs) so every process of a multi-process
    federation privatizes with the SAME noise streams and derives the
    SAME pair secrets. With a TLS dir (and the optional ``cryptography``
    package) secagg pair secrets come from P-256 ECDH over the scenario
    certs; otherwise the seeded fallback (see privacy.secagg's threat
    model)."""
    priv = cfg.privacy
    out: dict = {}
    if priv.dp:
        from p2pfl_tpu.privacy.dp import DPSpec

        out["dp"] = DPSpec(clip_norm=priv.clip_norm,
                           noise_multiplier=priv.noise_multiplier,
                           seed=cfg.seed)
    if priv.secagg:
        from p2pfl_tpu.privacy.secagg import PairwiseMasker

        out["masker"] = PairwiseMasker(
            idx, root_seed=cfg.seed, bits=priv.secagg_bits,
            pair_secrets=_tls_pair_secrets(tls_dir, idx, cfg.n_nodes),
        )
    return out


def _tls_pair_secrets(tls_dir: str | None, idx: int,
                      n: int) -> dict[int, bytes] | None:
    """ECDH pair secrets off the scenario TLS identity layer, or None
    (→ seeded fallback) when there is no TLS dir or no ``cryptography``
    package in this interpreter."""
    if not tls_dir:
        return None
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import serialization
    except ImportError:
        return None
    from p2pfl_tpu.privacy.secagg import pair_secrets_from_tls

    d = pathlib.Path(tls_dir)
    key_path = d / f"node{idx}.key"
    if not key_path.exists():
        return None
    private_key = serialization.load_pem_private_key(
        key_path.read_bytes(), password=None
    )
    peer_certs = {}
    for j in range(n):
        cert_path = d / f"node{j}.crt"
        if j != idx and cert_path.exists():
            peer_certs[j] = x509.load_pem_x509_certificate(
                cert_path.read_bytes()
            )
    return pair_secrets_from_tls(idx, private_key, peer_certs)


def _privacy_status(cfg: ScenarioConfig, round_num: int) -> dict:
    """DP spend gauges for a status record: the accountant's ε is a
    pure function of (config, rounds completed), so every process —
    and the monitor/health plane reading the records — sees the same
    number with no cross-process state."""
    priv = cfg.privacy
    if not priv.dp:
        return {}
    from p2pfl_tpu.privacy.dp import epsilon_at

    eps = epsilon_at(priv.noise_multiplier, int(round_num), priv.delta)
    return {
        "dp_epsilon": round(eps, 4) if math.isfinite(eps) else eps,
        "dp_epsilon_budget": priv.epsilon_budget,
    }


def _declares_full_mesh(cfg) -> bool:
    """True when the launcher can PROMISE every pair of nodes a healthy
    direct link: fully-connected topology with no link shaping at all.
    Any shaping (loss, delay, jitter, or a rate cap that can convoy
    beats behind multi-MB PARAMS frames) disqualifies — relay damping
    must not remove the repair path on links the shaper degrades.
    A scheduled partition plan disqualifies for the same reason: while
    a cut is open the "full mesh" promise is false by design."""
    net = cfg.network
    return cfg.topology == "fully" and not (
        net.loss_pct or net.delay_ms or net.jitter_ms or net.rate_mbps
        or getattr(net, "partitions", None)
    )


def _aggd_status(client: SidecarClient | None) -> dict:
    """Sidecar gauges for a status record: descriptor-queue depth vs
    slot releases is the pair the sidecar-stalled health rule compares,
    bytes_ingested is the live zero-copy-ingest odometer."""
    if client is None:
        return {}
    return {
        "aggd_desc_q_depth": client.queue_depth(),
        "aggd_slot_releases": client.slot_releases,
        "aggd_bytes_ingested": client.bytes_ingested,
    }


def _critpath_status(node) -> dict:
    """Flatten the node's last per-round critical-path snapshot into
    ``critpath_*`` status gauges — the monitor's WAIT% column and the
    webapp's breakdown pane read these. Empty before round 1 closes."""
    cp = node.critpath_last
    if not cp:
        return {}
    return {
        "critpath_round": cp["round"],
        "critpath_round_s": cp["round_s"],
        "critpath_fit_s": cp["fit_s"],
        "critpath_wire_s": cp["wire_s"],
        "critpath_wait_s": cp["wait_s"],
        "critpath_agg_s": cp["agg_s"],
        "critpath_other_s": cp["other_s"],
    }


def _crossdev_status(obj) -> dict:
    """Cross-device throughput gauges (round 20) for a status record:
    ``crossdev_clients_per_s`` plus, on streamed rounds, the prefetch
    bytes/stall pair. Reads the driver's ``crossdev_last`` dict
    (CrossDeviceScenario refreshes it per round); empty — and therefore
    rendered as "-" by the monitor — for anything that is not a
    cross-device driver."""
    last = getattr(obj, "crossdev_last", None)
    if not last:
        return {}
    return dict(last)


def _devprof_status(obj) -> dict:
    """Device-profiling gauges (MFU / achieved-TFLOPs / HBM+RSS
    watermarks) for a status record. Reads ``devprof_last`` off the
    learner (socket plane: the JaxLearner refreshes it per fit when
    ``P2PFL_DEVPROF`` is on) — accepts either the learner itself or a
    Node wrapping one. Empty — rendered "-" — when devprof is off."""
    last = getattr(obj, "devprof_last", None)
    if last is None:
        last = getattr(getattr(obj, "learner", None), "devprof_last", None)
    if not last:
        return {}
    return dict(last)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


async def _run_node(cfg: ScenarioConfig, idx: int, ports: list[int],
                    tls_dir: str | None = None,
                    hosts: list[str] | None = None,
                    bind: str = "127.0.0.1",
                    resume: bool = False,
                    sidecar: SidecarClient | None = None) -> dict:
    """One node's full lifecycle (node_start.py main analog).

    ``hosts`` gives each node's reachable address (container service
    names in a compose deployment; defaults to loopback for localhost
    federations); ``bind`` is this node's listen address ("0.0.0.0"
    inside containers so peers can reach it). ``resume=True`` is the
    supervisor's restart path: the node adopts its own periodic
    checkpoint and re-enters through the live-join handshake.
    """
    n = cfg.n_nodes
    hosts = hosts or ["127.0.0.1"] * n
    tls = None
    if tls_dir:
        from p2pfl_tpu.p2p.tls import load_node_credentials

        tls = load_node_credentials(tls_dir, idx)
    data = FederatedDataset.make(cfg.data, n)  # deterministic: same shards
    adv_kwargs = _node_adversary_kwargs(cfg, idx, data,
                                        _adversary_setup(cfg))
    priv_kwargs = _node_privacy_kwargs(cfg, idx, tls_dir=tls_dir)
    from p2pfl_tpu.learning.lora import maybe_wrap_lora

    learner = JaxLearner(
        model=maybe_wrap_lora(build_model(cfg.model), cfg,
                              data.nodes[idx].x[:1]),
        data=data.nodes[idx],
        objective=cfg.model.objective,
        optimizer=cfg.training.optimizer,
        learning_rate=cfg.training.learning_rate,
        momentum=cfg.training.momentum,
        weight_decay=cfg.training.weight_decay,
        momentum_dtype=cfg.training.momentum_dtype,
        batch_size=cfg.data.batch_size,
        seed=cfg.seed,
    )
    node = P2PNode(
        idx,
        learner,
        host=bind,
        port=ports[idx],
        role=cfg.nodes[idx].role,
        n_nodes=n,
        aggregator=get_aggregator(cfg.aggregator, **cfg.aggregator_kwargs),
        protocol=cfg.protocol,
        federation=cfg.federation,
        seed=cfg.seed,
        tls=tls,
        netem=cfg.network,
        full_mesh=_declares_full_mesh(cfg),
        wire_dtype=cfg.wire_dtype,
        elastic=cfg.elastic,
        fit_slowdown=cfg.nodes[idx].fit_slowdown,
        local_epochs=cfg.nodes[idx].epochs,
        checkpoint_dir=cfg.checkpoint_dir,
        checkpoint_every=cfg.checkpoint_every,
        resume=resume,
        joiner=resume,
        sidecar=sidecar,
        **adv_kwargs,
        **priv_kwargs,
    )
    await node.start()
    topo = generate_topology(cfg.topology, n, **cfg.topology_kwargs)
    # connect to higher-index neighbors; lower-index ones dial us.
    # retry until the peer's listener is up (replaces node_start.py:106's
    # fixed 30 s grace sleep)
    for j in topo.neighbors(idx):
        if j < idx:
            continue
        deadline = time.monotonic() + 60
        while True:
            try:
                await node.connect_to(hosts[j], ports[j])
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.1)
    # wait until every neighbor connection exists (either direction)
    want = set(topo.neighbors(idx))
    deadline = time.monotonic() + 60
    while not want <= set(node.peers) and time.monotonic() < deadline:
        await asyncio.sleep(0.1)
    status_task = None
    if cfg.log_dir:
        from p2pfl_tpu.utils.monitor import publish_status

        status_dir = pathlib.Path(cfg.log_dir) / cfg.name / "status"

        async def _publish_loop():
            # the reference's REPORT_STATUS_TO_CONTROLLER heartbeat
            # cycle (node.py:916-937, heartbeater.py:75-78)
            while True:
                publish_status(
                    status_dir, idx,
                    {"role": node.role, "round": node.round,
                     "peers": len(node.peers),
                     "leader": node.leader,
                     "round_p95_s": node.round_p95_s(),
                     "bytes_in": node.bytes_in,
                     "bytes_out": node.bytes_out,
                     # per-LINK wire totals: the partition-suspected
                     # health rule keys on cross-cohort counters going
                     # one-sided (json turns the int keys into strings)
                     "peer_bytes_in": dict(node.peer_bytes_in),
                     "peer_bytes_out": dict(node.peer_bytes_out),
                     "recompiles": obs_trace.xla_recompiles(),
                     **_privacy_status(cfg, node.round),
                     **_critpath_status(node),
                     **_crossdev_status(learner),
                     **_devprof_status(learner),
                     **_aggd_status(sidecar)},
                )
                await asyncio.sleep(cfg.protocol.heartbeat_period_s)

        status_task = asyncio.get_event_loop().create_task(_publish_loop())
    # warm the compiled programs off-loop BEFORE the round clock can
    # start (run_simulation warms every node the same way): the first
    # fit would otherwise bill its XLA compile to round 1 and skew
    # learn_wall_s, the number the multi-process bench reports
    await asyncio.get_running_loop().run_in_executor(None, learner.warm_up)
    if cfg.nodes[idx].start and not resume:
        # a resumed relaunch never re-starts the federation: it joins
        # the running one through the "jr" hello → STATE_SYNC handshake
        learner.init()
        node.set_start_learning(cfg.training.rounds,
                                cfg.training.epochs_per_round)
    await asyncio.wait_for(node.finished.wait(), timeout=600)
    # the learning loop already evaluated and recorded its own metrics
    # (the METRICS flood) — don't evaluate twice
    own = node.peer_metrics.get(idx)
    metrics = (
        {k: v for k, v in own.items() if k != "round"}
        if own is not None else learner.evaluate()
    )
    if status_task is not None:
        status_task.cancel()
        publish_status(
            status_dir, idx,
            {"role": node.role, "round": node.round,
             "peers": len(node.peers), "leader": node.leader,
             "round_p95_s": node.round_p95_s(),
             "bytes_in": node.bytes_in,
             "bytes_out": node.bytes_out, **metrics},
        )
    await node.stop()
    result = {"node": idx, "round": node.round,
              "round_p95_s": node.round_p95_s(),
              "bytes_in": node.bytes_in, "bytes_out": node.bytes_out,
              "params_bytes_out": node.params_bytes_out,
              **metrics}
    # round-loop wall clock (post-warm-up, excludes startup/diffusion):
    # what socket_round_s_24node_multiproc is computed from
    if node.learn_t0 is not None and node.learn_t1 is not None:
        result["learn_wall_s"] = round(node.learn_t1 - node.learn_t0, 3)
    return result


def node_main(config_path: str, idx: int | list[int], ports: list[int],
              tls_dir: str | None = None,
              hosts: list[str] | None = None,
              bind: str = "127.0.0.1",
              resume: bool = False) -> None:
    """Child-process entry. ``idx`` may be a LIST of node indices: all
    of them share this process's event loop (the k-nodes-per-process
    layouts the multi-process bench measures, e.g. 6 processes × 4
    nodes) — in-between the two extremes of run_simulation (n×1-loop)
    and one-process-per-node."""
    idxs = [idx] if isinstance(idx, int) else list(idx)
    cfg = ScenarioConfig.load(config_path)
    tracer = _trace_setup(cfg)
    if cfg.log_dir:
        # per-participant log trail + environment banner
        # (base_node.py:133-158, utils/env.py parity)
        from p2pfl_tpu.utils.env import log_environment
        from p2pfl_tpu.utils.nodelog import setup_node_logging

        setup_node_logging(cfg.log_dir, cfg.name, idxs[0])
        log_environment()

    # one sidecar per OS process: every node sharing this event loop
    # lands payloads into the same shared-memory arena (the per-HOST
    # deployment shape — each host runs its own aggd). Sizing: each of
    # this process's sessions holds up to n_nodes payload slots for the
    # whole round (full mesh, entries pinned until the fuse) plus a
    # result slot; +8 margin for in-flight reads
    sidecar = None
    if cfg.aggregation_plane == "sidecar":
        sidecar = SidecarClient(
            n_slots=len(idxs) * (cfg.n_nodes + 2) + 8)

    async def _run_all() -> list[dict]:
        return list(
            await asyncio.gather(
                *(_run_node(cfg, i, ports, tls_dir=tls_dir,
                            hosts=hosts, bind=bind, resume=resume,
                            sidecar=sidecar)
                  for i in idxs)
            )
        )

    try:
        results = asyncio.run(_run_all())
    except Exception as e:
        # an unhandled child-process exception is exactly the moment
        # the control-event ring matters: dump before dying so the
        # parent finds a postmortem next to the (absent) result line
        flight.record("proc.exception", nodes=idxs, error=repr(e))
        flight.dump(f"proc{idxs[0]}.exception")
        raise
    finally:
        if sidecar is not None:
            sidecar.close()
    if tracer.enabled:
        # one file per OS process; nodes sharing this event loop are
        # separated by lane inside it (traceview merges across files)
        tracer.export(
            process_name="nodes " + ",".join(map(str, idxs))
        )
    for result in results:
        print("P2PFL_RESULT " + json.dumps(result), flush=True)


async def _simulate(cfg: ScenarioConfig, timeout: float = 600) -> dict:
    n = cfg.n_nodes
    tracer = _trace_setup(cfg)
    data = FederatedDataset.make(cfg.data, n)
    topo = generate_topology(cfg.topology, n, **cfg.topology_kwargs)
    from p2pfl_tpu.learning.learner import SharedTrainer
    from p2pfl_tpu.learning.lora import maybe_wrap_lora

    shared = SharedTrainer(
        maybe_wrap_lora(build_model(cfg.model), cfg, data.nodes[0].x[:1]),
        objective=cfg.model.objective,
        optimizer=cfg.training.optimizer,
        learning_rate=cfg.training.learning_rate,
        momentum=cfg.training.momentum,
        weight_decay=cfg.training.weight_decay,
        momentum_dtype=cfg.training.momentum_dtype,
        batch_size=cfg.data.batch_size,
    )
    adv_setup = _adversary_setup(cfg)
    # shard poisoning mutates data.nodes — run BEFORE learners capture
    adv_kwargs = [
        _node_adversary_kwargs(cfg, i, data, adv_setup) for i in range(n)
    ]
    # in-process simulation has no TLS layer: secagg maskers run in
    # seeded-fallback pair-secret mode (privacy.secagg threat model)
    priv_kwargs = [_node_privacy_kwargs(cfg, i) for i in range(n)]
    # one shared sidecar for the whole in-process federation (simulation
    # mode models ONE host). Sizing: every session can hold up to n
    # payload slots for the whole round (full mesh, entries pinned
    # until the fuse) plus its result slot; +8 margin for in-flight
    # reads — exhaustion degrades to blob entries, never to wrong math
    sidecar = None
    if cfg.aggregation_plane == "sidecar":
        sidecar = SidecarClient(n_slots=n * (n + 2) + 8)
    nodes = [
        P2PNode(
            i,
            JaxLearner(model=None, data=data.nodes[i],
                       batch_size=cfg.data.batch_size, seed=cfg.seed,
                       trainer=shared),
            role=cfg.nodes[i].role,
            n_nodes=n,
            aggregator=get_aggregator(cfg.aggregator, **cfg.aggregator_kwargs),
            protocol=cfg.protocol,
            federation=cfg.federation,
            seed=cfg.seed,
            netem=cfg.network,
            full_mesh=_declares_full_mesh(cfg),
            wire_dtype=cfg.wire_dtype,
            elastic=cfg.elastic,
            fit_slowdown=cfg.nodes[i].fit_slowdown,
            local_epochs=cfg.nodes[i].epochs,
            checkpoint_dir=cfg.checkpoint_dir,
            checkpoint_every=cfg.checkpoint_every,
            sidecar=sidecar,
            **adv_kwargs[i],
            **priv_kwargs[i],
        )
        for i in range(n)
    ]
    for node in nodes:
        await node.start()
    for i in range(n):
        for j in topo.neighbors(i):
            if j > i:
                await nodes[i].connect_to(nodes[j].host, nodes[j].port)
    starter = next(
        (i for i, nc in enumerate(cfg.nodes) if nc.start), 0
    )
    nodes[starter].learner.init()
    # warm EVERY node's compiled programs before the clock starts
    # (ragged dirichlet shards mean distinct shapes per node; the jit
    # cache dedups identical ones, so iid costs one compile): the
    # first fit/evaluate would otherwise bill their compiles to round
    # 1 and skew the steady-state round time being measured
    for node in nodes:
        node.learner.warm_up()
    # steady-state recompile accounting starts HERE: warm-up compiles
    # are expected; anything counted past this point is a mid-round
    # recompile (the round-7 storm this counter exists to surface)
    obs_trace.reset_xla_counters()

    # ---- scripted churn (round 11): on the socket plane FaultEvents
    # drive ACTUAL node death and live re-join — a "crash" is an
    # abrupt teardown peers must detect via heartbeat silence and the
    # probe machine, a "join"/"recover" builds a FRESH P2PNode that
    # re-enters through the live-join handshake ("jr" hello →
    # STATE_SYNC model fetch) instead of a scripted beating flag.
    el = cfg.elastic
    joined: list[int] = []
    restarted: list[int] = []

    async def _rejoin_node(i: int, resume: bool = False) -> None:
        ln = JaxLearner(model=None, data=data.nodes[i],
                        batch_size=cfg.data.batch_size, seed=cfg.seed,
                        trainer=shared)
        nd = P2PNode(
            i, ln, role=cfg.nodes[i].role, n_nodes=n,
            aggregator=get_aggregator(cfg.aggregator,
                                      **cfg.aggregator_kwargs),
            protocol=cfg.protocol, federation=cfg.federation,
            seed=cfg.seed, netem=cfg.network,
            full_mesh=_declares_full_mesh(cfg),
            wire_dtype=cfg.wire_dtype, elastic=el,
            fit_slowdown=cfg.nodes[i].fit_slowdown,
            local_epochs=cfg.nodes[i].epochs,
            joiner=True,
            checkpoint_dir=cfg.checkpoint_dir,
            checkpoint_every=cfg.checkpoint_every,
            resume=resume,
            sidecar=sidecar,
            **adv_kwargs[i],
            # fresh masker, same derived secrets: pair streams are a
            # pure function of (seed, pair, round), so a rejoiner
            # re-derives exactly what the fleet expects of it
            **_node_privacy_kwargs(cfg, i),
        )
        nodes[i] = nd
        await nd.start()
        ln.warm_up()  # shared trainer is already compiled — cheap
        for j in topo.neighbors(i):
            other = nodes[j]
            if other is nd or other.finished.is_set():
                continue
            try:
                await nd.connect_to(other.host, other.port)
            except OSError:
                continue
        (restarted if resume else joined).append(i)

    status_task = None
    publish_pass = None
    if cfg.log_dir:
        # simulation-mode status publishing (round 12): the same
        # records _run_node's per-process loop publishes, emitted for
        # every node from one task — so the monitor/healthcheck see an
        # in-process federation too.
        from p2pfl_tpu.utils.monitor import publish_status

        status_dir = pathlib.Path(cfg.log_dir) / cfg.name / "status"

        published_final: set[int] = set()

        def publish_pass() -> None:
            for nd in nodes:
                if nd.finished.is_set():
                    # a CRASHED node never publishes again — its record
                    # ages out like a killed process's, which is what
                    # the node-dead rule keys on. A node that finished
                    # the schedule gracefully gets ONE final record so
                    # the dashboards and the healthcheck see its true
                    # final round instead of a stale mid-run snapshot.
                    if nd._crashed or nd.idx in published_final:
                        continue
                    published_final.add(nd.idx)
                publish_status(
                    status_dir, nd.idx,
                    {"role": nd.role, "round": nd.round,
                     "peers": len(nd.peers), "leader": nd.leader,
                     "round_p95_s": nd.round_p95_s(),
                     "bytes_in": nd.bytes_in,
                     "bytes_out": nd.bytes_out,
                     "peer_bytes_in": dict(nd.peer_bytes_in),
                     "peer_bytes_out": dict(nd.peer_bytes_out),
                     "recompiles": obs_trace.xla_recompiles(),
                     **_privacy_status(cfg, nd.round),
                     **_critpath_status(nd),
                     **_crossdev_status(nd),
                     **_devprof_status(nd),
                     **_aggd_status(sidecar)},
                )

        async def _status_loop() -> None:
            while True:
                publish_pass()
                await asyncio.sleep(cfg.protocol.heartbeat_period_s)

        status_task = asyncio.create_task(_status_loop())

    fault_task = None
    watch_tasks: list[asyncio.Task] = []
    recovery: dict = {"partitions": 0, "heals": 0}
    if cfg.faults:
        events = sorted(cfg.faults, key=lambda f: (f.round, f.node))

        async def _recovery_watch(t_heal: float,
                                  rounds_at_heal: dict[int, int]) -> None:
            # chaos_recovery_s: heal observation → first POST-MERGE
            # round, i.e. every live node has completed a round that
            # started after the heal (its front moved past the snapshot)
            while True:
                live = [nd for nd in nodes if not nd.finished.is_set()]
                if not live:
                    break
                if all(nd.round > rounds_at_heal.get(nd.idx, -1)
                       for nd in live):
                    break
                await asyncio.sleep(0.05)
            recovery["recovery_s"] = round(time.monotonic() - t_heal, 3)
            flight.record("sim.recovered",
                          recovery_s=recovery["recovery_s"])

        async def _fault_driver() -> None:
            for f in events:
                while True:
                    fronts = [nd.round for nd in nodes
                              if not nd.finished.is_set()]
                    if not fronts:
                        return  # federation over; remaining faults moot
                    if max(fronts) >= f.round:
                        break
                    await asyncio.sleep(0.05)
                if f.kind == "crash":
                    await nodes[f.node].crash()
                elif f.kind == "partition":
                    # same cut on every live node → symmetric sever
                    recovery["partitions"] += 1
                    for nd in nodes:
                        if not nd.finished.is_set():
                            nd.apply_partition(f.groups)
                elif f.kind == "heal":
                    recovery["heals"] += 1
                    snap = {nd.idx: nd.round for nd in nodes
                            if not nd.finished.is_set()}
                    for nd in nodes:
                        if not nd.finished.is_set():
                            nd.heal_partition()
                    watch_tasks.append(asyncio.create_task(
                        _recovery_watch(time.monotonic(), snap)))
                elif f.kind == "restart":
                    # crash-consistent relaunch: the fresh node adopts
                    # the newer of (own checkpoint, peer STATE_SYNC)
                    await _rejoin_node(f.node, resume=True)
                else:  # recover / join: live re-entry via the handshake
                    await _rejoin_node(f.node)

        fault_task = asyncio.create_task(_fault_driver())

    async def _all_finished() -> None:
        # replacement-aware: a join swaps nodes[i] for a fresh object,
        # so a plain gather over the initial events would miss it
        while not all(nd.finished.is_set() for nd in nodes):
            await asyncio.sleep(0.1)

    t0 = time.monotonic()
    nodes[starter].set_start_learning(
        cfg.training.rounds, cfg.training.epochs_per_round
    )
    try:
        await asyncio.wait_for(_all_finished(), timeout=timeout)
    finally:
        wall = time.monotonic() - t0
        if fault_task is not None:
            fault_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await fault_task
        for wt in watch_tasks:
            # give a still-pending recovery watch one tick to observe
            # the (now fully finished) federation, then reap it
            if not wt.done():
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(wt, timeout=0.5)
            if not wt.done():
                wt.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await wt
        if status_task is not None:
            status_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await status_task
        if publish_pass is not None:
            # one synchronous pass after the loop dies: the LAST node
            # to finish otherwise races the cancel and never gets its
            # graceful final record
            publish_pass()
        for node in nodes:
            await node.stop()
        if sidecar is not None:
            sidecar.close()
    accs = [
        m.get("accuracy") for m in
        (nd.peer_metrics.get(nd.idx) or {} for nd in nodes)
        if m.get("accuracy") is not None
    ]
    out = {
        "n_nodes": n,
        "rounds": min(nd.round for nd in nodes),
        "wall_s": round(wall, 3),
        "round_s": round(wall / max(cfg.training.rounds, 1), 3),
        "mean_accuracy": (
            round(sum(accs) / len(accs), 4) if accs else None
        ),
        # post-warm-up recompiles (0 on a healthy run — see the reset
        # above) and the federation's total wire traffic
        "xla_recompiles": obs_trace.xla_recompiles(),
        "bytes_in": sum(nd.bytes_in for nd in nodes),
        "bytes_out": sum(nd.bytes_out for nd in nodes),
        # encoded PARAMS blob bytes × targets — the wire-dtype A/B's
        # numerator, isolated from control-plane traffic
        "params_bytes_out": sum(nd.params_bytes_out for nd in nodes),
        # payload bytes the event loop itself decoded/materialized on
        # the round path — the aggregation-plane A/B's contrast metric
        # (sidecar arm pins this at 0; inline arm pays it in full)
        "loop_payload_touch_bytes": sum(
            nd.loop_payload_touch_bytes for nd in nodes),
    }
    if sidecar is not None:
        out["aggd_bytes_ingested"] = sidecar.bytes_ingested
        out["aggd_fused_rounds"] = sidecar.fused_rounds
        out["aggd_fallbacks"] = sidecar.fallbacks
    if cfg.faults or el.active:
        # elasticity accounting: who crashed/re-joined, which nodes ran
        # slow, and whether the async close rule was on — the churn
        # bench and the elasticity tests read these
        out["churn"] = {
            "async": el.async_aggregation,
            "crashes": sorted(f.node for f in cfg.faults
                              if f.kind == "crash"),
            "joined": sorted(joined),
            "restarted": sorted(restarted),
            "stragglers": [i for i in range(n)
                           if cfg.nodes[i].fit_slowdown > 1.0],
        }
        if recovery["partitions"] or recovery["heals"]:
            out["churn"]["partitions"] = recovery["partitions"]
            out["churn"]["heals"] = recovery["heals"]
            if "recovery_s" in recovery:
                out["churn"]["recovery_s"] = recovery["recovery_s"]
    if tracer.enabled:
        out["obs"] = tracer.summarize()
        tracer.export(process_name=f"sim[{cfg.name}]")
    if any(nd.reputation is not None for nd in nodes):
        # each node's LOCAL trust vector (decentralized: no shared
        # monitor) + who it would exclude — the robustness tests and
        # the monitor read these
        out["trust"] = [
            [round(float(t), 4) for t in nd.reputation.trust]
            if nd.reputation is not None else None
            for nd in nodes
        ]
        out["suspects"] = sorted(
            {s for nd in nodes if nd.reputation is not None
             for s in nd.reputation.suspects()}
        )
    return out


def run_simulation(cfg: ScenarioConfig, timeout: float = 600) -> dict:
    """ALL nodes of a socket federation in one process/event loop —
    the reference's simulation mode (``scenario_args.simulation``,
    SURVEY §4: same code path, loopback TCP, no cluster). One
    ``SharedTrainer`` serves every node, so the model compiles once
    instead of ``n_nodes`` times. Returns wall-clock and per-round
    timing plus the federation's mean final accuracy.

    Under ``P2PFL_SANITIZE=1`` the run executes with jax_debug_nans,
    asyncio debug mode, and leaked-resource/never-awaited warnings
    promoted to errors (utils/sanitize.py)."""
    from p2pfl_tpu.utils import sanitize

    with sanitize.scope():
        return asyncio.run(_simulate(cfg, timeout),
                           debug=sanitize.asyncio_debug())


def launch(cfg: ScenarioConfig, config_path: str | pathlib.Path,
           platform: str | None = None,
           nodes_per_proc: int = 1,
           max_restarts: int = 0,
           restart_backoff_s: float = 1.0) -> list[dict]:
    """Spawn node processes; collect their results.

    ``max_restarts`` > 0 turns the parent into a supervisor: a child
    group that dies (non-zero exit) is relaunched with ``--resume`` —
    each node adopts the newer of its own periodic checkpoint
    (``cfg.checkpoint_dir``) and a peer's STATE_SYNC — under
    exponential backoff (``restart_backoff_s * 2^(attempt-1)``, capped
    at 30 s), up to ``max_restarts`` times per group.

    ``nodes_per_proc`` > 1 packs k nodes into each child's event loop
    (``--node "0,1,2,3"``), so a 24-node federation can run as 24×1,
    6×4, … — the layouts the multi-process bench compares against the
    all-in-one-loop simulation mode.

    ``platform="cpu"`` forces the children onto the CPU backend — N
    processes cannot share one TPU chip, so multi-process mode on a
    single-chip host runs compute on CPU (on a pod each host pins its
    own chips).

    With ``cfg.encrypt`` the parent mints a scenario CA + per-node
    certificates next to the config file and every connection runs
    mutual TLS (controller-stamps-credentials analog of the
    reference's encrypter wiring, base_node.py:246-256).
    """
    ports = _free_ports(cfg.n_nodes)
    tls_dir = None
    if cfg.encrypt:
        from p2pfl_tpu.p2p.tls import make_scenario_credentials

        tls_dir = str(pathlib.Path(config_path).resolve().parent / "tls")
        make_scenario_credentials(tls_dir, cfg.n_nodes, name=cfg.name)
    k = max(int(nodes_per_proc), 1)
    groups = [list(range(i, min(i + k, cfg.n_nodes)))
              for i in range(0, cfg.n_nodes, k)]

    def _spawn(cmd: list[str]) -> subprocess.Popen:
        return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    cmds, procs = [], []
    for group in groups:
        cmd = [sys.executable, "-m", "p2pfl_tpu.p2p.launch",
               str(config_path), "--node", ",".join(map(str, group)),
               "--ports", ",".join(map(str, ports))]
        if platform:
            cmd += ["--platform", platform]
        if tls_dir:
            cmd += ["--tls-dir", tls_dir]
        cmds.append(cmd)
        procs.append(_spawn(cmd))

    def _supervise(gi: int) -> str:
        """Wait out one group, restarting it (with ``--resume``) on
        non-zero exit until the restart budget runs dry. Returns the
        concatenated stdout of every attempt — the parent scans it for
        P2PFL_RESULT lines, so a successful relaunch reports exactly
        like an uninterrupted child."""
        p, attempt, chunks = procs[gi], 0, []
        while True:
            out, _ = p.communicate(timeout=900)
            chunks.append(out)
            if p.returncode == 0 or attempt >= max_restarts:
                return "".join(chunks)
            attempt += 1
            delay = min(restart_backoff_s * (2.0 ** (attempt - 1)), 30.0)
            flight.record("launch.restart", group=groups[gi],
                          attempt=attempt, rc=p.returncode,
                          backoff_s=round(delay, 3))
            time.sleep(delay)
            p = _spawn(cmds[gi] + ["--resume"])

    if max_restarts > 0:
        # supervise groups concurrently: a crashed group must respawn
        # while its peers are still mid-federation, not after they exit
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(groups)) as pool:
            outs = list(pool.map(_supervise, range(len(groups))))
    else:
        outs = [_supervise(gi) for gi in range(len(groups))]
    results = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("P2PFL_RESULT "):
                results.append(json.loads(line[len("P2PFL_RESULT "):]))
    return results


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.p2p.launch")
    ap.add_argument("config")
    ap.add_argument("--node", default=None,
                    help="node index, or comma-separated indices to run "
                         "on one event loop (child mode)")
    ap.add_argument("--nodes-per-proc", type=int, default=1,
                    help="parent mode: pack k nodes into each child "
                         "process (e.g. 24 nodes, k=4 -> 6 processes)")
    ap.add_argument("--ports", default=None,
                    help="comma-separated port per node (child mode)")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu) in children")
    ap.add_argument("--tls-dir", default=None,
                    help="directory with scenario TLS material (child mode)")
    ap.add_argument("--hosts", default=None,
                    help="comma-separated per-node hostnames (child mode; "
                         "compose service names in a container deployment)")
    ap.add_argument("--bind", default="127.0.0.1",
                    help="listen address (0.0.0.0 inside containers)")
    ap.add_argument("--resume", action="store_true",
                    help="child mode: adopt the node's periodic "
                         "checkpoint before joining (restart path)")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="parent mode: relaunch a dead child group with "
                         "--resume up to this many times")
    ap.add_argument("--restart-backoff-s", type=float, default=1.0,
                    help="base of the exponential restart backoff "
                         "(doubles per attempt, capped at 30 s)")
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.node is not None:
        node_main(args.config,
                  [int(i) for i in str(args.node).split(",")],
                  [int(p) for p in args.ports.split(",")],
                  tls_dir=args.tls_dir,
                  hosts=args.hosts.split(",") if args.hosts else None,
                  bind=args.bind,
                  resume=args.resume)
        return 0
    cfg = ScenarioConfig.load(args.config)
    results = launch(cfg, args.config, platform=args.platform,
                     nodes_per_proc=args.nodes_per_proc,
                     max_restarts=args.max_restarts,
                     restart_backoff_s=args.restart_backoff_s)
    print(json.dumps({"nodes": results}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
