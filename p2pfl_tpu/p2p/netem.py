"""Deterministic network emulation for the socket path.

The reference degrades links with ``tcset --rate/--delay/--loss`` read
from config (fedstellar/base_node.py:82-85,
config/participant.json.example:34-38) — kernel-level shaping that
needs root and real interfaces. Here shaping happens at the message
layer instead, deterministically (seeded), so a test can assert "an
8-node federation converges under 50 ms delay + 5% loss" and get the
same drops every run.

Semantics per (src → dst) link:

- **delay + jitter**: each message is due at ``now + delay ± U(0,
  jitter)``; a per-link FIFO worker enforces ``due >= previous due``
  so a link never reorders (TCP semantics — shaped latency, not UDP).
- **loss**: the message is silently dropped before the socket write —
  modeling a gossip datagram that never arrives. On this framework's
  long-lived connections that is the application-level analog of
  ``tcset --loss`` stalling a TCP stream past its usefulness window:
  the receiver's timeouts (vote / aggregation / heartbeat eviction)
  must carry the round, which is exactly what the knob exists to test.
- **rate**: transmission time per message (payload bytes / rate) is
  added to the link occupancy — the ``tcset --rate`` analog.
- **backpressure**: link queues are bounded; a sender flooding a slow
  link blocks on ``send`` like a full TCP send buffer would, instead
  of growing an infinite buffer that starves every later message.
- **partition** (round 14): scheduled sever/heal windows
  (:class:`~p2pfl_tpu.config.schema.PartitionSpec`) drop every message
  crossing the declared group cut while a window is open — composing
  with the delay/loss/rate shaping above. The schedule is a pure
  function of (config, seed): window boundaries, including the
  optional seeded jitter, are drawn from ``(seed, "partition", k)``
  and are deliberately NOT per-source, so every node in the
  federation severs and heals the same cut at the same plan time.

Decisions come from one ``random.Random`` seeded per source node, so a
given scenario seed yields one reproducible fault schedule per node
regardless of event-loop interleaving across links.
"""

from __future__ import annotations

import asyncio
import random
from typing import Awaitable, Callable

from p2pfl_tpu.obs import flight
from p2pfl_tpu.p2p.protocol import Message, write_message


class LinkShaper:
    """Per-source shaping of outbound messages (delay/jitter/loss)."""

    #: bounded link queue — the "TCP send buffer". A sender that
    #: outpaces the link blocks on send() when this fills.
    QUEUE_DEPTH = 32

    def __init__(
        self,
        src: int,
        delay_ms: float = 0.0,
        jitter_ms: float = 0.0,
        loss_pct: float = 0.0,
        rate_mbps: float = 0.0,
        seed: int = 0,
        on_error: Callable[[object], None] | None = None,
        partitions=(),
        on_transition: Callable[[str, list], None] | None = None,
    ):
        self.src = src
        self.delay_s = max(delay_ms, 0.0) / 1000.0
        self.jitter_s = max(jitter_ms, 0.0) / 1000.0
        self.loss = min(max(loss_pct, 0.0), 100.0) / 100.0
        self.rate_bps = max(rate_mbps, 0.0) * 1e6 / 8.0  # bytes/s
        self._rng = random.Random((seed, "netem", src).__repr__())
        self._on_error = on_error
        # partition plan: (start, end, groups, node -> group index).
        # Boundary jitter is seeded per WINDOW, not per source — the
        # whole federation must agree on when the cut exists
        self._windows: list[tuple[float, float, list, dict[int, int]]] = []
        for k, spec in enumerate(partitions or ()):
            wrng = random.Random((seed, "partition", k).__repr__())
            j = float(getattr(spec, "jitter_s", 0.0))
            start = max(spec.start_s + wrng.uniform(-j, j), 0.0)
            end = max(start + spec.duration_s + wrng.uniform(-j, j), start)
            group_of = {int(n): gi for gi, g in enumerate(spec.groups)
                        for n in g}
            self._windows.append((start, end, spec.groups, group_of))
        self._part_active: set[int] = set()
        self._epoch: float | None = None
        self._on_transition = on_transition
        # per-destination FIFO: (peer, msg, due) consumed by one worker
        self._queues: dict[int, asyncio.Queue] = {}
        self._workers: dict[int, asyncio.Task] = {}
        self._busy_until: dict[int, float] = {}
        self._last_due: dict[int, float] = {}
        self.sent = 0
        self.dropped = 0
        self.part_dropped = 0

    @property
    def active(self) -> bool:
        return (self.delay_s > 0 or self.jitter_s > 0 or self.loss > 0
                or self.rate_bps > 0 or bool(self._windows))

    # -- partition plan ----------------------------------------------------
    def start_clock(self) -> None:
        """Pin plan time 0 to now (idempotent). Called from node start
        so every node's windows are measured from federation start;
        otherwise the epoch pins lazily at the first send."""
        if self._epoch is None:
            self._epoch = asyncio.get_event_loop().time()

    def _plan_time(self, now: float) -> float:
        if self._epoch is None:
            self._epoch = now
        return now - self._epoch

    def severed(self, dst: int, t: float) -> bool:
        """True when plan time ``t`` falls inside a window whose cut
        separates this source from ``dst``. Nodes outside every group
        of a window are unaffected by it."""
        for start, end, _groups, group_of in self._windows:
            if start <= t < end:
                gs, gd = group_of.get(self.src), group_of.get(int(dst))
                if gs is not None and gd is not None and gs != gd:
                    return True
        return False

    def severed_now(self, dst: int) -> bool:
        """``severed`` against the live plan clock — the node's probe
        machinery asks this before trusting a TCP dial across the cut."""
        if not self._windows:
            return False
        return self.severed(dst,
                            self._plan_time(asyncio.get_event_loop().time()))

    def _note_transitions(self, t: float) -> None:
        """Record sever/heal edges (flight + callback) as plan time
        crosses window boundaries. Piggybacked on send(), so detection
        latency is one outbound message — at most a heartbeat period."""
        now_active = {k for k, (s, e, _g, _m) in enumerate(self._windows)
                      if s <= t < e}
        for k in sorted(now_active - self._part_active):
            groups = self._windows[k][2]
            flight.record("netem.partition", src=self.src, window=k,
                          groups=groups, t=round(t, 3))
            if self._on_transition is not None:
                self._on_transition("partition", groups)
        for k in sorted(self._part_active - now_active):
            groups = self._windows[k][2]
            flight.record("netem.heal", src=self.src, window=k,
                          groups=groups, t=round(t, 3))
            if self._on_transition is not None:
                self._on_transition("heal", groups)
        self._part_active = now_active

    def _size(self, msg: Message) -> int:
        return len(msg.payload or b"") + 256  # header/body estimate

    async def send(self, peer, msg: Message) -> None:
        """Queue ``msg`` for ``peer`` under the link schedule. Blocks
        only when the link's bounded queue is full (backpressure);
        delivery happens on the link worker."""
        loop = asyncio.get_event_loop()
        if self._windows:
            t = self._plan_time(loop.time())
            self._note_transitions(t)
            if self.severed(peer.idx, t):
                self.part_dropped += 1
                return
        if self.loss and self._rng.random() < self.loss:
            self.dropped += 1
            return
        now = loop.time()
        # link occupancy: serialization time at the configured rate,
        # FIFO behind whatever is already scheduled on this link
        start = max(now, self._busy_until.get(peer.idx, 0.0))
        tx = self._size(msg) / self.rate_bps if self.rate_bps else 0.0
        self._busy_until[peer.idx] = start + tx
        # one-way latency on top of serialization
        due = start + tx + self.delay_s
        if self.jitter_s:
            due += self._rng.uniform(0.0, self.jitter_s)
        # jitter must not reorder the link (TCP semantics)
        due = max(due, self._last_due.get(peer.idx, 0.0))
        self._last_due[peer.idx] = due
        q = self._queues.get(peer.idx)
        if q is None:
            q = self._queues[peer.idx] = asyncio.Queue(self.QUEUE_DEPTH)
            self._workers[peer.idx] = asyncio.create_task(self._drain(q))
        await q.put((peer, msg, due))

    async def _drain(self, q: asyncio.Queue) -> None:
        loop = asyncio.get_event_loop()
        while True:
            peer, msg, due = await q.get()
            wait = due - loop.time()
            if wait > 0:
                await asyncio.sleep(wait)
            try:
                await write_message(peer.writer, msg)
                self.sent += 1
            except (ConnectionError, RuntimeError, OSError):
                if self._on_error is not None:
                    self._on_error(peer)

    def close(self) -> None:
        for t in self._workers.values():
            t.cancel()
        self._workers.clear()
        self._queues.clear()


def shaper_from_config(src: int, net, on_error=None,
                       on_transition=None) -> LinkShaper | None:
    """Build a shaper from a ``NetworkConfig`` (None or all-zero →
    no shaping, zero-overhead direct writes). A partition plan alone
    activates the shaper even with all rate/delay/loss knobs at zero."""
    if net is None:
        return None
    s = LinkShaper(
        src,
        delay_ms=net.delay_ms,
        jitter_ms=net.jitter_ms,
        loss_pct=net.loss_pct,
        rate_mbps=getattr(net, "rate_mbps", 0.0),
        seed=net.seed,
        on_error=on_error,
        partitions=getattr(net, "partitions", ()),
        on_transition=on_transition,
    )
    return s if s.active else None
