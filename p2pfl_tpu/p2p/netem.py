"""Deterministic network emulation for the socket path.

The reference degrades links with ``tcset --rate/--delay/--loss`` read
from config (fedstellar/base_node.py:82-85,
config/participant.json.example:34-38) — kernel-level shaping that
needs root and real interfaces. Here shaping happens at the message
layer instead, deterministically (seeded), so a test can assert "an
8-node federation converges under 50 ms delay + 5% loss" and get the
same drops every run.

Semantics per (src → dst) link:

- **delay + jitter**: each message is due at ``now + delay ± U(0,
  jitter)``; a per-link FIFO worker enforces ``due >= previous due``
  so a link never reorders (TCP semantics — shaped latency, not UDP).
- **loss**: the message is silently dropped before the socket write —
  modeling a gossip datagram that never arrives. On this framework's
  long-lived connections that is the application-level analog of
  ``tcset --loss`` stalling a TCP stream past its usefulness window:
  the receiver's timeouts (vote / aggregation / heartbeat eviction)
  must carry the round, which is exactly what the knob exists to test.
- **rate**: transmission time per message (payload bytes / rate) is
  added to the link occupancy — the ``tcset --rate`` analog.
- **backpressure**: link queues are bounded; a sender flooding a slow
  link blocks on ``send`` like a full TCP send buffer would, instead
  of growing an infinite buffer that starves every later message.

Decisions come from one ``random.Random`` seeded per source node, so a
given scenario seed yields one reproducible fault schedule per node
regardless of event-loop interleaving across links.
"""

from __future__ import annotations

import asyncio
import random
from typing import Awaitable, Callable

from p2pfl_tpu.p2p.protocol import Message, write_message


class LinkShaper:
    """Per-source shaping of outbound messages (delay/jitter/loss)."""

    #: bounded link queue — the "TCP send buffer". A sender that
    #: outpaces the link blocks on send() when this fills.
    QUEUE_DEPTH = 32

    def __init__(
        self,
        src: int,
        delay_ms: float = 0.0,
        jitter_ms: float = 0.0,
        loss_pct: float = 0.0,
        rate_mbps: float = 0.0,
        seed: int = 0,
        on_error: Callable[[object], None] | None = None,
    ):
        self.src = src
        self.delay_s = max(delay_ms, 0.0) / 1000.0
        self.jitter_s = max(jitter_ms, 0.0) / 1000.0
        self.loss = min(max(loss_pct, 0.0), 100.0) / 100.0
        self.rate_bps = max(rate_mbps, 0.0) * 1e6 / 8.0  # bytes/s
        self._rng = random.Random((seed, "netem", src).__repr__())
        self._on_error = on_error
        # per-destination FIFO: (peer, msg, due) consumed by one worker
        self._queues: dict[int, asyncio.Queue] = {}
        self._workers: dict[int, asyncio.Task] = {}
        self._busy_until: dict[int, float] = {}
        self._last_due: dict[int, float] = {}
        self.sent = 0
        self.dropped = 0

    @property
    def active(self) -> bool:
        return (self.delay_s > 0 or self.jitter_s > 0 or self.loss > 0
                or self.rate_bps > 0)

    def _size(self, msg: Message) -> int:
        return len(msg.payload or b"") + 256  # header/body estimate

    async def send(self, peer, msg: Message) -> None:
        """Queue ``msg`` for ``peer`` under the link schedule. Blocks
        only when the link's bounded queue is full (backpressure);
        delivery happens on the link worker."""
        if self.loss and self._rng.random() < self.loss:
            self.dropped += 1
            return
        loop = asyncio.get_event_loop()
        now = loop.time()
        # link occupancy: serialization time at the configured rate,
        # FIFO behind whatever is already scheduled on this link
        start = max(now, self._busy_until.get(peer.idx, 0.0))
        tx = self._size(msg) / self.rate_bps if self.rate_bps else 0.0
        self._busy_until[peer.idx] = start + tx
        # one-way latency on top of serialization
        due = start + tx + self.delay_s
        if self.jitter_s:
            due += self._rng.uniform(0.0, self.jitter_s)
        # jitter must not reorder the link (TCP semantics)
        due = max(due, self._last_due.get(peer.idx, 0.0))
        self._last_due[peer.idx] = due
        q = self._queues.get(peer.idx)
        if q is None:
            q = self._queues[peer.idx] = asyncio.Queue(self.QUEUE_DEPTH)
            self._workers[peer.idx] = asyncio.create_task(self._drain(q))
        await q.put((peer, msg, due))

    async def _drain(self, q: asyncio.Queue) -> None:
        loop = asyncio.get_event_loop()
        while True:
            peer, msg, due = await q.get()
            wait = due - loop.time()
            if wait > 0:
                await asyncio.sleep(wait)
            try:
                await write_message(peer.writer, msg)
                self.sent += 1
            except (ConnectionError, RuntimeError, OSError):
                if self._on_error is not None:
                    self._on_error(peer)

    def close(self) -> None:
        for t in self._workers.values():
            t.cancel()
        self._workers.clear()
        self._queues.clear()


def shaper_from_config(src: int, net, on_error=None) -> LinkShaper | None:
    """Build a shaper from a ``NetworkConfig`` (None or all-zero →
    no shaping, zero-overhead direct writes)."""
    if net is None:
        return None
    s = LinkShaper(
        src,
        delay_ms=net.delay_ms,
        jitter_ms=net.jitter_ms,
        loss_pct=net.loss_pct,
        rate_mbps=getattr(net, "rate_mbps", 0.0),
        seed=net.seed,
        on_error=on_error,
    )
    return s if s.active else None
