"""Transport security for the socket path: mutual TLS.

The reference encrypts peer traffic with a hand-rolled RSA-1024
PKCS1-OAEP handshake carrying an AES-128-**ECB** session key
(fedstellar/encrypter.py:48-193, base_node.py:246-256) — a homemade
scheme with a broken cipher mode. This module replaces it with real
mutual TLS: one self-signed **scenario CA** issues a certificate per
node; both sides of every connection require a peer certificate chained
to the scenario CA, so a plaintext peer or a node from another scenario
cannot join the federation.

Key type is ECDSA P-256 (fast issuance — a 64-node scenario mints its
certs in well under a second, vs multi-second RSA keygen).

Transport TLS alone authenticates the *connection*, not the *origin* of
a gossiped message: control messages flood multi-hop, so a relayed
frame's ``sender`` is legitimately not the connection peer, and a
malicious-but-valid member could forge another node's STOP or ballot.
MessageSigner/MessageVerifier close that hole with per-message origin
signatures: the originator signs the frame's canonical bytes with its
TLS key and attaches its certificate; receivers chain the cert to the
pinned scenario CA, require CN == node<sender>, and verify the
signature. The signed bytes cover the payload only through its SHA-256
digest (protocol.Message.signing_bytes), so the round-7 two-segment
wire format changes nothing here: the digest is computed once when the
origin signs, cached on the Message, and relays re-frame the header
without rehashing the payload; verifiers always recompute the digest
from the bytes they received, never trusting the header's copy.
``asyncio.start_server(ssl=...)`` wraps the same StreamReader/Writer
pair the plaintext path uses, so writelines-vectored sends and the
payload-segment reads work unchanged over TLS (the SSL transport
copies into its encryption buffer — that copy is the cipher's, not the
framing's). Short-term replay is absorbed by the gossip dedup ring
(msg_id is inside the signed bytes); a replay after ring eviction can
only re-deliver a message the origin really sent, and every handler a
late replay could bite is fenced: ballots and leadership transfers
carry their round inside the signed bytes and stale rounds are
rejected, progress snapshots sit behind a monotonic guard, and
re-evicting a node that already left is idempotent.
"""

from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import pathlib
import ssl

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

_ONE_DAY = datetime.timedelta(days=1)
_VALIDITY = datetime.timedelta(days=365)


def _name(common_name: str) -> x509.Name:
    return x509.Name(
        [
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "p2pfl_tpu"),
            x509.NameAttribute(NameOID.COMMON_NAME, common_name),
        ]
    )


def _write_key(path: pathlib.Path, key) -> None:
    path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
    )


def _write_cert(path: pathlib.Path, cert: x509.Certificate) -> None:
    path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))


@dataclasses.dataclass(frozen=True)
class TLSCredentials:
    """One node's identity: its cert/key plus the scenario CA to pin."""

    ca_cert: pathlib.Path
    cert: pathlib.Path
    key: pathlib.Path

    def _context(self, purpose: ssl.Purpose) -> ssl.SSLContext:
        ctx = ssl.create_default_context(purpose, cafile=str(self.ca_cert))
        ctx.load_cert_chain(str(self.cert), str(self.key))
        # authentication is CA pinning, not hostname matching: every
        # scenario member presents a cert from THIS scenario's CA;
        # hostnames are meaningless for ephemeral localhost ports
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def server_context(self) -> ssl.SSLContext:
        return self._context(ssl.Purpose.CLIENT_AUTH)

    def client_context(self) -> ssl.SSLContext:
        return self._context(ssl.Purpose.SERVER_AUTH)


def generate_scenario_ca(directory: str | pathlib.Path,
                         name: str = "scenario") -> tuple[pathlib.Path, pathlib.Path]:
    """Mint the scenario CA. Returns (ca_cert_path, ca_key_path)."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    subject = _name(f"p2pfl_tpu CA {name}")
    cert = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + _VALIDITY)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    ca_cert, ca_key = directory / "ca.crt", directory / "ca.key"
    _write_cert(ca_cert, cert)
    _write_key(ca_key, key)
    return ca_cert, ca_key


def issue_node_cert(directory: str | pathlib.Path, idx: int,
                    ca_cert: str | pathlib.Path,
                    ca_key: str | pathlib.Path) -> TLSCredentials:
    """Issue node ``idx``'s certificate signed by the scenario CA."""
    directory = pathlib.Path(directory)
    ca_cert = pathlib.Path(ca_cert)
    ca = x509.load_pem_x509_certificate(ca_cert.read_bytes())
    ca_private = serialization.load_pem_private_key(
        pathlib.Path(ca_key).read_bytes(), password=None
    )
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(f"node{idx}"))
        .issuer_name(ca.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + _VALIDITY)
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName(f"node{idx}"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(ca_private, hashes.SHA256())
    )
    cert_path, key_path = directory / f"node{idx}.crt", directory / f"node{idx}.key"
    _write_cert(cert_path, cert)
    _write_key(key_path, key)
    return TLSCredentials(ca_cert=ca_cert, cert=cert_path, key=key_path)


def make_scenario_credentials(
    directory: str | pathlib.Path, n_nodes: int, name: str = "scenario"
) -> list[TLSCredentials]:
    """CA + one credential per node, all in ``directory``."""
    ca_cert, ca_key = generate_scenario_ca(directory, name)
    return [issue_node_cert(directory, i, ca_cert, ca_key)
            for i in range(n_nodes)]


def _cn_to_idx(cn: str) -> int | None:
    """The single source of the ``node<idx>`` CN naming rule."""
    if not cn.startswith("node"):
        return None
    try:
        return int(cn[4:])
    except ValueError:
        return None


def peer_index(peercert: dict | None) -> int | None:
    """Node index from a transport peer certificate, as returned by
    ``ssl``'s ``getpeercert()`` dict form (available because both
    contexts set CERT_REQUIRED). None if the CN is not ``node<idx>``."""
    if not peercert:
        return None
    for rdn in peercert.get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                return _cn_to_idx(value)
    return None


class MessageSigner:
    """Signs self-originated frames with this node's TLS key."""

    def __init__(self, creds: TLSCredentials):
        self._key = serialization.load_pem_private_key(
            creds.key.read_bytes(), password=None
        )
        self.cert_pem = creds.cert.read_bytes()

    def sign(self, data: bytes) -> bytes:
        return self._key.sign(data, ec.ECDSA(hashes.SHA256()))


class MessageVerifier:
    """Verifies origin signatures against the pinned scenario CA.

    Certificates arrive attached to the message (a receiver has only
    its own credentials + the CA, and flooded messages originate from
    nodes it never handshook with). Verified certs are cached by their
    PEM bytes so steady-state cost is one ECDSA verify per message.
    """

    _CACHE_MAX = 4096  # bounded: one entry per distinct member cert

    def __init__(self, ca_cert: str | pathlib.Path):
        ca = x509.load_pem_x509_certificate(
            pathlib.Path(ca_cert).read_bytes()
        )
        self._ca_key = ca.public_key()
        self._trusted: dict[bytes, tuple[int, object]] = {}

    def _load(self, cert_pem: bytes) -> tuple[int, object]:
        cached = self._trusted.get(cert_pem)
        if cached is not None:
            return cached
        cert = x509.load_pem_x509_certificate(cert_pem)
        # chain to the pinned CA (path length 0: members are leaves)
        self._ca_key.verify(
            cert.signature,
            cert.tbs_certificate_bytes,
            ec.ECDSA(cert.signature_hash_algorithm),
        )
        cn = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)[0].value
        idx = _cn_to_idx(cn)
        if idx is None:
            raise ValueError(f"not a member certificate: CN={cn!r}")
        entry = (idx, cert.public_key())
        if len(self._trusted) < self._CACHE_MAX:
            self._trusted[cert_pem] = entry
        return entry

    def verify(self, cert_pem: bytes, sig: bytes, data: bytes,
               claimed_idx: int) -> bool:
        """True iff ``cert_pem`` chains to the CA, its CN names
        ``claimed_idx``, and ``sig`` covers ``data``."""
        if not cert_pem or not sig:
            return False
        try:
            idx, public_key = self._load(cert_pem)
            if idx != claimed_idx:
                return False
            public_key.verify(sig, data, ec.ECDSA(hashes.SHA256()))
            return True
        except Exception:
            return False


def load_node_credentials(directory: str | pathlib.Path,
                          idx: int) -> TLSCredentials:
    """Load credentials previously minted by make_scenario_credentials
    (the multi-process children's path)."""
    directory = pathlib.Path(directory)
    creds = TLSCredentials(
        ca_cert=directory / "ca.crt",
        cert=directory / f"node{idx}.crt",
        key=directory / f"node{idx}.key",
    )
    for p in (creds.ca_cert, creds.cert, creds.key):
        if not p.exists():
            raise FileNotFoundError(f"missing TLS material: {p}")
    return creds
