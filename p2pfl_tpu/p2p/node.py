"""P2PNode: an asyncio federated node over TCP.

Role/behavior parity with the reference's Node (fedstellar/node.py) and
BaseNode (base_node.py), with the thread-per-connection design replaced
by one event loop per node:

- listener + per-peer streams + CONNECT handshake
  (base_node.py:197-278);
- heartbeats feeding wall-clock membership (heartbeater.py);
- gossip flooding of control messages with at-most-once dedup
  (gossiper.py, communication_protocol.py:146-160);
- the round state machine with role branches (node.py:427-524):
  AGGREGATOR/SERVER train + aggregate + gossip partial aggregates;
  TRAINER trains, ships its model, adopts the aggregate; IDLE only
  adopts; per-peer progress tracking (MODELS_AGGREGATED /
  MODELS_READY / MODEL_INITIALIZED) gates who still needs gossip
  (node.py:695-724);
- initial model diffusion from the starter node (node.py:299);
- SDFL leadership transfer (node.py:676-686).

Local training runs through any NodeLearner (JaxLearner — jitted on
the host's TPU); only weight payloads cross the network, in the safe
envelope from p2pfl_tpu.core.serialize.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging
import math
import random
import secrets
import time
from typing import Any

import jax
import numpy as np

from p2pfl_tpu.config.schema import ElasticConfig, FaultEvent, ProtocolConfig
from p2pfl_tpu.core.aggregators import Aggregator
from p2pfl_tpu.core.serialize import (
    WIRE_DTYPES,
    decode_parameters,
    dequantize_int8,
    encode_parameters,
    quantize_int8,
)
from p2pfl_tpu.federation.events import Events
from p2pfl_tpu.federation.membership import Membership
from p2pfl_tpu.obs import flight
from p2pfl_tpu.obs.trace import NULL_SPAN, get_tracer
from p2pfl_tpu.p2p.protocol import (
    GOSSIPED,
    PERIODIC_FLOODS,
    DedupRing,
    Message,
    MsgType,
    read_message,
    write_message,
)

log = logging.getLogger("p2pfl_tpu.p2p")

#: transport-buffer ceiling for the idle-lane fast write (matches
#: asyncio's default 64 KiB high-water mark): under it a send goes
#: straight to the transport; over it the frame takes the bounded
#: queue and the drain task's drain() await applies real backpressure
_FAST_LANE_MAX = 1 << 16


@dataclasses.dataclass
class PeerState:
    """One live connection (node_connection.py's socket half).

    ``send_q`` + ``send_task`` form the connection's egress lane: every
    outbound frame is enqueued and a single per-peer drain task owns
    the writer. The queue is bounded (ProtocolConfig.send_queue_depth),
    so a peer that stops reading exerts backpressure on ITS lane only —
    broadcast enqueues to all lanes concurrently and never serializes
    on the slowest peer's TCP buffer. The single-writer discipline also
    guarantees frames never interleave and per-peer FIFO order holds
    (round-state messages rely on stream order, see _train_round)."""

    idx: int
    writer: asyncio.StreamWriter
    reader_task: asyncio.Task | None = None
    send_q: asyncio.Queue | None = None
    send_task: asyncio.Task | None = None
    # True only while the drain task is mid-write: the idle-lane fast
    # path (node._write) must not interleave with it
    draining: bool = False


@dataclasses.dataclass
class NodeProgress:
    """A node's round-progress as this node knows it
    (node_connection.py:275-335's tracking, decoupled from the
    connection: progress messages FLOOD, so state is known for every
    federation member, not just direct peers — that is what lets a
    gossiper reason about nodes it can only reach through a PROXY)."""

    models_aggregated: set[int] = dataclasses.field(default_factory=set)
    agg_round: int = -1  # round the models_aggregated set belongs to
    initialized: bool = False
    ready_round: int = -1


class P2PNode:
    """One federated node. Wire up a learner, start, connect, learn."""

    def __init__(
        self,
        idx: int,
        learner,
        host: str = "127.0.0.1",
        port: int = 0,
        role: str = "aggregator",
        n_nodes: int = 2,
        aggregator: Aggregator | None = None,
        protocol: ProtocolConfig | None = None,
        start_learning: bool = False,
        gossip_period_s: float | None = None,
        federation: str = "DFL",
        seed: int = 0,
        tls=None,
        netem=None,
        full_mesh: bool = False,
        attack=None,
        reputation=None,
        wire_dtype: str = "f32",
        elastic: ElasticConfig | None = None,
        fit_slowdown: float = 1.0,
        local_epochs: int | None = None,
        joiner: bool = False,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        sidecar=None,
        dp=None,
        masker=None,
    ):
        from p2pfl_tpu.p2p.session import AggregationSession, SidecarSession

        self.idx = idx
        self.learner = learner
        self.host = host
        self.port = port
        self.role = role
        self.n_nodes = n_nodes
        self.protocol = protocol or ProtocolConfig()
        self.start_learning_flag = start_learning
        # explicit argument wins; otherwise the ProtocolConfig knob
        # (GOSSIP_MODELS_FREC analog) paces gossip/poll ticks
        self.gossip_period_s = (
            gossip_period_s if gossip_period_s is not None
            else self.protocol.gossip_period_s
        )
        self.federation = federation
        # Declared-full-mesh relay suppression (set by the launcher for
        # topology="fully" ONLY): when every pair of nodes holds a
        # direct link by construction, the origin's broadcast already
        # reached everyone and epidemic re-relay multiplies control
        # traffic by the fanout for zero reach (measured ~1.2M frames
        # over 3 rounds at 24 nodes, exp_socket_profile.py). This must
        # be DECLARED, not inferred from len(peers) == n-1: in a line
        # 0-1-2 the middle node has n-1 peers while the ends cannot
        # reach each other except through its relay.
        self.full_mesh = full_mesh
        # mutual TLS (p2pfl_tpu.p2p.tls.TLSCredentials) — replaces the
        # reference's RSA/AES-ECB handshake (encrypter.py:48-193).
        # With TLS on, every self-originated message is origin-signed
        # and every received message's signature is checked against the
        # scenario CA, so a valid member cannot forge another node's
        # STOP / ballot / leadership transfer (see p2p.tls docstring).
        self.tls = tls
        if tls is not None:
            from p2pfl_tpu.p2p.tls import MessageSigner, MessageVerifier

            self._signer = MessageSigner(tls)
            self._verifier = MessageVerifier(tls.ca_cert)
        else:
            self._signer = None
            self._verifier = None
        self._rng = random.Random(seed * 7919 + idx)
        # deterministic link shaping (NetworkConfig / tcset analog,
        # base_node.py:82-85) — None when unshaped, so the default
        # send path stays a direct socket write
        from p2pfl_tpu.p2p.netem import shaper_from_config

        self.shaper = shaper_from_config(
            idx, netem, on_error=self._drop_conn,
            on_transition=self._on_netem_transition)
        # adversary hooks (p2pfl_tpu.adversary): ``attack`` is an
        # AttackSpec THIS node applies to its own outgoing update
        # (a malicious node attacks; honest nodes pass None);
        # ``reputation`` is a ReputationMonitor shared with the session
        # so finish-time aggregation is trust-weighted
        self.attack = attack
        self.reputation = reputation
        # privacy hooks (p2pfl_tpu.privacy): ``dp`` is a DPSpec — this
        # node clips + noises its own trained update post-fit, keyed by
        # (dp.seed, idx, round) so the SPMD row is bit-identical;
        # ``masker`` is a PairwiseMasker — outgoing updates are
        # pairwise-masked fixed-point trees and the session fuses in
        # the modular domain, unmasking only at quorum close
        self.dp = dp
        self.masker = masker
        # wire precision for PARAMS payloads (config.wire_dtype). The
        # knob names what this node WANTS to ship; what it actually
        # ships to a given target set is negotiated per send: every
        # CONNECT hello carries the supported-dtype list ("wd"), and a
        # reduced-precision payload goes out only when ALL targets of
        # that send advertised the dtype — otherwise the send falls
        # back to the f32 v1 envelope (one Message per target set, so
        # precision is per-send, never per-peer re-encoded). Peers that
        # predate the field advertise nothing and always get f32.
        if wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"unknown wire_dtype {wire_dtype!r}; have {WIRE_DTYPES}")
        self.wire_dtype = wire_dtype
        self._peer_wire: dict[int, tuple[str, ...]] = {}
        # int8 error feedback: the quantization error of this node's
        # own shipped update, carried into the next round's send so the
        # rounding bias cancels over time instead of accumulating
        # (residual lives host-side; reset on leaf-structure change)
        self._ef_residual: list[Any] | None = None
        # params payload bytes shipped (encoded blob size × targets):
        # the wire-dtype A/B's numerator, isolated from control traffic
        self.params_bytes_out = 0
        # obs wiring: the process tracer (configured in place, so the
        # cached reference stays valid across enable/disable) + always-
        # counted wire totals. The plain ints cost two adds per frame
        # regardless of tracing; per-peer/per-type counter keys are
        # built only behind tracer.enabled (f-strings per frame are
        # exactly the allocation the disabled path must not pay).
        self._tracer = get_tracer()
        self._lane = f"node{idx}"
        self.bytes_in = 0
        self.bytes_out = 0
        # always-on per-peer wire totals (round 14): two dict-int adds
        # per frame, published with the status record so the health
        # plane can see per-LINK silence — a partition is invisible in
        # the plain totals (gossip inside one side keeps them growing)
        # but shows as cross-cut per-peer counters going one-sided
        self.peer_bytes_in: dict[int, int] = {}
        self.peer_bytes_out: dict[int, int] = {}
        # per-round wall clocks (appended by _learning_loop) — the p95
        # the status publisher reports comes from here
        self.round_wall_s: list[float] = []
        # per-round critical-path accumulators (round 18): plain-float
        # adds like bytes_in — always-on except _cp_wire_s, which needs
        # the sender's tc stamp and therefore only accrues while
        # tracing is on. _learning_loop snapshots them into
        # ``critpath_last`` at every round close (the status publisher
        # flattens that into critpath_* gauges) and zeroes them.
        self._cp_fit_s = 0.0
        self._cp_wait_s = 0.0
        self._cp_wire_s = 0.0
        self._cp_agg_mark = 0.0
        #: last completed round's fit/wire/wait/aggregate/other split
        #: (None until a round finishes)
        self.critpath_last: dict[str, float] | None = None
        # elasticity profile (round 11): async aggregation knobs feed
        # the session, heartbeat probe/backoff knobs feed membership,
        # and the per-node compute class (fit_slowdown / local_epochs)
        # shapes _fit. ``joiner`` marks a node entering a RUNNING
        # federation: its CONNECT hello declares the join ("jr") and
        # the established side answers with STATE_SYNC.
        el = elastic if elastic is not None else ElasticConfig()
        self.elastic = el
        self.fit_slowdown = float(fit_slowdown)
        self.local_epochs = local_epochs
        self.joiner = bool(joiner)
        # crash-consistent auto-resume (round 14): with a checkpoint
        # dir configured the node snapshots (params, round) every
        # ``checkpoint_every`` rounds; ``resume=True`` relaunches it
        # from the newest of (own checkpoint, peer STATE_SYNC)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.resume = bool(resume)
        # the round the on-disk checkpoint carried; STATE_SYNC adoption
        # compares against it (newer wins) and clears it once decided
        self._resume_round: int | None = None
        # peers currently behind a scripted partition cut — outbound
        # frames to them are dropped at the write layer (both sides of
        # the cut hold the same set, so the sever is symmetric)
        self._severed: set[int] = set()
        # dial-back addresses, learned from CONNECT hellos — reconnect
        # probes redial these when a peer's heartbeats go silent
        self._peer_addrs: dict[int, tuple[str, int]] = {}
        # STATE_SYNC round target that arrived while a round body was
        # active — applied at the next round boundary (jumping
        # self.round mid-round would desync the live session)
        self._join_round_target: int | None = None
        # aggregation sidecar (round 16): ``sidecar`` is the host
        # process's shared aggd.SidecarClient — when present, payload
        # bytes bypass this loop entirely (protocol slot_sink → shm
        # arena → sidecar fuse) and the session is the slot-native
        # SidecarSession. ``loop_payload_touch_bytes`` counts every
        # payload byte the ROUND PATH still materializes/decodes on
        # the loop (the zero-copy pin asserts ≈0 under the sidecar;
        # one-time init diffusion is bootstrap, not round path, and
        # executor-side decodes never touch the loop).
        self.sidecar = sidecar
        self.loop_payload_touch_bytes = 0
        if sidecar is not None and masker is not None:
            # config.schema refuses this combination; a direct caller
            # gets the same loud failure instead of a sidecar fuse that
            # silently float-averages masked ring elements
            raise ValueError(
                "secagg masking needs the inline session: the sidecar "
                "fuses raw slot bytes as floats, not the modular sum"
            )
        if sidecar is not None:
            self.session: AggregationSession = SidecarSession(
                aggregator,
                timeout_s=self.protocol.aggregation_timeout_s,
                reputation=reputation, lane=self._lane,
                min_received=el.min_received if el.async_aggregation
                else 1.0,
                staleness_beta=el.staleness_beta
                if el.async_aggregation else 0.0,
                client=sidecar, spawn=self._track_task,
            )
        else:
            self.session = AggregationSession(
                aggregator,
                timeout_s=self.protocol.aggregation_timeout_s,
                reputation=reputation, lane=self._lane,
                min_received=el.min_received if el.async_aggregation
                else 1.0,
                staleness_beta=el.staleness_beta
                if el.async_aggregation else 0.0,
                masker=masker,
            )
        self.membership = Membership(
            n_nodes, self.protocol, virtual=False,
            retry_limit=el.heartbeat_retry_limit,
            backoff_base_s=el.heartbeat_backoff_base_s,
            backoff_max_s=el.heartbeat_backoff_max_s,
        )
        self.peers: dict[int, PeerState] = {}
        self.progress: dict[int, NodeProgress] = {}
        self.peer_roles: dict[int, str] = {}
        # flooded evaluation metrics per node (METRICS messages — the
        # reference defines the type but stubs the handler,
        # node.py:875-878; here they feed monitoring)
        self.peer_metrics: dict[int, dict[str, Any]] = {}
        # capacity scales with federation size: BEATs from every node
        # share this ring, and 100 ids evict before a flood quiesces
        # once ~100 gossip ids are in flight per eviction window
        self.dedup = DedupRing(capacity=max(100, 20 * n_nodes))
        self.round = 0
        self.total_rounds = 0
        # train-set ballots: round -> voter -> candidate tuple
        # (VOTE_TRAIN_SET flow, communication_protocol.py:47 +
        # node.py:881-887 vote intake)
        self._votes: dict[int, dict[int, tuple[int, ...]]] = {}
        self.epochs = 1
        self.initialized = False
        self.learning = False
        self.leader: int | None = None
        # every leadership token position this node observed, in order —
        # tests and monitoring assert on the rotation *history*, not the
        # chance-dependent final position
        self.leader_history: list[int] = []
        # weight messages that arrived for a FUTURE round (a fast peer
        # past the barrier) or outside an active round body — replayed
        # when this node's round body reaches them
        self._pending_params: list[tuple[PeerState, Message]] = []
        # highest beat sequence seen per node (replay fence — see the
        # BEAT handler)
        self._beat_seen: dict[int, int] = {}
        self._round_active = False
        # round-loop wall clock (set by _learning_loop): launch.py's
        # multi-process bench reads these to time ROUNDS, excluding
        # startup/compile/diffusion — comparable to run_simulation's
        # post-warm-up clock
        self.learn_t0: float | None = None
        self.learn_t1: float | None = None
        self._server: asyncio.Server | None = None
        self._tasks: list[asyncio.Task] = []
        self._learn_task: asyncio.Task | None = None
        self._crashed = False
        self.finished = asyncio.Event()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def _track_task(self, coro, what: str) -> asyncio.Task:
        """Spawn ``coro`` as a tracked, exception-consuming task.

        A bare ``asyncio.create_task`` keeps no reference — the task
        can be garbage-collected mid-flight and a failure surfaces only
        as "exception was never retrieved" at interpreter exit (the
        round-11 prober class). Tracking in ``self._tasks`` pins the
        task and lets ``stop()`` cancel it; the done-callback prunes
        the list on completion (so reconnect churn doesn't accumulate
        dead tasks) and logs any exception instead of swallowing it.
        """
        task = asyncio.create_task(coro)
        self._tasks.append(task)

        def _done(t: asyncio.Task) -> None:
            if t in self._tasks:
                self._tasks.remove(t)
            if t.cancelled():
                return
            exc = t.exception()
            if exc is not None:
                log.error("node %d background task %r failed: %r",
                          self.idx, what, exc)
                flight.record("node.task_failed", node=self.idx,
                              what=what, error=repr(exc)[:200])

        task.add_done_callback(_done)
        return task

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port,
            ssl=self.tls.server_context() if self.tls else None,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self.membership.beat(self.idx, 0.0)
        if self.shaper is not None:
            # partition-plan time 0 = node start, not first send
            self.shaper.start_clock()
        if self.resume and self.checkpoint_dir:
            self._try_resume()
        self._track_task(self._heartbeat_loop(), "heartbeat_loop")

    def _try_resume(self) -> None:
        """Crash-consistent restart (round 14): adopt this node's own
        periodic checkpoint before any peer contact. A later STATE_SYNC
        only overrides it when the peer's round is NEWER (see
        ``_on_state_sync``). A torn checkpoint is reported loudly
        (the loader names the file) but does not kill the relaunch —
        the node falls back to the plain joiner path."""
        from p2pfl_tpu.federation.checkpoint import load_node_checkpoint

        ln = self.learner
        if (getattr(ln, "state", True) is None
                or getattr(ln, "fns", True) is None):
            ln.init()
        try:
            got = load_node_checkpoint(self.checkpoint_dir, self.idx,
                                       ln.get_parameters())
        except ValueError as e:
            log.warning("node %d resume failed, joining fresh: %s",
                        self.idx, e)
            flight.record("checkpoint.resume_failed", node=self.idx,
                          error=str(e)[:200])
            return
        if got is None:
            flight.record("checkpoint.resume_missing", node=self.idx)
            return
        params, rnd = got
        ln.set_parameters(params)
        self.initialized = True
        self.round = rnd
        self._resume_round = rnd
        flight.record("checkpoint.resume", node=self.idx, round=rnd)

    async def crash(self) -> None:
        """Failure injection (round 11 churn): abrupt teardown WITHOUT
        the STOP announcement — peers must detect the death through
        heartbeat silence and the reconnect-probe machine, exactly as
        for a real process kill. stop() after a crash is a no-op."""
        if self._crashed:
            return
        self._crashed = True
        flight.record("node.crash", node=self.idx, round=self.round)
        self.learning = False
        for t in [self._learn_task, *self._tasks]:
            if t is not None:
                t.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await t
        if self.shaper is not None:
            self.shaper.close()
        for peer in list(self.peers.values()):
            if peer.send_task:
                peer.send_task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await peer.send_task
            if peer.reader_task:
                peer.reader_task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await peer.reader_task
            peer.writer.close()
        self.peers.clear()
        if self._server:
            self._server.close()
        self._release_slot_refs()
        self.finished.set()
        # postmortem: the crash is exactly the moment the ring's
        # churn history stops being reconstructible any other way
        flight.dump(f"node{self.idx}.crash")

    def _release_slot_refs(self) -> None:
        """Return every shm slot this node still references — buffered
        future-round messages and the session's undecoded entries — to
        the host's sidecar arena. Crash/stop teardown MUST route here:
        a restarted node gets a fresh session, and slots stranded by
        the old one would bleed the shared arena dry."""
        if self.sidecar is None:
            return
        for _peer, msg in self._pending_params:
            if msg._slot is not None:
                self.sidecar.release(msg._slot)
                msg._slot = None
        release = getattr(self.session, "release_entries", None)
        if release is not None:
            release()

    # ------------------------------------------------------------------
    # partition control (round 14): the fault driver's scripted cut
    # ------------------------------------------------------------------
    def apply_partition(self, groups: list) -> None:
        """Sever every link crossing the ``groups`` cut, as seen from
        this node: outbound frames to peers in OTHER groups are dropped
        at the write layer. The driver applies the same cut on every
        node, so the sever is symmetric. A node absent from all groups
        is unaffected. Flows through membership as a ``partition``
        FaultEvent → Events.LINK_PARTITIONED + flight record."""
        mine = next((g for g in groups if self.idx in g), None)
        if mine is None:
            return
        others = {int(n) for g in groups if g is not mine for n in g}
        self._severed |= others - {self.idx}
        flight.record("node.partition", node=self.idx, round=self.round,
                      severed=sorted(self._severed))
        self.membership.apply_fault(
            FaultEvent(node=self.idx, kind="partition", groups=groups))

    def heal_partition(self) -> None:
        """The heal observation: reconnect all scripted cuts and grant
        eviction amnesty. Membership clears every sticky departure and
        re-arms an immediately-due probe; the existing probe machinery
        then redials each healed peer (``_peer_addrs``) and its first
        beat resurrects it — no operator action, no new merge math
        (the minority's model re-enters as a staleness-discounted
        ``add_model`` contribution via the round-11 stale-fold path)."""
        if not self._severed:
            return
        healed = sorted(self._severed)
        self._severed.clear()
        flight.record("node.heal", node=self.idx, round=self.round,
                      healed=healed)
        self.membership.apply_fault(FaultEvent(node=self.idx, kind="heal"))

    def _on_netem_transition(self, kind: str, groups: list) -> None:
        """Shaper-scheduled windows (NetworkConfig.partitions) reuse
        the same observation path as driver-scripted cuts. The shaper
        already drops the frames; here only the membership event +
        amnesty bookkeeping run. Severed-set updates are skipped for
        ``partition`` (the shaper owns the drop), but ``heal`` must
        still clear driver-applied state and trigger amnesty."""
        if kind == "partition":
            self.membership.apply_fault(
                FaultEvent(node=self.idx, kind="partition", groups=groups))
        else:
            self._severed.clear()
            self.membership.apply_fault(
                FaultEvent(node=self.idx, kind="heal"))

    def _link_severed(self, node: int) -> bool:
        """True while an open cut (driver- or shaper-scheduled)
        separates this node from ``node``."""
        if node in self._severed:
            return True
        return self.shaper is not None and self.shaper.severed_now(node)

    async def stop(self) -> None:
        if self._crashed:
            return
        # announce departure so peers drop us immediately instead of
        # waiting out the heartbeat timeout (Stop_cmd semantics).
        # Per-peer time bound, sent concurrently: one peer with a full
        # TCP send buffer must neither wedge our shutdown on drain()
        # nor starve the announcement to the healthy peers behind it.
        stop_msg = self._sign(Message(MsgType.STOP, self.idx))
        self.dedup.check_and_add(stop_msg.msg_id)

        async def announce(peer: PeerState) -> None:
            # routed through the peer's send lane (never a concurrent
            # direct write — that could interleave mid-frame with the
            # drain task); flush waits on the queue, bounded per peer
            with contextlib.suppress(Exception):
                await asyncio.wait_for(self._write(peer, stop_msg),
                                       timeout=1.0)
                if peer.send_q is not None:
                    await asyncio.wait_for(peer.send_q.join(), timeout=1.0)

        await asyncio.gather(
            *(announce(p) for p in list(self.peers.values()))
        )
        for t in [self._learn_task, *self._tasks]:
            if t is not None:
                t.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await t
        if self.shaper is not None:
            self.shaper.close()  # in-flight shaped messages are lost
        for peer in list(self.peers.values()):
            if peer.send_task:
                peer.send_task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await peer.send_task
            if peer.reader_task:
                peer.reader_task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await peer.reader_task
            peer.writer.close()
            with contextlib.suppress(Exception):
                await asyncio.wait_for(peer.writer.wait_closed(), timeout=1.0)
        self.peers.clear()
        if self._server:
            self._server.close()
            # NOT wait_closed(): on py3.12 it blocks until every peer
            # connection (including ones owned by other nodes) is gone
        self._release_slot_refs()

    def _transport_idx(self, writer: asyncio.StreamWriter) -> int | None:
        """The node index the connection's TLS certificate vouches for
        (None on plaintext federations)."""
        from p2pfl_tpu.p2p.tls import peer_index

        return peer_index(writer.get_extra_info("peercert"))

    def _hello_ok(self, hello: Message,
                  writer: asyncio.StreamWriter) -> bool:
        """CONNECT binding: with TLS on, the index claimed in the hello
        must be the one in the connection's certificate CN — otherwise
        member A could register a connection as member B and have every
        direct frame on it attributed to B. The hello's origin
        signature is checked too, binding its body (the dial-back port)
        to the same identity."""
        if self.tls is None:
            return True
        cert_idx = self._transport_idx(writer)
        if (cert_idx is not None and cert_idx == int(hello.sender)
                and self._verify_origin(hello)):
            return True
        log.warning(
            "node %d rejecting CONNECT: hello claims %s but certificate "
            "CN says %s", self.idx, hello.sender, cert_idx,
        )
        return False

    def _hello_body(self) -> dict:
        """CONNECT hello body: dial-back port, supported wire dtypes,
        and — when this node is entering a RUNNING federation — the
        live-join declaration ``"jr"`` (the last round it knows). The
        established side answers a ``"jr"`` hello with STATE_SYNC."""
        body = {"port": self.port, "wd": list(WIRE_DTYPES)}
        if self.joiner:
            body["jr"] = self.round
        return body

    async def connect_to(self, host: str, port: int) -> None:
        """Dial a neighbor (base_node.py connect_to)."""
        reader, writer = await asyncio.open_connection(
            host, port,
            ssl=self.tls.client_context() if self.tls else None,
        )
        await write_message(
            writer,
            self._sign(Message(MsgType.CONNECT, self.idx,
                               self._hello_body())),
        )
        hello = await read_message(reader)
        if not self._hello_ok(hello, writer):
            writer.close()
            raise ConnectionError("peer hello does not match its certificate")
        self._record_peer_wire(hello)
        peer = self._register_peer(int(hello.sender), reader, writer)
        self._on_hello_extras(peer, hello, host=host)
        log.debug("node %d connected to %d", self.idx, peer.idx)

    async def _on_connection(self, reader, writer) -> None:
        try:
            hello = await read_message(reader)
        except (asyncio.IncompleteReadError, ValueError):
            writer.close()
            return
        if hello.type is not MsgType.CONNECT or not self._hello_ok(
            hello, writer
        ):
            writer.close()
            return
        await write_message(
            writer,
            self._sign(Message(MsgType.CONNECT, self.idx,
                               self._hello_body())),
        )
        self._record_peer_wire(hello)
        peer = self._register_peer(int(hello.sender), reader, writer)
        self._on_hello_extras(peer, hello)

    def _on_hello_extras(self, peer: PeerState, hello: Message,
                         host: str | None = None) -> None:
        """Round-11 CONNECT extensions, applied once the connection is
        registered: remember the peer's dial-back address (reconnect
        probes redial it on heartbeat silence), and honor a live-join
        declaration ("jr") — clear any sticky departure so the joiner
        re-enters membership, and answer with the current model."""
        port = hello.body.get("port")
        if host is None:
            peername = peer.writer.get_extra_info("peername")
            host = peername[0] if peername else None
        if host is not None and port is not None:
            self._peer_addrs[peer.idx] = (host, int(port))
        if hello.body.get("jr") is None:
            return
        self.membership.apply_fault(
            FaultEvent(node=peer.idx, round=self.round, kind="join"))
        if self._tracer.enabled:
            self._tracer.count("peer_join")
        # Answer while learning OR after the run ended: a joiner that
        # dials in after the last round would otherwise wait forever
        # for a model that nobody is going to push. A finished node
        # replies with its FINAL state (round == total_rounds), so the
        # late joiner adopts the converged model, fast-forwards past
        # the whole schedule, and terminates immediately.
        if self.initialized and (self.learning or self.finished.is_set()):
            self._track_task(self._send_state_sync(peer), "state_sync")

    async def _send_state_sync(self, peer: PeerState) -> None:
        """Answer a joiner's hello with the current global model in
        CHECKPOINT format (federation.checkpoint.pack_model — the join
        path and the restart-from-disk path share one serialization)
        plus the run parameters it needs to fast-forward."""
        from p2pfl_tpu.federation.checkpoint import pack_model

        with self._tracer.span("p2p.state_sync", lane=self._lane,
                               args={"peer": peer.idx,
                                     "round": self.round}):
            flight.record("checkpoint.state_sync_out", node=self.idx,
                          peer=peer.idx, round=self.round)
            blob = pack_model(self.learner.get_parameters(), self.round)
            msg = self._sign(
                Message(MsgType.STATE_SYNC, self.idx,
                        {"round": self.round,
                         "rounds": self.total_rounds,
                         "epochs": self.epochs,
                         "leader": self.leader},
                        payload=blob)
            )
            try:
                await self._write(peer, msg)
            except (ConnectionError, RuntimeError):
                self._drop_conn(peer)

    def _record_peer_wire(self, hello: Message) -> None:
        """Remember the wire precisions the peer's CONNECT hello
        advertised ("wd"). Absent on pre-quantization peers — they are
        recorded as supporting nothing reduced, so every PARAMS send
        that targets them negotiates down to the f32 v1 envelope."""
        self._peer_wire[int(hello.sender)] = tuple(
            str(d) for d in hello.body.get("wd", ())
        )
        # once per CONNECT hello (NOT per send — _wire_dtype_for is hot)
        flight.record("wire.negotiate", node=self.idx,
                      peer=int(hello.sender),
                      peer_wd=list(self._peer_wire[int(hello.sender)]),
                      own=str(self.wire_dtype))

    def _register_peer(self, idx: int, reader, writer) -> PeerState:
        peer = PeerState(idx=idx, writer=writer)
        if self.shaper is None:
            # egress lane: bounded queue + one drain task per peer (the
            # shaped path has its own per-link queues in netem.py, so
            # only one writer owner ever exists per connection)
            peer.send_q = asyncio.Queue(
                maxsize=max(self.protocol.send_queue_depth, 1)
            )
            peer.send_task = asyncio.create_task(self._drain_send_q(peer))
        peer.reader_task = asyncio.create_task(self._read_loop(peer, reader))
        self.peers[idx] = peer
        self.membership.beat(idx)
        # tracked: protects against task GC and lets stop() cancel a
        # sync still draining a large init-weights write
        self._track_task(self._sync_peer(peer), "sync_peer")
        return peer

    async def _sync_peer(self, peer: PeerState) -> None:
        """Bring a NEW connection up to date with sticky state it may
        have missed as a one-shot flood — the deterministic replacement
        for the reference's paced Gossiper re-broadcast thread
        (gossiper.py:66-112): a late joiner learns our role, that
        learning is underway, and our round progress immediately."""

        async def send(msg: Message) -> None:
            # register our own msg_id first (as broadcast() does) so
            # the flood can't echo back and be re-processed/re-forwarded
            self._sign(msg)
            self.dedup.check_and_add(msg.msg_id)
            await self._write(peer, msg)

        try:
            await send(Message(MsgType.ROLE, self.idx, {"role": self.role}))
            if self.learning:
                await send(
                    Message(MsgType.START_LEARNING, self.idx,
                            {"rounds": self.total_rounds,
                             "epochs": self.epochs,
                             "leader": self.leader})
                )
                if self.initialized:
                    await send(Message(MsgType.MODEL_INITIALIZED, self.idx))
                    # a joiner that missed the initial diffusion gets
                    # the weights directly (diffusion loops have long
                    # exited by now)
                    await self._send_params(
                        peer, self.learner.get_parameters(), (), 1,
                        init=True,
                    )
                await send(
                    Message(MsgType.MODELS_READY, self.idx,
                            {"round": self.round})
                )
        except (ConnectionError, RuntimeError):
            self._drop_conn(peer)

    # ------------------------------------------------------------------
    # receive path
    # ------------------------------------------------------------------
    def _drop_conn(self, peer: PeerState) -> None:
        """Remove a dead connection — but only if it is STILL the
        registered one; a redialed replacement must not be evicted by
        the old connection's dying task."""
        if self.peers.get(peer.idx) is peer:
            self.peers.pop(peer.idx, None)
        if peer.send_task is not None and not peer.send_task.done():
            peer.send_task.cancel()
        if peer.send_q is not None:
            # discard queued frames and wake any producer blocked on a
            # full queue — the lane is dead, nothing will drain it
            while True:
                try:
                    peer.send_q.get_nowait()
                except asyncio.QueueEmpty:
                    break
                with contextlib.suppress(ValueError):
                    peer.send_q.task_done()

    def _teardown_conn(self, conn: PeerState) -> None:
        """Full lane teardown (send task included — an orphaned drain
        task parked on get() would outlive the run)."""
        self._drop_conn(conn)
        if conn.reader_task:
            conn.reader_task.cancel()
        conn.writer.close()

    def _evict_dead(self, node: int) -> None:
        """Reconnect budget exhausted: the crash is final as far as
        this node is concerned — same teardown as an explicit STOP, so
        round barriers and gossip stop waiting on the corpse. A later
        live re-join ("jr" hello) clears the sticky departure."""
        log.info("node %d evicting unreachable peer %d", self.idx, node)
        if self._tracer.enabled:
            self._tracer.count("peer_evicted")
        self.membership.evict(node)
        self.progress.pop(node, None)
        self.peer_roles.pop(node, None)
        conn = self.peers.pop(node, None)
        if conn is not None:
            self._teardown_conn(conn)
        self._secagg_on_evict(node)
        flight.dump(f"node{self.idx}.evicted_peer{node}")

    def _secagg_on_evict(self, node: int) -> None:
        """Dropout recovery: record the eviction and reveal this
        node's per-round pair seed against the corpse so every
        aggregator can reconstruct the dead pair's mask streams
        (Bonawitz reveal — unmasks nothing of any survivor)."""
        if (self.masker is None
                or self.masker.round_num is None
                or node not in self.masker.members
                or node in self.masker.evicted):
            return
        self.masker.note_evicted(node)
        seed = self.masker.reveal_share(node)
        flight.record("secagg.reveal", node=self.idx, dead=node,
                      round=self.masker.round_num)
        self._track_task(
            self.broadcast(Message(
                MsgType.SECAGG_SHARE, self.idx,
                {"dead": int(node),
                 "round": int(self.masker.round_num),
                 "seed": int(seed)},
            )),
            "secagg_share",
        )

    async def _drain_send_q(self, peer: PeerState) -> None:
        """Backpressure writer for one connection: drains the peer's
        bounded send queue in FIFO order. The queue only sees traffic
        when the lane is congested (see _write's idle-lane fast path),
        so this task is parked on get() in the steady state. A write
        failure drops the connection; the task then keeps consuming
        (discarding) so producers blocked on put() unwedge until
        stop()/drop cancels it."""
        dead = False
        while True:
            msg = await peer.send_q.get()
            try:
                if not dead:
                    peer.draining = True
                    try:
                        await write_message(peer.writer, msg)
                        self._count_tx(peer, msg)
                    except (ConnectionError, RuntimeError, OSError):
                        dead = True
                        self._drop_conn(peer)
                    finally:
                        peer.draining = False
            finally:
                with contextlib.suppress(ValueError):
                    peer.send_q.task_done()

    def _count_rx(self, peer: PeerState, msg: Message) -> None:
        self.bytes_in += msg._wire_bytes
        pb = self.peer_bytes_in
        pb[peer.idx] = pb.get(peer.idx, 0) + msg._wire_bytes
        tr = self._tracer
        if tr.enabled:
            tr.count(f"rx_bytes/peer{peer.idx}", msg._wire_bytes)
            tr.count(f"rx_msgs/{msg.type.value}")

    def _count_tx(self, peer: PeerState, msg: Message) -> None:
        n = msg.wire_size()
        self.bytes_out += n
        pb = self.peer_bytes_out
        pb[peer.idx] = pb.get(peer.idx, 0) + n
        tr = self._tracer
        if tr.enabled:
            tr.count(f"tx_bytes/peer{peer.idx}", n)
            tr.count(f"tx_msgs/{msg.type.value}")

    async def _read_loop(self, peer: PeerState, reader) -> None:
        # with a sidecar, eligible PARAMS payloads land straight in the
        # shm arena (read_message's slot_sink) — the loop sees only the
        # header + a slot id, never the payload bytes
        sink = self._slot_sink if self.sidecar is not None else None
        try:
            while True:
                msg = await read_message(reader, slot_sink=sink)
                self._count_rx(peer, msg)
                await self._dispatch(peer, msg)
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            self._drop_conn(peer)

    def _slot_sink(self, obj: dict, pl: int):
        """Divert decision for read_message: lease an arena slot for
        this payload, or None to keep the heap-bytes path. Eligible:
        unsigned PARAMS with contributor/weight metadata in the body
        ("c"/"w" — all session bookkeeping runs off the header), not
        init diffusion, not on a proxy (relays must re-ship the
        payload), and not a full-model adoption while this session
        waits (adoption decodes, so it stays on the heap)."""
        if obj.get("t") != MsgType.PARAMS.value or obj.get("g"):
            return None
        body = obj.get("b") or {}
        if body.get("init") or body.get("c") is None or body.get("w") is None:
            return None
        if self.role == "proxy":
            return None
        if self.session.waiting and body.get("aggregated"):
            return None
        lease = self.sidecar.lease(pl)
        if lease is None:
            return None  # arena exhausted/oversized: inline fallback
        slot, mv = lease
        return slot, mv, self.sidecar.release

    async def _dispatch(self, peer: PeerState, msg: Message) -> None:
        if not (0 <= msg.sender < self.n_nodes):
            # wire-supplied index guards every handler that indexes
            # membership/progress arrays — and garbage isn't forwarded
            return
        if msg.type in GOSSIPED:
            # peek-dedup first (duplicates cost no crypto), verify,
            # REGISTER ONLY WHAT VERIFIED. Registering before verifying
            # would let a malicious relay poison an id: forward a
            # corrupted copy of a mid-flood frame ahead of the honest
            # paths and the genuine message gets dropped as a duplicate
            # everywhere downstream — a one-member censorship primitive.
            if self.dedup.seen(msg.msg_id):
                return  # already processed — at-most-once
            if not self._verify_origin(msg):
                return  # forged: not processed, not forwarded, NOT SEEN
            self.dedup.check_and_add(msg.msg_id)
            # Relay damping on DECLARED full meshes (see __init__),
            # PERIODIC flood types only: the origin's direct broadcast
            # already reached everyone, so relays are pure redundancy —
            # but a DEAD A-B link with both ends otherwise fully
            # connected is invisible to the relaying third party C
            # (C still has n-1 peers), and C's relay is the only path
            # keeping A/B from falsely evicting each other. The relay
            # probability scales with the mesh so the EXPECTED number
            # of repair relays per beat stays ~1 regardless of n:
            # p = min(1, 1/(n-2)) over the n-2 third parties. At n=3
            # the lone third party always relays (a flat rate would
            # leave a severed A-B pair waiting ~1/p beats per crossing
            # and false-evicting inside node_timeout_s); at n=24 this
            # is ~0.045 — the measured relay traffic stays >95% gone.
            # One-shot floods (STOP, votes, leadership) always relay.
            # The peer-count guard restores full relaying whenever this
            # node's own links are down.
            relay_p = min(1.0, 1.0 / max(self.n_nodes - 2, 1))
            damped = (self.full_mesh
                      and msg.type in PERIODIC_FLOODS
                      and len(self.peers) >= self.n_nodes - 1
                      and self._rng.random() >= relay_p)
            if not damped:
                await self._forward(msg, exclude=peer.idx,
                                    limit=self.protocol.gossip_fanout)
        elif (msg.type in (MsgType.PARAMS, MsgType.STATE_SYNC)
              and not self._verify_origin(msg)):
            return
        t = msg.type
        if t is MsgType.BEAT:
            # sequence fence: the beat counter rides inside the signed
            # bytes, so a replayed BEAT (after its msg_id evicts from
            # the bounded dedup ring) cannot keep a crashed node alive
            # in membership — only strictly newer beats count
            seq = int(msg.body.get("n", 0))
            if seq > self._beat_seen.get(msg.sender, -1):
                self._beat_seen[msg.sender] = seq
                self.membership.beat(msg.sender)
        elif t is MsgType.ROLE:
            self.peer_roles[msg.sender] = msg.body["role"]
        elif t is MsgType.START_LEARNING:
            # finished-run fence: a replayed genuine START_LEARNING
            # must not restart a completed federation (and reset the
            # leader/history from its stale body)
            if not self.learning and not self.finished.is_set():
                self._start_learning(
                    msg.body["rounds"], msg.body["epochs"],
                    leader=msg.body.get("leader"),
                )
        elif t is MsgType.STOP_LEARNING:
            self._stop_learning()
        elif t is MsgType.METRICS:
            self.peer_metrics[msg.sender] = dict(msg.body)
        elif t is MsgType.STOP:
            # msg.sender left the federation (Stop_cmd semantics):
            # evict everywhere — membership (no timeout wait), progress
            # (round barriers), and the direct connection if one exists
            gone_id = int(msg.sender)
            self.membership.evict(gone_id)
            self.progress.pop(gone_id, None)
            self.peer_roles.pop(gone_id, None)
            conn = self.peers.pop(gone_id, None)
            if conn is not None:
                self._teardown_conn(conn)
            self._secagg_on_evict(gone_id)
        elif t is MsgType.PARAMS:
            await self._on_params(peer, msg)
        elif t is MsgType.STATE_SYNC:
            await self._on_state_sync(msg)
        elif t is MsgType.MODELS_AGGREGATED:
            # monotonic like MODELS_READY: flood paths (and post-
            # eviction replays) can deliver an older snapshot after a
            # newer one; within a round coverage only grows, so stale
            # rounds are ignored and same-round sets union
            pr = self._progress(msg.sender)
            r = int(msg.body.get("round", 0))
            if r > pr.agg_round:
                pr.models_aggregated = set(msg.body["contributors"])
                pr.agg_round = r
            elif r == pr.agg_round:
                pr.models_aggregated |= set(msg.body["contributors"])
        elif t is MsgType.MODEL_INITIALIZED:
            self._progress(msg.sender).initialized = True
        elif t is MsgType.MODELS_READY:
            pr = self._progress(msg.sender)
            # monotonic: flood paths can deliver an older snapshot (a
            # relayed _sync_peer message) after a newer one — a
            # regression would re-block the round barrier
            pr.ready_round = max(pr.ready_round, int(msg.body["round"]))
        elif t is MsgType.VOTE_TRAIN_SET:
            r = int(msg.body["round"])
            if r >= self.round:  # stale-round ballots are dead voters
                self._votes.setdefault(r, {})[msg.sender] = tuple(
                    int(c) for c in msg.body["candidates"]
                )
        elif t is MsgType.SECAGG_SHARE:
            # survivor's reveal for an evicted member's pair: file it
            # with the masker (stale-round shares are pruned at the
            # next begin_round), and mirror the eviction locally —
            # which also reveals OUR pair seed against the corpse once,
            # so reveals propagate quorum-wide even before every
            # survivor's own probe gives up on the dead node
            if self.masker is not None:
                self.masker.add_share(
                    int(msg.sender), int(msg.body["dead"]),
                    int(msg.body["round"]), int(msg.body["seed"]),
                )
                if int(msg.body["round"]) == self.masker.round_num:
                    self._secagg_on_evict(int(msg.body["dead"]))
        elif t is MsgType.TRANSFER_LEADERSHIP:
            # round fencing: the dedup ring is bounded, so a recorded
            # genuine transfer could be re-flooded rounds later after
            # its id evicts — a stale token must not reset leadership
            # (the body's round is inside the signed bytes)
            if int(msg.body.get("round", self.round)) >= self.round:
                self.leader = int(msg.body["to"])
                self.leader_history.append(self.leader)

    async def _on_params(self, peer: PeerState, msg: Message) -> None:
        """Traced entry: a tc-stamped frame (sender was tracing) is
        handled under a ``p2p.rx`` span parented to the sender's tx
        span — the cross-process edge — and its send→receive wall
        delta accrues into the round's wire seconds (skew-clamped; the
        critpath analyzer does the proper pairwise skew correction
        offline). Untraced (or legacy) frames skip straight through."""
        tr = self._tracer
        if tr.enabled and msg.tc is not None:
            rx_ns = time.time_ns()
            lat_s = (rx_ns - int(msg.tc[2])) / 1e9
            if 0.0 < lat_s < 60.0:
                self._cp_wire_s += lat_s
            with tr.span(
                "p2p.rx", lane=self._lane,
                args={"parent": msg.tc[1], "trace": msg.tc[0],
                      "tx_ns": int(msg.tc[2]), "rx_ns": rx_ns,
                      "from": msg.sender,
                      "round": int(msg.body.get("round", -1))},
            ):
                return await self._on_params_inner(peer, msg)
        return await self._on_params_inner(peer, msg)

    async def _on_params_inner(self, peer: PeerState,
                               msg: Message) -> None:
        # sender's tx span id: threads into session.add_model spans so
        # the ingest parents to the send even across a buffered replay
        cp = (msg.tc[1]
              if self._tracer.enabled and msg.tc is not None else None)
        if msg.body.get("init"):
            # whoever pushes initial weights evidently HAS the model —
            # count them initialized even if their MODEL_INITIALIZED
            # flood was lost or predates our connection, or our own
            # diffusion loop would chase their ack until its deadline
            self._progress(msg.sender).initialized = True
            if not self.initialized:
                payload = decode_parameters(msg.payload)
                self.learner.set_parameters(payload.params)
                self.initialized = True
                await self.broadcast(
                    Message(MsgType.MODEL_INITIALIZED, self.idx)
                )
                # relay the initial weights onward — on multi-hop
                # topologies (ring/random) the starter only reaches its
                # direct neighbors, so every receiver re-diffuses
                # (node.py:702-724 diffusion-until-initialized)
                self._track_task(self._diffuse_initial(),
                                 "diffuse_initial")
            return
        if self.role == "proxy" and msg.msg_id:
            # PROXY: relay weight traffic onward so it bridges nodes
            # with no direct link (node.py:492-515, 999-1017 — the
            # reference stores and re-gossips on a timer; here the
            # relay is immediate, deduped by msg_id so two proxies
            # can't ping-pong the same message)
            if self.dedup.check_and_add(msg.msg_id):
                await self._forward(msg, exclude=peer.idx)
        # round fencing: a round-r model must never enter a round-r'
        # session (a stale full aggregate would instantly "cover" a
        # fresh session and erase this round's training). Messages for
        # a future round — or for the current round while we are still
        # in the previous round's barrier (self.round is incremented
        # BEFORE the barrier, so the session is stale there) — are
        # buffered and replayed at that round's start.
        msg_round = int(msg.body.get("round", self.round))
        if msg_round > self.round or (
            msg_round == self.round and not self._round_active
        ):
            self._pending_params.append((peer, msg))
            return
        if msg_round < self.round:
            # Async elasticity (round 11): a straggler's update for a
            # RECENT round folds into the current session with a
            # staleness-discounted weight (1/(1+s)^beta, applied inside
            # add_model) instead of being dropped — FedBuff-style late
            # inclusion. Only raw contributions qualify: a stale FULL
            # aggregate is last round's RESULT, and adopting it would
            # instantly cover the fresh session and erase this round's
            # training (the exact hazard the round fence exists for).
            staleness = self.round - msg_round
            if (self.session.async_mode and self._round_active
                    and not self.session.waiting
                    and not msg.body.get("aggregated")):
                if msg._slot is not None:
                    # slot-native stale fold: staleness discounts the
                    # WEIGHT (params-agnostic), and the header's
                    # "c"/"w" metadata is all the session needs —
                    # the payload stays undecoded in the arena
                    contribs = frozenset(
                        int(c) for c in msg.body.get("c") or ())
                    ts = self.session.train_set
                    if contribs and not (ts and contribs >= ts):
                        covered = self.session.add_slot(
                            msg._slot, msg._slot_len, contribs,
                            int(msg.body.get("w", 1)),
                            staleness=staleness, parent=cp,
                        )
                        msg._slot = None  # session owns it now
                        if self._tracer.enabled:
                            self._tracer.count("stale_params_folded")
                        if covered:
                            await self.broadcast(
                                Message(
                                    MsgType.MODELS_AGGREGATED, self.idx,
                                    {"contributors": sorted(covered),
                                     "round": self.round},
                                )
                            )
                        return
                    self.sidecar.release(msg._slot)
                    msg._slot = None
                    return
                if (self.sidecar is not None and "c" in msg.body
                        and "w" in msg.body):
                    # arena was exhausted at the sink: the payload is
                    # loop-side bytes, but it still folds UNDECODED —
                    # add_blob retries the lease or queues the blob
                    contribs = frozenset(
                        int(c) for c in msg.body.get("c") or ())
                    ts = self.session.train_set
                    if contribs and not (ts and contribs >= ts):
                        covered = self.session.add_blob(
                            msg.payload, contribs,
                            int(msg.body.get("w", 1)),
                            staleness=staleness, parent=cp,
                        )
                        if self._tracer.enabled:
                            self._tracer.count("stale_params_folded")
                        if covered:
                            await self.broadcast(
                                Message(
                                    MsgType.MODELS_AGGREGATED, self.idx,
                                    {"contributors": sorted(covered),
                                     "round": self.round},
                                )
                            )
                    return
                self.loop_payload_touch_bytes += len(msg.payload)
                payload = decode_parameters(msg.payload)
                contribs = frozenset(payload.contributors)
                ts = self.session.train_set
                if contribs and not (ts and contribs >= ts):
                    covered = self.session.add_model(
                        payload.params, payload.contributors,
                        payload.weight, staleness=staleness, parent=cp,
                    )
                    if self._tracer.enabled:
                        self._tracer.count("stale_params_folded")
                    if covered:
                        await self.broadcast(
                            Message(
                                MsgType.MODELS_AGGREGATED, self.idx,
                                {"contributors": sorted(covered),
                                 "round": self.round},
                            )
                        )
                return
            if msg._slot is not None:
                self.sidecar.release(msg._slot)
                msg._slot = None
            return
        if self.session.waiting and not msg.body.get("aggregated"):
            if msg._slot is not None:
                self.sidecar.release(msg._slot)
                msg._slot = None
            return  # waiting nodes adopt only a *finished* aggregate
        if msg._slot is not None:
            if self.session.waiting:
                # buffered-then-replayed aggregate meeting a session
                # that turned waiting (e.g. voted out between rounds):
                # adoption needs the decoded tree. Rare, counted — the
                # zero-copy pin tolerates it only because the sink
                # never diverts adoption payloads on the live path.
                n = msg._slot_len
                self.loop_payload_touch_bytes += n
                blob = bytes(self.sidecar.view(msg._slot, n))
                self.sidecar.release(msg._slot)
                msg._slot = None
                payload = decode_parameters(blob)
                covered = self.session.add_model(
                    payload.params, payload.contributors, payload.weight,
                    parent=cp,
                )
            else:
                covered = self.session.add_slot(
                    msg._slot, msg._slot_len,
                    tuple(int(c) for c in msg.body.get("c") or ()),
                    int(msg.body.get("w", 1)), parent=cp,
                )
                msg._slot = None  # session owns it now
        elif (self.sidecar is not None and not self.session.waiting
                and "c" in msg.body and "w" in msg.body):
            # sink lease failed (arena momentarily exhausted): fold the
            # raw blob without decoding — same undecoded plane, just
            # via the descriptor queue instead of a slot
            covered = self.session.add_blob(
                msg.payload,
                tuple(int(c) for c in msg.body.get("c") or ()),
                int(msg.body.get("w", 1)), parent=cp,
            )
        else:
            self.loop_payload_touch_bytes += len(msg.payload)
            payload = decode_parameters(msg.payload)
            covered = self.session.add_model(
                payload.params, payload.contributors, payload.weight,
                parent=cp,
            )
        if covered:
            await self.broadcast(
                Message(
                    MsgType.MODELS_AGGREGATED, self.idx,
                    {"contributors": sorted(covered), "round": self.round},
                )
            )

    async def _on_state_sync(self, msg: Message) -> None:
        """Joiner side of the live-join handshake: adopt the
        established node's model (checkpoint format) and fast-forward
        to its round, then enter the running federation. Only declared
        joiners act on STATE_SYNC, the round fast-forward never rewinds,
        and the model is adopted at most once (first answer wins — the
        init-params catch-up from _sync_peer may already have landed).

        A checkpoint-resumed relaunch (round 14) arrives already
        initialized with its disk state at ``_resume_round``; the first
        STATE_SYNC then decides ONCE which side is newer — the peer's
        model is adopted only when its round is strictly ahead of the
        checkpoint, otherwise the (at least as fresh) disk state
        stands."""
        if not self.joiner:
            return
        rnd = int(msg.body.get("round", 0))
        adopt_over_resume = (self.initialized
                             and self._resume_round is not None
                             and rnd > self._resume_round)
        if self._resume_round is not None and not self.learning:
            flight.record("checkpoint.resume_decision", node=self.idx,
                          checkpoint_round=self._resume_round,
                          sync_round=rnd, adopt_sync=adopt_over_resume)
            self._resume_round = None  # first answer decides
        flight.record("checkpoint.state_sync_in", node=self.idx,
                      peer=int(msg.sender), round=rnd)
        with self._tracer.span("p2p.join", lane=self._lane,
                               args={"round": rnd, "from": msg.sender}):
            if rnd > self.round:
                if self.learning:
                    # defer for the WHOLE round body, not just the
                    # active-session window: _train_round awaits in its
                    # vote phase before _round_active is set, and a
                    # direct jump there would let the body's trailing
                    # round increment skip past the jump target. The
                    # learning loop applies the target at the next
                    # round boundary.
                    self._join_round_target = max(
                        self._join_round_target or 0, rnd)
                else:
                    self.round = rnd
            if not self.initialized or adopt_over_resume:
                ln = self.learner
                if (getattr(ln, "state", True) is None
                        or getattr(ln, "fns", True) is None):
                    ln.init()
                from p2pfl_tpu.federation.checkpoint import unpack_model

                try:
                    params, _ = unpack_model(
                        msg.payload, ln.get_parameters())
                except ValueError:
                    log.warning(
                        "node %d: STATE_SYNC blob from %d does not "
                        "match the local model", self.idx, msg.sender)
                    return
                ln.set_parameters(params)
                self.initialized = True
                await self.broadcast(
                    Message(MsgType.MODEL_INITIALIZED, self.idx))
            if self._tracer.enabled:
                self._tracer.count("join_state_sync")
            if not self.learning and not self.finished.is_set():
                self._start_learning(
                    int(msg.body.get("rounds", 0)),
                    int(msg.body.get("epochs", 1)),
                    leader=msg.body.get("leader"),
                )

    # ------------------------------------------------------------------
    # send path
    # ------------------------------------------------------------------
    def _sign(self, msg: Message) -> Message:
        """Origin-sign a self-originated message (no-op without TLS).
        Forwarded messages keep the ORIGIN's signature — only messages
        this node creates pass through here."""
        if self._signer is not None and not msg.sig:
            msg.sig = self._signer.sign(msg.signing_bytes())
            msg.cert = self._signer.cert_pem
            msg._head = None  # signature changes the framed-header memo
        return msg

    def _verify_origin(self, msg: Message) -> bool:
        """True iff the message's origin signature is valid for the
        claimed sender (always true on plaintext federations)."""
        if self._verifier is None:
            return True
        tr = self._tracer
        # a tc-stamped frame parents its verify span to the sender's
        # tx span (args built only on the traced path)
        args = None
        if tr.enabled and msg.tc is not None:
            args = {"parent": msg.tc[1], "from": msg.sender}
        with tr.span("p2p.verify", lane=self._lane, args=args):
            ok = self._verifier.verify(
                msg.cert, msg.sig, msg.signing_bytes(), msg.sender
            )
        if ok:
            if tr.enabled:
                tr.count("verify_ok")
            return True
        if tr.enabled:
            tr.count("verify_fail")
        log.warning(
            "node %d dropping %s with unverifiable origin claim sender=%d",
            self.idx, msg.type.value, msg.sender,
        )
        return False

    async def broadcast(self, msg: Message, exclude: int | None = None) -> None:
        self._sign(msg)
        if msg.type in GOSSIPED:
            self.dedup.check_and_add(msg.msg_id)
        await self._forward(msg, exclude)

    def _try_fast_write(self, peer: PeerState, msg: Message) -> bool:
        """Idle-lane fast path: when nothing is queued, the drain task
        is parked, and the transport buffer is under the high-water
        mark, write synchronously — no queue hop, no task wakeup, not
        even a drain() await (flow control is the buffer check itself;
        measured: routing EVERY frame through the queue cost ~17% on
        the 24-node control-bound round and ~38% on the payload-bound
        one). The checks and the write run without an await between
        them, so the sole-writer-per-connection invariant holds.
        Returns True when the frame was handled (written or the
        connection dropped), False when the caller must queue."""
        if peer.idx in self._severed:
            return True  # scripted partition: the frame dies on the cut
        q = peer.send_q
        if (q is None or not q.empty() or peer.draining
                or self.peers.get(peer.idx) is not peer):
            return False
        tr = peer.writer.transport
        if tr.is_closing() or tr.get_write_buffer_size() >= _FAST_LANE_MAX:
            return False
        try:
            peer.writer.writelines(msg.wire_segments())
        except (ConnectionError, RuntimeError, OSError):
            self._drop_conn(peer)
        else:
            self._count_tx(peer, msg)
        return True

    async def _write(self, peer: PeerState, msg: Message) -> None:
        """Single egress point: the idle-lane fast write when the
        peer's lane is clear, else enqueue onto its bounded send lane
        (the drain task owns the socket under congestion), or the link
        shaper's delayed/lossy schedule when network emulation is on.
        Blocks only when THIS peer's bounded queue is full
        (backpressure); never raises for delivery errors — those
        surface on the drain/link worker, which drops the connection."""
        if peer.idx in self._severed:
            return  # scripted partition (fault driver): symmetric drop
        if self.shaper is not None:
            await self.shaper.send(peer, msg)
        elif self._try_fast_write(peer, msg):
            return
        elif peer.send_q is not None and self.peers.get(peer.idx) is peer:
            tr = self._tracer
            if tr.enabled:
                # queue depth AT enqueue (incl. this frame): the lane's
                # congestion high-water mark — a depth pinned at the
                # bound means the bounded queue, not the socket, paces
                # this peer's egress
                tr.high_water(f"send_q_depth/peer{peer.idx}",
                              peer.send_q.qsize() + 1)
            await peer.send_q.put(msg)
        else:
            # pre-registration writes (none today) fall through direct
            await write_message(peer.writer, msg)

    async def _forward(self, msg: Message, exclude: int | None = None,
                       limit: int = 0) -> None:
        """Send to peers. ``limit`` > 0 relays to a random subset
        instead (the GOSSIP_MESSAGES_PER_ROUND-style fan-out cap,
        gossiper.py:66-112): on dense overlays every receiver
        re-forwarding to ALL peers is O(peers^2) per flood; capped
        epidemic relay with at-most-once dedup reaches everyone whp
        in O(log n) hops at O(peers * fanout) traffic.

        Never serializes on a slow peer: idle lanes are written inline
        (synchronous, cheap); congested lanes are enqueued CONCURRENTLY
        — before round 7 this was a sequential write-then-drain loop,
        so one wedged TCP buffer stalled the fanout to every peer
        behind it."""
        targets = [p for p in self.peers.values() if p.idx != exclude]
        if limit > 0 and len(targets) > limit:
            targets = self._rng.sample(targets, limit)
        congested = [
            p for p in targets
            if self.shaper is not None or not self._try_fast_write(p, msg)
        ]
        if not congested:
            return

        async def enqueue(peer: PeerState) -> None:
            try:
                await self._write(peer, msg)
            except (ConnectionError, RuntimeError):
                self._drop_conn(peer)

        await asyncio.gather(*(enqueue(p) for p in congested))

    def _wire_dtype_for(self, peers, *, init: bool = False) -> str | None:
        """Negotiate the wire precision for one PARAMS send. Reduced
        precision requires EVERY target to have advertised it in its
        CONNECT hello; the initial model diffusion always ships f32
        (quantizing the common starting point would seed every node
        with a slightly different model and break same-seed parity
        with the f32 wire)."""
        if init or self.wire_dtype == "f32":
            return None
        if all(self.wire_dtype in self._peer_wire.get(p.idx, ())
               for p in peers):
            return self.wire_dtype
        return None

    def _apply_error_feedback(self, params):
        """Fold the residual of the previous int8 send into this one.

        Quantization is deterministic, so adding the carried error to
        the floating leaves BEFORE encode and recording the new
        carried error (carried-input minus its dequantized image) is
        exactly error-feedback compression — the wire still sees a
        plain int8 envelope. The residual is reset whenever the leaf
        structure changes (model swap between runs)."""
        leaves, treedef = jax.tree.flatten(
            jax.tree.map(np.asarray, params))
        res = self._ef_residual
        if res is None or len(res) != len(leaves) or any(
            r is not None and r.shape != np.shape(leaf)
            for r, leaf in zip(res, leaves)
        ):
            res = [
                np.zeros_like(leaf, dtype=np.float32)
                if np.issubdtype(leaf.dtype, np.floating) else None
                for leaf in leaves
            ]
        carried = [
            leaf.astype(np.float32) + r if r is not None else leaf
            for leaf, r in zip(leaves, res)
        ]
        tree = jax.tree.unflatten(treedef, carried)
        deq = jax.tree.leaves(dequantize_int8(*quantize_int8(tree)))
        self._ef_residual = [
            np.asarray(c, np.float32) - np.asarray(d, np.float32)
            if r is not None else None
            for c, d, r in zip(carried, deq, res)
        ]
        return tree

    async def _send_params(self, peers, params, contributors,
                           weight, _ef: bool = False, **body) -> None:
        """Ship a weights payload to one peer or a list of peers.

        The Message is built ONCE for the whole target list: the
        payload encode, the content hash, the signature, and the framed
        header are all per-message-lifetime costs — every additional
        recipient costs only a queue put of the same object (the frame
        memo makes the drain tasks reuse identical segments).

        ``_ef`` marks this node's OWN trained update: when the
        negotiated wire dtype is int8, the error-feedback residual is
        applied to it (aggregates/partials ship without EF — their
        error has no stable per-node carrier)."""
        if isinstance(peers, PeerState):
            peers = [peers]
        if not peers:
            return
        body.setdefault("round", self.round)
        # contributor/weight metadata rides the HEADER too (round 16):
        # a sidecar receiver runs its whole session bookkeeping —
        # supersede/evict, quorum, staleness folds — off these fields
        # without ever decoding the payload envelope
        body["c"] = [int(c) for c in contributors]
        body["w"] = int(weight)
        wd = self._wire_dtype_for(peers, init=bool(body.get("init")))
        if wd == "int8" and _ef:
            params = self._apply_error_feedback(params)
        blob = encode_parameters(params, tuple(contributors), int(weight),
                                 wire_dtype=wd)
        self.params_bytes_out += len(blob) * len(peers)
        msg = self._sign(
            Message(MsgType.PARAMS, self.idx, body, payload=blob,
                    # explicit id: PARAMS is a direct message, but
                    # proxies relay it and need at-most-once dedup
                    msg_id=secrets.token_hex(8))
        )
        # causal trace context (round 18): stamp the header's tc
        # BEFORE the first encode (the framed-header memo is built
        # once for the whole target list) and time the send under a
        # tx span whose id rides the wire — rx-side spans parent to
        # it, turning the merged trace into a cross-process graph.
        # Untraced path: msg.tc stays None and the header bytes are
        # identical to the pre-tc format (pinned by test).
        tr = self._tracer
        tx_span = NULL_SPAN
        if tr.enabled:
            sid = tr.next_span_id()
            msg.tc = (tr.trace_id, sid, time.time_ns())
            tx_span = tr.span(
                "p2p.tx", lane=self._lane,
                args={"sid": sid, "round": int(body["round"]),
                      "n_peers": len(peers), "bytes": len(blob)})
        with tx_span:
            congested = [
                p for p in peers
                if self.shaper is not None
                or not self._try_fast_write(p, msg)
            ]
            if not congested:
                return

            async def ship(peer: PeerState) -> None:
                try:
                    await self._write(peer, msg)
                except (ConnectionError, RuntimeError):
                    self._drop_conn(peer)

            await asyncio.gather(*(ship(p) for p in congested))

    # ------------------------------------------------------------------
    # control plane loops
    # ------------------------------------------------------------------
    async def _heartbeat_loop(self) -> None:
        period = self.protocol.heartbeat_period_s
        beats = 0
        while True:
            self.membership.beat(self.idx)
            # the sequence is wall-clock-derived (ms), not a zero-based
            # counter: it must stay monotonic across a process restart
            # or a recovered node's fresh beats would read as replays.
            # Skew doesn't matter — receivers compare per-sender only.
            await self.broadcast(
                Message(MsgType.BEAT, self.idx,
                        {"n": int(time.time() * 1000)})
            )
            beats += 1
            if beats % 2 == 0:
                # role refresh every 2nd beat (heartbeater.py:66-78
                # SEND_ROLE cadence) — keeps role views converged even
                # if the initial ROLE flood was missed
                await self.broadcast(
                    Message(MsgType.ROLE, self.idx, {"role": self.role})
                )
            self.membership.advance_to(self.membership.clock + period)
            await self._probe_suspects()
            await asyncio.sleep(period)

    async def _probe_suspects(self) -> None:
        """Actual peer-death detection (round 11): probe each SUSPECT
        (heartbeat-timed-out; NODE_DIED already fired) whose backoff
        window elapsed. A real process death closes its sockets, so by
        the time heartbeat silence is noticed the read loop has already
        dropped the peer entry — redial, and membership clears the
        suspicion on the replacement's first beat. A STILL-registered
        open lane is the opposite case: heartbeat silence there is far
        more often event-loop lag (CPU-bound fits starve the loop in
        packed layouts) than death, and tearing down a healthy lane
        drops in-flight round traffic — so leave it alone and only burn
        a retry, which keeps a genuinely wedged-but-open connection on
        the same bounded path to eviction. Once the retry budget is
        exhausted the death goes sticky (_evict_dead)."""
        for node in self.membership.probes_due():
            if self._link_severed(node):
                # a probe cannot succeed across an open partition cut —
                # but the in-process emulation's TCP dial WOULD (the cut
                # drops frames, it doesn't close sockets), so count the
                # failure here instead of letting the dial lie
                if self.membership.probe_failed(node):
                    self._evict_dead(node)
                continue
            conn = self.peers.get(node)
            if conn is not None and not conn.writer.is_closing():
                if self.membership.probe_failed(node):
                    self._evict_dead(node)
                elif self._tracer.enabled:
                    self._tracer.count("probe_defer")
                continue
            addr = self._peer_addrs.get(node)
            ok = False
            if addr is not None:
                if conn is not None:
                    # lane already closing: finish the teardown so the
                    # redial replaces it instead of racing it
                    self._teardown_conn(conn)
                with self._tracer.span("p2p.probe", lane=self._lane,
                                       args={"peer": node}):
                    try:
                        await asyncio.wait_for(
                            self.connect_to(*addr),
                            timeout=self.protocol.heartbeat_period_s,
                        )
                        ok = True
                    except Exception:
                        ok = False
            if ok:
                flight.record("membership.probe", node=node, ok=True)
                if self._tracer.enabled:
                    self._tracer.count("probe_ok")
            elif self.membership.probe_failed(node):
                self._evict_dead(node)
            elif self._tracer.enabled:
                self._tracer.count("probe_fail")

    # ------------------------------------------------------------------
    # learning
    # ------------------------------------------------------------------
    def set_start_learning(self, rounds: int, epochs: int = 1) -> None:
        """Initiator entry point (node.py:224)."""
        self._track_task(self._kickoff(rounds, epochs), "kickoff")

    async def _kickoff(self, rounds: int, epochs: int) -> None:
        await self.broadcast(
            Message(
                MsgType.START_LEARNING, self.idx,
                {"rounds": rounds, "epochs": epochs, "leader": self.idx
                 if self.role in ("server", "aggregator") else None},
            )
        )
        # initial model diffusion (node.py:299): push our weights until
        # every peer reports initialized. The starter must flood its own
        # MODEL_INITIALIZED too: an adopter re-diffuses until EVERY peer
        # — starter included — reports initialized, and nothing else
        # ever acks the starter, so a node that enters its learning
        # loop already-adopted would block in _diffuse_initial for the
        # whole aggregation timeout waiting on it.
        self.initialized = True
        await self.broadcast(Message(MsgType.MODEL_INITIALIZED, self.idx))
        self._start_learning(rounds, epochs, leader=self.idx)

    def _start_learning(self, rounds, epochs, leader=None) -> None:
        self.learning = True
        self.total_rounds = rounds
        self.epochs = epochs
        if leader is not None:
            self.leader = leader
            self.leader_history.append(leader)
        self._track_task(
            self.broadcast(
                Message(MsgType.ROLE, self.idx, {"role": self.role})
            ),
            "role_announce",
        )  # heartbeater.py:74 SEND_ROLE analog — peers learn who aggregates
        self._learn_task = asyncio.create_task(self._learning_loop())

    def _stop_learning(self) -> None:
        self.learning = False
        if self._learn_task:
            self._learn_task.cancel()
        self.finished.set()

    def _progress(self, idx: int) -> NodeProgress:
        if idx not in self.progress:
            self.progress[idx] = NodeProgress()
        return self.progress[idx]

    def _aggregated_by(self, idx: int) -> set[int]:
        """What node ``idx`` has aggregated THIS round (stale rounds
        read as empty — the reference clears per-peer aggregation state
        at round end, node.py:646)."""
        pr = self.progress.get(idx)
        if pr is None or pr.agg_round != self.round:
            return set()
        return pr.models_aggregated

    def _train_set(self) -> set[int]:
        alive = set(self.membership.get_nodes())
        return (alive & (set(self.peers) | {self.idx}))

    def _trainable(self, nodes: set[int]) -> set[int]:
        """Nodes that may carry training duty: proxies and idles are
        never train-set candidates (they forward/adopt but don't
        contribute — node.py:492-524)."""
        out = set()
        for i in nodes:
            role = self.peer_roles.get(i) if i != self.idx else self.role
            if role not in ("proxy", "idle"):
                out.add(i)
        return out

    async def _vote_train_set(self) -> set[int]:
        """Elect this round's train set (node.py:537-630 vote flow,
        VOTE_TIMEOUT + TRAIN_SET_SIZE knobs, participant.json.example:70).

        Every node's ballot is the trainable part of its own live
        neighborhood (itself + direct peers it believes alive) — the
        nodes it can vouch for. Ballots flood the overlay; the tally
        elects the ``train_set_size`` best-vouched-for candidates with
        index tie-break, so every node computes the same winners from
        the same ballots. Dead voters (evicted by membership) are
        dropped from the tally. If the ballot flood does NOT complete
        within ``vote_timeout_s``, the tally would depend on which
        ballots arrived where — so the election falls back to a
        deterministic ballot-independent function of the local alive
        view instead (identical winners whenever membership views
        agree, which heartbeats converge far faster than vote floods).
        """
        loop = asyncio.get_event_loop()
        alive = set(self.membership.get_nodes())
        ballot = sorted(
            self._trainable(alive & (set(self.peers) | {self.idx}))
        )
        votes = self._votes.setdefault(self.round, {})
        votes[self.idx] = tuple(ballot)
        await self.broadcast(
            Message(MsgType.VOTE_TRAIN_SET, self.idx,
                    {"round": self.round, "candidates": ballot})
        )
        deadline = loop.time() + self.protocol.vote_timeout_s
        complete = False
        while loop.time() < deadline:
            alive = set(self.membership.get_nodes())
            if alive <= set(votes):
                complete = True  # every live node's ballot arrived
                break
            await asyncio.sleep(self.gossip_period_s)
        if not complete:
            # Deterministic incomplete-ballot path: a partial tally
            # depends on WHICH ballots happened to arrive here before
            # the timeout, so two slow-gossip nodes could elect
            # different train sets and their aggregation sessions
            # would only close by timeout. Fall back to a
            # ballot-independent election over the trainable alive
            # MEMBERSHIP view (beats flood, so it spans multi-hop
            # overlays — restricting to direct peers would diverge on
            # a ring); nodes that share a membership view (heartbeats
            # converge much faster than a vote flood) agree again.
            alive = set(self.membership.get_nodes())
            cands = self._trainable(alive)
            tally = {c: 1 for c in cands}
        else:
            tally = {}
            for voter, cands in votes.items():
                if voter in alive:  # dead voters dropped (node.py:537-548)
                    for c in cands:
                        tally[c] = tally.get(c, 0) + 1
        k = self.protocol.train_set_size
        if k <= 0 or k > len(tally):
            k = len(tally)
        # tie-break ROTATES with the round so a binding cap still
        # covers every node's data over time (the reference's vote
        # uses random weights for the same effect, node.py:573-598);
        # round number is barrier-agreed, so all nodes elect the same set
        winners = sorted(
            tally,
            key=lambda c: (-tally[c], (c - self.round) % self.n_nodes),
        )[:k]
        win = set(winners) or {self.idx}
        # the leader must aggregate, so it is always seated (CFL server /
        # SDFL token holder); it displaces the weakest winner
        if (self.leader is not None and self.leader in alive
                and self.leader not in win):
            if winners and len(win) >= k:
                win.discard(winners[-1])
            win.add(self.leader)
        # ballots for finished rounds are garbage; future ones are kept
        self._votes = {r: v for r, v in self._votes.items() if r > self.round}
        return win

    async def _learning_loop(self) -> None:
        ln = self.learner
        # per-node profile (round 11): a compute-class epochs override
        # beats the federation-wide START_LEARNING value
        ln.set_epochs(self.local_epochs
                      if self.local_epochs is not None else self.epochs)
        if getattr(ln, "state", True) is None or getattr(ln, "fns", True) is None:
            ln.init()
        if self.initialized:
            await self._diffuse_initial()
        else:
            # wait for the initializer's weights
            while not self.initialized:
                await asyncio.sleep(self.gossip_period_s)
        self.learn_t0 = time.monotonic()
        while self.round < self.total_rounds:
            if self._join_round_target is not None:
                # deferred join fast-forward (STATE_SYNC landed while a
                # round body was active): jump at the boundary, where
                # no session references the old round number
                self.round = max(self.round, self._join_round_target)
                self._join_round_target = None
                if self.round >= self.total_rounds:
                    break
            t0 = time.monotonic()
            round_no = self.round
            self._cp_fit_s = self._cp_wait_s = self._cp_wire_s = 0.0
            self._cp_agg_mark = self.session.agg_wall_s
            with self._tracer.span("node.round", lane=self._lane,
                                   args={"round": self.round}):
                await self._train_round()
            wall = time.monotonic() - t0
            self.round_wall_s.append(wall)
            self._cp_snapshot(round_no, wall)
            self._maybe_checkpoint()
        self.learn_t1 = time.monotonic()
        # final evaluation, shared with the federation (the metrics
        # flood the reference stubbed out, node.py:611-620 + 875-878)
        try:
            metrics = await asyncio.get_running_loop().run_in_executor(
                None, self.learner.evaluate
            )
            self.peer_metrics[self.idx] = {"round": self.round, **metrics}
            await self.broadcast(
                Message(MsgType.METRICS, self.idx,
                        {"round": self.round, **metrics})
            )
        except Exception:  # evaluation is best-effort reporting
            log.exception("node %d final evaluate failed", self.idx)
        self.learning = False
        self.finished.set()

    def _maybe_checkpoint(self) -> None:
        """Round-boundary per-node checkpoint (round 14). Runs on the
        loop — the blob is one small msgpack serialize plus an fsynced
        file replace; a crash between rounds then restarts from a state
        at most ``checkpoint_every`` rounds old. Failures are reported
        and swallowed: checkpointing must never kill a healthy round
        loop (a full disk is an ops alert, not a training fault)."""
        if (not self.checkpoint_dir or self.checkpoint_every <= 0
                or self.round % self.checkpoint_every != 0):
            return
        from p2pfl_tpu.federation.checkpoint import save_node_checkpoint

        try:
            save_node_checkpoint(self.checkpoint_dir, self.idx,
                                 self.learner.get_parameters(), self.round)
            self.membership.notify(Events.CHECKPOINT_SAVED,
                                   {"node": self.idx, "round": self.round})
        except Exception as e:
            log.warning("node %d checkpoint failed at round %d: %s",
                        self.idx, self.round, e)

    async def _diffuse_initial(self) -> None:
        params = self.learner.get_parameters()
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self.protocol.aggregation_timeout_s
        # re-send pacing: a resend before the previous copy could even
        # arrive and be acknowledged (via the MODEL_INITIALIZED flood)
        # just convoys megabytes behind itself — especially under
        # shaped/delayed links. The reference paces diffusion at
        # GOSSIP_MODELS_FREC = 1 Hz for the same reason.
        retry_s = max(self.gossip_period_s * 4, 0.5)
        last_sent: dict[int, float] = {}
        while (
            any(not self._progress(i).initialized for i in self.peers)
            and loop.time() < deadline
        ):
            now = loop.time()
            due = []
            for idx, peer in list(self.peers.items()):
                if (not self._progress(idx).initialized
                        and now - last_sent.get(idx, -1e9) >= retry_s):
                    last_sent[idx] = now
                    due.append(peer)
            if due:
                # one encode+sign for the whole sweep — every due peer
                # gets the same Message object off its own send lane
                await self._send_params(due, params, (), 1, init=True)
            await asyncio.sleep(self.gossip_period_s)

    def _effective_role(self) -> str:
        """SDFL: the aggregator role follows the leadership token
        (node.py:649-686); other schemes use the static role."""
        if self.federation == "SDFL":
            return "aggregator" if self.leader == self.idx else "trainer"
        return self.role

    async def _fit(self) -> None:
        """Local training off the event loop: a blocking device call in
        line would starve heartbeats/gossip for the whole epoch and get
        peers evicted by membership timeouts.

        ``fit_slowdown`` (heterogeneous compute classes, round 11)
        stretches the fit by sleeping ``elapsed * (k - 1)`` AFTER the
        real fit: a straggler is exactly k× its own natural speed, with
        no absolute-time guess that would drift across models/hosts —
        and the sleep yields the loop, so heartbeats keep flowing."""
        t0 = time.monotonic()
        with self._tracer.span("node.fit", lane=self._lane,
                               args={"round": self.round}):
            await asyncio.get_running_loop().run_in_executor(
                None, self.learner.fit
            )
        if self.fit_slowdown > 1.0:
            await asyncio.sleep(
                (time.monotonic() - t0) * (self.fit_slowdown - 1.0)
            )
        # slowdown sleep included: the critical path cares how long
        # this node's update took to exist, not why
        self._cp_fit_s += time.monotonic() - t0

    def _cp_snapshot(self, round_no: int, wall: float) -> None:
        """Fold the round's accumulators into ``critpath_last`` — the
        per-node fit/wire/wait/aggregate/other split the status
        publisher flattens into critpath_* gauges (monitor WAIT%
        column, webapp breakdown pane).

        Wire seconds accrue per received frame and overlap the quorum
        wait (arrivals land while this node sleeps in the wait loops),
        so wire is carved OUT of wait: of the time spent waiting, wire
        is the part the bytes were actually in flight/queued, wait is
        the part the peers simply hadn't finished. ``other`` is the
        residual (vote, encode, bookkeeping), clamped at zero — the
        five components always sum to the measured round wall."""
        fit = self._cp_fit_s
        agg = max(0.0, self.session.agg_wall_s - self._cp_agg_mark)
        wire = min(self._cp_wire_s, self._cp_wait_s)
        wait = self._cp_wait_s - wire
        other = max(0.0, wall - fit - wait - wire - agg)
        self.critpath_last = {
            "round": round_no, "round_s": round(wall, 6),
            "fit_s": round(fit, 6), "wire_s": round(wire, 6),
            "wait_s": round(wait, 6), "agg_s": round(agg, 6),
            "other_s": round(other, 6),
        }

    def round_p95_s(self) -> float | None:
        """p95 of completed round wall times (None before the first
        round finishes) — the tail statistic the status publisher and
        monitor columns report; a mean would hide the one straggler
        round a stalled peer causes."""
        if not self.round_wall_s:
            return None
        xs = sorted(self.round_wall_s)
        return xs[min(len(xs) - 1, int(0.95 * len(xs)))]

    def _poisons_updates(self) -> bool:
        return self.attack is not None and self.attack.poisons_updates

    def _poison_own_update(self, ref) -> None:
        """Malicious node: transform the trained params ONCE, in place
        via set_parameters — the poisoned tree then backs both the own-
        session add_model AND every _send_params, exactly like the SPMD
        path's poisoned row entering every mix (its own included).
        ``ref`` is the round-start params (pre-fit snapshot); keyed by
        (seed, idx, round) so the SPMD row is bit-identical."""
        from p2pfl_tpu.adversary.attacks import poison_update

        flight.record("attack.inject", node=self.idx, round=self.round,
                      attack=type(self.attack).__name__)
        self.learner.set_parameters(
            poison_update(self.learner.get_parameters(), ref,
                          self.idx, self.round, self.attack)
        )

    def _privatize_own_update(self, ref) -> None:
        """DP-FedAvg: clip + noise the trained params ONCE in place —
        the privatized tree then backs the own-session add_model AND
        every _send_params, exactly like the SPMD path's privatized row
        entering every mix. ``ref`` is the round-start params; keyed by
        (dp.seed, idx, round) so the SPMD row is bit-identical."""
        from p2pfl_tpu.privacy.dp import dp_key, privatize_update_jit

        flight.record("dp.privatize", node=self.idx, round=self.round)
        self.learner.set_parameters(
            privatize_update_jit(
                self.learner.get_parameters(), ref,
                self.dp.clip_norm, self.dp.noise_multiplier,
                dp_key(self.dp.seed, self.idx, self.round),
            )
        )

    async def _train_round(self) -> None:
        train_set = await self._vote_train_set()
        self.session.clear()
        if self.masker is not None:
            # fresh pair-mask streams for this round's member set; a
            # mid-round eviction then knows exactly which pairs may
            # need reconstruction at quorum close
            self.masker.begin_round(self.round, train_set)
        # Snapshot the effective role and token position for the WHOLE
        # round: a TRANSFER_LEADERSHIP that lands mid-round must not
        # flip this round's behavior (it takes effect next round), or a
        # node that both led and received the token would rotate twice
        # in one round.
        role = self._effective_role()
        leader_at_start = self.leader
        if self.idx not in train_set and role in ("aggregator", "trainer"):
            # voted out this round: no training duty, adopt only
            # (the reference's is-in-train-set gate, node.py:425-427)
            role = "idle"
        # session mode is set BEFORE fit (which runs in an executor)
        # and BEFORE replaying buffered messages: an aggregate arriving
        # mid-fit or buffered from a fast peer must be adopted by a
        # waiting node, not mistaken for a regular partial contribution
        if role in ("aggregator", "server"):
            self.session.set_nodes_to_aggregate(train_set)
            # round-start params: the delta reference for reputation
            # scoring — and under secagg the dtype/shape template the
            # masked sum dequantizes against at close (set BEFORE the
            # pending replay below — a replayed model can complete
            # coverage and finish the session immediately)
            if self.reputation is not None or self.masker is not None:
                self.session.set_reference(self.learner.get_parameters())
        else:
            self.session.set_waiting_aggregated_model()
        self._round_active = True
        # replay weight messages that arrived before this round's
        # session was ready for them
        pending, self._pending_params = self._pending_params, []
        for peer, msg in pending:
            if peer.idx in self.peers:
                # inner entry: the rx span + wire-latency accrual fired
                # at true arrival; replaying through the traced wrapper
                # would double-count the frame's wire seconds
                await self._on_params_inner(peer, msg)
            elif msg._slot is not None and self.sidecar is not None:
                # the sender is gone; return its buffered payload's slot
                self.sidecar.release(msg._slot)
                msg._slot = None
        if role in ("aggregator", "server"):
            ref = (self.learner.get_parameters()
                   if self._poisons_updates() or self.dp is not None
                   else None)
            await self._fit()
            if self._poisons_updates():
                self._poison_own_update(ref)
            if self.dp is not None:
                # privatize AFTER any poisoning (the clip then also
                # bounds injected updates — deployment semantics,
                # matching the SPMD round fn's ordering)
                self._privatize_own_update(ref)
            n_samples = self.learner.get_num_samples()[0]
            own = self.learner.get_parameters()
            if self.masker is not None:
                # the masked tree is what enters the session AND what
                # gossip forwards — the raw update never leaves the
                # learner
                own = self.masker.mask_update(own, n_samples)
            covered = self.session.add_model(own, (self.idx,), n_samples)
            await self.broadcast(
                Message(MsgType.MODELS_AGGREGATED, self.idx,
                        {"contributors": sorted(covered),
                         "round": self.round})
            )
            await self._gossip_until_done(train_set, role, leader_at_start)
        elif role == "trainer":
            ref = (self.learner.get_parameters()
                   if self._poisons_updates() or self.dp is not None
                   else None)
            await self._fit()
            if self._poisons_updates():
                self._poison_own_update(ref)
            if self.dp is not None:
                self._privatize_own_update(ref)
            n_samples = self.learner.get_num_samples()[0]
            own = self.learner.get_parameters()
            if self.masker is not None:
                own = self.masker.mask_update(own, n_samples)
            target = (
                leader_at_start if leader_at_start in self.peers else None
            )
            sent_to = (
                [self.peers[target]] if target is not None
                else list(self.peers.values())
            )
            await self._send_params(
                sent_to, own, (self.idx,), n_samples, _ef=True,
            )
            await self._wait_done()
        else:  # idle / proxy: adopt whatever aggregate arrives
            await self._wait_done()

        if self.session.result is not None:
            params, _ = self.session.result
            self.learner.set_parameters(params)
        self._round_active = False  # barrier window: buffer, don't drop
        self.round += 1
        self.learner.finalize_round()
        if self.federation == "SDFL" and role == "aggregator":
            # Rotate the aggregator token (node.py:676-686 "random",
            # excluding self like the reference's choice of neighbors).
            # Rotation is decided by the node that LED this round (the
            # snapshot above), and broadcast BEFORE MODELS_READY: the
            # per-peer TCP stream is ordered, so no peer can observe our
            # round completion (and exit its round barrier) without
            # having the new token — the next round always starts with
            # exactly one leader everywhere.
            candidates = sorted(
                (train_set & set(self.membership.get_nodes())) - {self.idx}
            )
            if candidates:
                new_leader = self._rng.choice(candidates)
                self.leader = new_leader
                self.leader_history.append(new_leader)
                await self.broadcast(
                    Message(MsgType.TRANSFER_LEADERSHIP, self.idx,
                            # self.round was just incremented: the token
                            # names the round it takes effect in, and
                            # receivers reject transfers for past rounds
                            {"to": new_leader, "round": self.round})
                )
        await self.broadcast(
            Message(MsgType.MODELS_READY, self.idx, {"round": self.round})
        )
        await self._wait_neighbors_ready()

    async def _gossip_until_done(
        self, train_set: set[int], role: str, leader_at_start: int | None
    ) -> None:
        """Partial-aggregation gossip (node.py:692-700 + 726-809):
        send each stale peer the aggregate of models it lacks, until
        the session completes (coverage or timeout). ``role`` and
        ``leader_at_start`` are the caller's round-start snapshot — the
        live token may have moved mid-round."""
        fanout = max(self.protocol.gossip_models_per_round, 1)
        loop = asyncio.get_event_loop()
        # wait-on-quorum accounting: this loop's wall time, net of any
        # aggregation that ran inside it (session.agg_wall_s delta) —
        # partial-encode/gossip work in here is noise against the
        # multi-second quorum waits the breakdown exists to expose
        tw0 = time.monotonic()
        agg0 = self.session.agg_wall_s
        with self._tracer.span("node.wait", lane=self._lane,
                               args={"round": self.round,
                                     "kind": "gossip"}):
            try:
                await self._gossip_body(train_set, role,
                                        leader_at_start, fanout, loop)
            finally:
                self._cp_wait_s += max(
                    0.0, (time.monotonic() - tw0)
                    - (self.session.agg_wall_s - agg0))

    async def _gossip_body(self, train_set, role, leader_at_start,
                           fanout, loop) -> None:
        last_status = None
        last_change_t = loop.time()
        deadline = loop.time() + self.session.timeout_s
        self._gossip_sent: dict[int, tuple[frozenset, float]] = {}
        # who is expected to AGGREGATE this round: in CFL/SDFL only the
        # round's leader fuses models (trainers adopt its offer — they
        # will never show coverage themselves, so waiting on them would
        # deadlock until timeout); in DFL every train-set node with an
        # aggregating role does (the reference's split between
        # aggregation-gossip and diffusion, node.py:692-724)
        if self.federation in ("CFL", "SDFL"):
            aggregators = (
                {leader_at_start} if leader_at_start is not None else set()
            )
        else:
            aggregators = {
                i for i in train_set
                if self.peer_roles.get(i, "aggregator")
                in ("aggregator", "server")
            }
        while True:
            done = self.session.check_and_run()
            proxies = [
                p for i, p in self.peers.items()
                if self.peer_roles.get(i) == "proxy"
            ]
            # target = an aggregating NODE that hasn't covered the
            # WHOLE train set yet (node.py:695 candidate condition) —
            # gossip continues even after our own session completes,
            # or a node whose session fills up early (it received
            # everyone during its fit) would never ship its own model.
            # Progress floods, so this covers nodes reachable only
            # through a PROXY — but only REACHABLE targets may consume
            # fanout slots (building a partial for an undeliverable
            # node would waste both the aggregation and the slot), and
            # only LIVE ones: a crashed aggregator (heartbeat-evicted,
            # no STOP) must stop consuming fanout slots and proxy
            # bandwidth even while a proxy path to its address exists.
            live = set(self.membership.get_nodes())
            # In async mode a peer stops being a gossip target once its
            # coverage meets the QUORUM its own session closes on: full
            # train-set coverage is unreachable whenever a voted member
            # crashed mid-round, and chasing it would pin every round
            # at the aggregation deadline — exactly the serialization
            # the buffered session exists to remove. Sync mode keeps
            # the full-coverage bar (quorum is the whole train set).
            quorum = (self.session.quorum()
                      if self.session.async_mode else None)

            def _stale_target(has: set[int]) -> bool:
                if train_set <= has:
                    return False
                return quorum is None or len(has & train_set) < quorum

            targets = [
                (i, self._aggregated_by(i))
                for i in sorted((aggregators - {self.idx}) & live)
                if _stale_target(self._aggregated_by(i))
                and (i in self.peers or proxies)
            ]
            if (done and not targets) or loop.time() > deadline:
                break
            random.shuffle(targets)
            for i, has in targets[:fanout]:
                # re-send pacing: the same partial to the same stale
                # target is only repeated after a retry window (loss
                # recovery) — its progress flood needs at least an RTT
                # to reflect the last send, and blind per-tick resends
                # of megabyte payloads convoy every other message on
                # the link (see _diffuse_initial)
                now = loop.time()
                key = frozenset(has)
                prev = self._gossip_sent.get(i)
                if (prev is not None and prev[0] == key
                        and now - prev[1] < max(self.gossip_period_s * 4, 0.5)):
                    continue
                partial = self.session.get_partial_aggregation(has)
                if partial is None:
                    continue
                self._gossip_sent[i] = (key, now)
                params, contribs, weight = partial
                if i in self.peers:
                    await self._send_params(
                        self.peers[i], params, contribs, weight
                    )
                else:
                    # no direct link: hand the partial to proxies to
                    # relay (node.py:492-515) — one Message for all
                    await self._send_params(proxies, params, contribs,
                                            weight)
            # convergence exit (node.py:761-777, GOSSIP_EXIT_ON_X_EQUAL_
            # ROUNDS): the reference's gossip tick is 1 Hz, so "20
            # equal rounds" means ~20 quiet SECONDS — measure quiet
            # time by wall clock so fast tick rates don't turn the knob
            # into a hair trigger. On exit, stop SENDING only: the
            # reference exits just its gossip loop; aggregation still
            # completes by coverage or timeout (aggregator.py:46-76).
            status = (
                self.session.covered,
                tuple((i, tuple(sorted(has))) for i, has in sorted(targets)),
            )
            now = loop.time()
            if status != last_status:
                last_status, last_change_t = status, now
            if (self.protocol.gossip_exit_on_equal_rounds > 0
                    and now - last_change_t
                    >= self.protocol.gossip_exit_on_equal_rounds):
                while not self.session.check_and_run():
                    await asyncio.sleep(self.gossip_period_s)
                break
            await asyncio.sleep(self.gossip_period_s)
        # aggregation finished; if a full aggregate exists, also offer it
        # to trainer/idle peers waiting for one (CFL/SDFL broadcast)
        if self.session.result is not None and (
            role == "server"
            or (leader_at_start == self.idx and role == "aggregator")
        ):
            params, contribs = self.session.result
            await self._send_params(
                list(self.peers.values()),
                params, contribs or tuple(sorted(train_set)), 1,
                aggregated=True,
            )

    async def _wait_done(self) -> None:
        tw0 = time.monotonic()
        with self._tracer.span("node.wait", lane=self._lane,
                               args={"round": self.round,
                                     "kind": "adopt"}):
            try:
                deadline = (asyncio.get_event_loop().time()
                            + self.session.timeout_s)
                while not self.session.done.is_set():
                    if asyncio.get_event_loop().time() > deadline:
                        # keep local params (timeout, nothing arrived)
                        break
                    await asyncio.sleep(self.gossip_period_s)
            finally:
                self._cp_wait_s += time.monotonic() - tw0

    async def _wait_neighbors_ready(self) -> None:
        """Round barrier: wait until every alive node we've heard from
        reports this round (MODELS_READY gating, node.py:713; floods,
        so multi-hop members count too), bounded by the timeout.

        In async mode the barrier relaxes to the SAME quorum the
        session closes on: waiting for every straggler here would
        re-serialize the rounds the buffered aggregation just
        de-serialized — the whole async speedup would die at the
        barrier. Stragglers left behind catch up via the stale-params
        fold (see _on_params)."""
        tw0 = time.monotonic()
        with self._tracer.span("node.wait", lane=self._lane,
                               args={"round": self.round,
                                     "kind": "barrier"}):
            try:
                deadline = (asyncio.get_event_loop().time()
                            + self.session.timeout_s)
                frac = self.session.min_received
                while asyncio.get_event_loop().time() < deadline:
                    alive = set(self.membership.get_nodes())
                    known = set(self.peers) | set(self.progress)
                    others = [i for i in alive & known if i != self.idx]
                    behind = [
                        i for i in others
                        if self._progress(i).ready_round < self.round
                    ]
                    if not behind:
                        return
                    if self.session.async_mode and others:
                        need = max(1, math.ceil(frac * len(others)))
                        if len(others) - len(behind) >= need:
                            return
                    await asyncio.sleep(self.gossip_period_s)
            finally:
                self._cp_wait_s += time.monotonic() - tw0
