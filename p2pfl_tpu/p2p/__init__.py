"""Async P2P runtime: the DCN / multi-host path.

The in-process mesh transport (p2pfl_tpu.parallel) covers federations
that fit one host's devices; this package is the successor of the
reference's L1 socket runtime (fedstellar/base_node.py,
node_connection.py, communication_protocol.py, gossiper.py,
heartbeater.py) for federations spanning hosts/pods:

- ``protocol``: length-prefixed msgpack frames over TCP — replaces the
  reference's hand-rolled text grammar with 2 KB padded fragments and
  pickle payloads (communication_protocol.py:37-134, 737-769).
- ``session``: the aggregation session — contributor-set bookkeeping,
  partial aggregation for peers, timeout-bounded completion
  (learning/aggregators/aggregator.py:106-229 parity).
- ``node``: an asyncio node — listener, per-peer streams, gossip,
  heartbeats, and the round state machine — replacing the reference's
  thread-per-connection design with a single event loop.

Within a host, each node still trains through the same jitted StepFns;
across hosts only weights move, so the TPU compute path is identical
in both transports.
"""

from p2pfl_tpu.p2p.protocol import Message, MsgType, read_message, write_message
from p2pfl_tpu.p2p.session import AggregationSession
from p2pfl_tpu.p2p.node import P2PNode

__all__ = [
    "Message",
    "MsgType",
    "read_message",
    "write_message",
    "AggregationSession",
    "P2PNode",
]
