"""Aggregation session: contributor-set bookkeeping for gossip mode.

Behavior parity with the reference's Aggregator thread
(learning/aggregators/aggregator.py), re-done as a plain object + an
asyncio.Event instead of a daemon thread blocking on a lock (:40-49):

- models are stored keyed by their **contributor set** (:151);
- an incoming model is ignored if its contributors are already covered,
  and it evicts stored models it supersedes (:135-158 dedup);
- ``get_partial_aggregation(peer_has)`` builds the aggregate of models
  the peer doesn't have yet (:181-208) — this is what makes gossip
  converge without re-sending everything;
- completion fires when the train set is covered (:210-229) or the
  timeout expires, in which case whatever arrived is aggregated
  (:53-76);
- ``waiting`` mode (TRAINER/PROXY/IDLE, :93-123): the first full
  aggregate that arrives is adopted as-is.

Round 11 adds a buffered **async mode** (``min_received < 1``, the
FedBuff-style close rule): the round closes as soon as a quorum of the
expected train set is covered — or the deadline fires — instead of
waiting for everyone; a straggler's update that misses the close is
not dropped but folded into the NEXT round's aggregate with its weight
discounted by ``1/(1+staleness)^beta``
(p2pfl_tpu.parallel.federated.staleness_scale — the same host-side f32
formula the SPMD plane applies as a mix-column scale, so the two
planes' weighting stays bit-comparable). The discount is applied to
the entry's WEIGHT at add time: staleness is a property of the update
itself, so scaling once at the entry point composes correctly with
partial-aggregation forwarding (weighted means carry weights) and
never compounds, unlike reputation scaling which is receiver-context
and therefore applies only at finish.

The math is the pure aggregator from p2pfl_tpu.core.aggregators over a
stacked tree — device-jittable even in the socket path.
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import Any

import jax
import numpy as np

from p2pfl_tpu.core.aggregators import Aggregator, FedAvg
from p2pfl_tpu.core.pytree import tree_stack
from p2pfl_tpu.obs import flight
from p2pfl_tpu.obs.trace import get_tracer
from p2pfl_tpu.parallel.federated import staleness_scale

Params = Any


class AggregationSession:
    """One round's aggregation state for one node."""

    def __init__(self, aggregator: Aggregator | None = None,
                 timeout_s: float = 60.0, reputation=None,
                 lane: str | None = None, min_received: float = 1.0,
                 staleness_beta: float = 0.0):
        self.aggregator = aggregator or FedAvg()
        self.timeout_s = timeout_s  # AGGREGATION_TIMEOUT
        #: async close quorum as a fraction of the expected train set;
        #: 1.0 = classic synchronous behavior (full coverage or timeout)
        self.min_received = float(min_received)
        #: staleness discount exponent (0 = stale entries weigh fresh)
        self.staleness_beta = float(staleness_beta)
        # obs: the owning node's trace lane (k nodes share a process
        # tracer in packed launch layouts — the lane attributes spans)
        self._tracer = get_tracer()
        self._lane = lane
        #: optional adversary.ReputationMonitor shared across rounds:
        #: scores this session's entries at finish time and rescales
        #: their weights by contributor trust (see _finish/_aggregate)
        self.reputation = reputation
        #: round-start params of the session's owner — the delta
        #: reference for reputation scoring (set_reference per round)
        self.reference: Params | None = None
        self.models: dict[frozenset[int], tuple[Params, float]] = {}
        self.train_set: frozenset[int] = frozenset()
        self.waiting = False
        self.done = asyncio.Event()
        self.result: tuple[Params, tuple[int, ...]] | None = None
        self._deadline: float | None = None
        # partial-aggregation memo: the gossip loop asks for the same
        # (store, peer-coverage) combination every tick and for every
        # same-coverage target, and each miss costs a tree_stack +
        # aggregator pass on device. Keyed by the peer's coverage set;
        # invalidated whenever the store changes.
        self._partial_memo: dict[
            frozenset[int], tuple[Params, tuple[int, ...], float] | None
        ] = {}

    # -- setup ----------------------------------------------------------
    def set_nodes_to_aggregate(self, train_set) -> None:
        self.train_set = frozenset(int(i) for i in train_set)
        self._deadline = time.monotonic() + self.timeout_s
        flight.record("session.open", lane=self._lane,
                      train_set=sorted(self.train_set),
                      quorum=self.quorum())

    def set_waiting_aggregated_model(self) -> None:
        """TRAINER/PROXY/IDLE: adopt the next aggregate received."""
        self.waiting = True

    def set_reference(self, params: Params) -> None:
        """Round-start params — what this node's cohort trained FROM.
        Entry deltas for reputation scoring are measured against it."""
        self.reference = params

    # -- state ----------------------------------------------------------
    @property
    def covered(self) -> frozenset[int]:
        out: frozenset[int] = frozenset()
        for key in self.models:
            out = out | key
        return out

    def timed_out(self) -> bool:
        return self._deadline is not None and time.monotonic() > self._deadline

    @property
    def async_mode(self) -> bool:
        return self.min_received < 1.0

    def quorum(self) -> int:
        """Entries-covered threshold that closes an async round."""
        n = len(self.train_set)
        if not self.async_mode:
            return n
        return max(1, math.ceil(self.min_received * n))

    def quorum_met(self) -> bool:
        return bool(self.train_set) and (
            len(self.covered & self.train_set) >= self.quorum()
        )

    # -- adding models ---------------------------------------------------
    def add_model(self, params: Params, contributors, weight: float,
                  staleness: float = 0.0) -> tuple[int, ...]:
        """Returns the contributors now covered (broadcast as
        MODELS_AGGREGATED, node.py:363-369). Empty tuple = rejected.

        ``staleness`` (rounds-behind, async mode) discounts the entry's
        weight by ``staleness_scale`` at entry time — see module doc.
        """
        with self._tracer.span("session.add_model", lane=self._lane):
            if staleness > 0.0 and self.staleness_beta > 0.0:
                weight = float(weight) * float(
                    staleness_scale(staleness, self.staleness_beta)
                )
            return self._add_model(params, contributors, weight)

    def _add_model(self, params: Params, contributors,
                   weight: float) -> tuple[int, ...]:
        contrib = frozenset(int(i) for i in contributors)
        if not contrib:
            return ()
        if self.waiting:
            self.result = (params, tuple(sorted(contrib)))
            self.done.set()
            return tuple(sorted(contrib))
        if contrib <= self.covered:
            return ()  # nothing new (aggregator.py:149 overlap guard)
        # accept only if every contributor the incoming model shares
        # with our store is explained by stored models it supersedes
        # (k ⊆ contrib) — otherwise a partially-overlapping partial
        # (e.g. {B,C} arriving over stored {C,D}) would double-count
        # the shared contributor in the weighted mean
        evict = [k for k in self.models if k <= contrib]
        explained: frozenset[int] = frozenset()
        for k in evict:
            explained = explained | k
        if (contrib & self.covered) - explained:
            return ()  # overlapping but not superseding — reject
        for key in evict:
            del self.models[key]
        self.models[contrib] = (params, float(weight))
        self._partial_memo.clear()  # store changed; memoed partials stale
        if self.train_set and (
            self.covered >= self.train_set
            or (self.async_mode and self.quorum_met())
        ):
            if self.async_mode and not self.covered >= self.train_set:
                flight.record("session.quorum", lane=self._lane,
                              covered=sorted(self.covered),
                              quorum=self.quorum())
            self._finish()
        return tuple(sorted(self.covered))

    # -- partial aggregation for a peer ----------------------------------
    def get_partial_aggregation(
        self, peer_has
    ) -> tuple[Params, tuple[int, ...], float] | None:
        """Aggregate of stored models containing no contributor the
        peer already has; None if there is nothing new to send.
        Memoized per peer-coverage set until the store changes."""
        peer = frozenset(int(i) for i in peer_has)
        if peer in self._partial_memo:
            return self._partial_memo[peer]
        send = [
            (p, k, w) for k, (p, w) in self.models.items() if not (k & peer)
        ]
        if not send:
            self._partial_memo[peer] = None
            return None
        params, contribs, weight = self._aggregate(
            [(p, w) for p, k, w in send]
        )
        all_contrib: frozenset[int] = frozenset()
        for _, k, _ in send:
            all_contrib = all_contrib | k
        out = (params, tuple(sorted(all_contrib)), weight)
        self._partial_memo[peer] = out
        return out

    # -- completion -------------------------------------------------------
    def check_and_run(self) -> bool:
        """Called by the node loop: finish on coverage (async: quorum)
        or timeout with whatever arrived (aggregator.py:53-76)."""
        if self.done.is_set():
            return True
        if self.models and (
            (self.train_set and self.covered >= self.train_set)
            or (self.async_mode and self.quorum_met())
            or self.timed_out()
        ):
            self._finish()
            return True
        return False

    def _finish(self) -> None:
        # reputation applies ONLY at finish, never to the partial
        # aggregates gossiped mid-round: a partial is re-weighted again
        # inside every receiver's own finish, so scaling it at build
        # time would compound the trust discount sender x receiver
        keys = list(self.models.keys())
        if (self.reputation is not None and self.reference is not None
                and len(self.models) >= 3):
            # observe BEFORE aggregating: unlike SPMD (where scores
            # come out of the jitted round fn and can only shape the
            # NEXT round's mix), both steps here are host-side at the
            # same instant — same-round exclusion costs nothing and
            # stops a first-round attacker before any poisoned
            # aggregate lands. Under 3 entries the cohort median/
            # direction is meaningless (2 rows score symmetrically) —
            # no observation, trust persists.
            self.reputation.observe_entries(
                self.reference,
                [(k, p) for k, (p, _) in self.models.items()],
            )
        params, contribs, _ = self._aggregate(
            list(self.models.values()), keys=keys
        )
        self.result = (params, tuple(sorted(self.covered)))
        flight.record("session.close", lane=self._lane,
                      entries=len(keys), covered=sorted(self.covered),
                      timed_out=self.timed_out())
        self.done.set()

    def _aggregate(self, entries,
                   keys=None) -> tuple[Params, tuple[int, ...], float]:
        if len(entries) == 1:
            p, w = entries[0]
            return p, (), w
        # ONE effective-weights computation feeding BOTH execution
        # paths below — reputation (or any future weight shaping)
        # cannot be silently dropped by the numpy fast path
        weights = np.asarray([w for _, w in entries], np.float32)
        if keys is not None and self.reputation is not None:
            weights = weights * self.reputation.entry_scales(keys)
        if type(self.aggregator) is FedAvg:
            return self._aggregate_numpy(entries, weights)
        with self._tracer.span(
            "session.aggregate", lane=self._lane,
            args={"path": "stacked_device", "n": len(entries)},
        ):
            stacked = tree_stack(
                [jax.tree.map(np.asarray, p) for p, _ in entries]
            )
            agg = self.aggregator(stacked, weights)
            return jax.tree.map(np.asarray, agg), (), float(weights.sum())

    def _aggregate_numpy(self, entries, weights):
        # Host fast path. Models in the socket session are host
        # arrays on both sides (deserialized on arrival, re-encoded
        # on send), and the entry count varies with gossip timing —
        # pushing every combination through jnp.stack + eager XLA
        # reductions compiles a fresh program per distinct stack
        # size mid-round (measured: ~450 compiles / 2 rounds on the
        # 24-node uncapped bench, ~30% of wall). A numpy weighted
        # mean is shape-oblivious and stays off-device.
        with self._tracer.span(
            "session.aggregate", lane=self._lane,
            args={"path": "numpy_fast", "n": len(entries)},
        ):
            total = float(weights.sum())
            if total > 0:
                wn = weights / total
            else:  # tree_weighted_mean degenerate-case parity
                wn = np.full_like(weights, 1.0 / len(entries))
                total = float(len(entries))
            trees = [jax.tree.map(np.asarray, p) for p, _ in entries]

            def leaf(*xs):
                acc = np.asarray(xs[0], np.float32) * wn[0]
                for wi, x in zip(wn[1:], xs[1:]):
                    acc += np.asarray(x, np.float32) * wi
                return acc.astype(np.asarray(xs[0]).dtype)

            return jax.tree.map(leaf, *trees), (), total

    def clear(self) -> None:
        """Reset for the next round (aggregator.py:231-238)."""
        self.models.clear()
        self._partial_memo.clear()
        self.reference = None  # reputation state itself persists
        self.train_set = frozenset()
        self.waiting = False
        self.result = None
        self.done = asyncio.Event()
        self._deadline = None
