"""Aggregation session: contributor-set bookkeeping for gossip mode.

Behavior parity with the reference's Aggregator thread
(learning/aggregators/aggregator.py), re-done as a plain object + an
asyncio.Event instead of a daemon thread blocking on a lock (:40-49):

- models are stored keyed by their **contributor set** (:151);
- an incoming model is ignored if its contributors are already covered,
  and it evicts stored models it supersedes (:135-158 dedup);
- ``get_partial_aggregation(peer_has)`` builds the aggregate of models
  the peer doesn't have yet (:181-208) — this is what makes gossip
  converge without re-sending everything;
- completion fires when the train set is covered (:210-229) or the
  timeout expires, in which case whatever arrived is aggregated
  (:53-76);
- ``waiting`` mode (TRAINER/PROXY/IDLE, :93-123): the first full
  aggregate that arrives is adopted as-is.

The math is the pure aggregator from p2pfl_tpu.core.aggregators over a
stacked tree — device-jittable even in the socket path.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

import jax
import numpy as np

from p2pfl_tpu.core.aggregators import Aggregator, FedAvg
from p2pfl_tpu.core.pytree import tree_stack

Params = Any


class AggregationSession:
    """One round's aggregation state for one node."""

    def __init__(self, aggregator: Aggregator | None = None,
                 timeout_s: float = 60.0):
        self.aggregator = aggregator or FedAvg()
        self.timeout_s = timeout_s  # AGGREGATION_TIMEOUT
        self.models: dict[frozenset[int], tuple[Params, float]] = {}
        self.train_set: frozenset[int] = frozenset()
        self.waiting = False
        self.done = asyncio.Event()
        self.result: tuple[Params, tuple[int, ...]] | None = None
        self._deadline: float | None = None

    # -- setup ----------------------------------------------------------
    def set_nodes_to_aggregate(self, train_set) -> None:
        self.train_set = frozenset(int(i) for i in train_set)
        self._deadline = time.monotonic() + self.timeout_s

    def set_waiting_aggregated_model(self) -> None:
        """TRAINER/PROXY/IDLE: adopt the next aggregate received."""
        self.waiting = True

    # -- state ----------------------------------------------------------
    @property
    def covered(self) -> frozenset[int]:
        out: frozenset[int] = frozenset()
        for key in self.models:
            out = out | key
        return out

    def timed_out(self) -> bool:
        return self._deadline is not None and time.monotonic() > self._deadline

    # -- adding models ---------------------------------------------------
    def add_model(self, params: Params, contributors, weight: float) -> tuple[int, ...]:
        """Returns the contributors now covered (broadcast as
        MODELS_AGGREGATED, node.py:363-369). Empty tuple = rejected."""
        contrib = frozenset(int(i) for i in contributors)
        if not contrib:
            return ()
        if self.waiting:
            self.result = (params, tuple(sorted(contrib)))
            self.done.set()
            return tuple(sorted(contrib))
        if contrib <= self.covered:
            return ()  # nothing new (aggregator.py:149 overlap guard)
        # accept only if every contributor the incoming model shares
        # with our store is explained by stored models it supersedes
        # (k ⊆ contrib) — otherwise a partially-overlapping partial
        # (e.g. {B,C} arriving over stored {C,D}) would double-count
        # the shared contributor in the weighted mean
        evict = [k for k in self.models if k <= contrib]
        explained: frozenset[int] = frozenset()
        for k in evict:
            explained = explained | k
        if (contrib & self.covered) - explained:
            return ()  # overlapping but not superseding — reject
        for key in evict:
            del self.models[key]
        self.models[contrib] = (params, float(weight))
        if self.train_set and self.covered >= self.train_set:
            self._finish()
        return tuple(sorted(self.covered))

    # -- partial aggregation for a peer ----------------------------------
    def get_partial_aggregation(
        self, peer_has
    ) -> tuple[Params, tuple[int, ...], float] | None:
        """Aggregate of stored models containing no contributor the
        peer already has; None if there is nothing new to send."""
        peer = frozenset(int(i) for i in peer_has)
        send = [
            (p, k, w) for k, (p, w) in self.models.items() if not (k & peer)
        ]
        if not send:
            return None
        params, contribs, weight = self._aggregate(
            [(p, w) for p, k, w in send]
        )
        all_contrib: frozenset[int] = frozenset()
        for _, k, _ in send:
            all_contrib = all_contrib | k
        return params, tuple(sorted(all_contrib)), weight

    # -- completion -------------------------------------------------------
    def check_and_run(self) -> bool:
        """Called by the node loop: finish on coverage or timeout with
        whatever arrived (aggregator.py:53-76)."""
        if self.done.is_set():
            return True
        if self.models and (
            (self.train_set and self.covered >= self.train_set)
            or self.timed_out()
        ):
            self._finish()
            return True
        return False

    def _finish(self) -> None:
        params, contribs, _ = self._aggregate(list(self.models.values()))
        self.result = (params, tuple(sorted(self.covered)))
        self.done.set()

    def _aggregate(self, entries) -> tuple[Params, tuple[int, ...], float]:
        if len(entries) == 1:
            p, w = entries[0]
            return p, (), w
        stacked = tree_stack([jax.tree.map(np.asarray, p) for p, _ in entries])
        weights = np.asarray([w for _, w in entries], np.float32)
        agg = self.aggregator(stacked, weights)
        return jax.tree.map(np.asarray, agg), (), float(weights.sum())

    def clear(self) -> None:
        """Reset for the next round (aggregator.py:231-238)."""
        self.models.clear()
        self.train_set = frozenset()
        self.waiting = False
        self.result = None
        self.done = asyncio.Event()
        self._deadline = None
