"""Aggregation session: contributor-set bookkeeping for gossip mode.

Behavior parity with the reference's Aggregator thread
(learning/aggregators/aggregator.py), re-done as a plain object + an
asyncio.Event instead of a daemon thread blocking on a lock (:40-49):

- models are stored keyed by their **contributor set** (:151);
- an incoming model is ignored if its contributors are already covered,
  and it evicts stored models it supersedes (:135-158 dedup);
- ``get_partial_aggregation(peer_has)`` builds the aggregate of models
  the peer doesn't have yet (:181-208) — this is what makes gossip
  converge without re-sending everything;
- completion fires when the train set is covered (:210-229) or the
  timeout expires, in which case whatever arrived is aggregated
  (:53-76);
- ``waiting`` mode (TRAINER/PROXY/IDLE, :93-123): the first full
  aggregate that arrives is adopted as-is.

Round 11 adds a buffered **async mode** (``min_received < 1``, the
FedBuff-style close rule): the round closes as soon as a quorum of the
expected train set is covered — or the deadline fires — instead of
waiting for everyone; a straggler's update that misses the close is
not dropped but folded into the NEXT round's aggregate with its weight
discounted by ``1/(1+staleness)^beta``
(p2pfl_tpu.parallel.federated.staleness_scale — the same host-side f32
formula the SPMD plane applies as a mix-column scale, so the two
planes' weighting stays bit-comparable). The discount is applied to
the entry's WEIGHT at add time: staleness is a property of the update
itself, so scaling once at the entry point composes correctly with
partial-aggregation forwarding (weighted means carry weights) and
never compounds, unlike reputation scaling which is receiver-context
and therefore applies only at finish.

The math is the pure aggregator from p2pfl_tpu.core.aggregators over a
stacked tree — device-jittable even in the socket path.
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import Any

import jax
import numpy as np

from p2pfl_tpu.core.aggregators import Aggregator, FedAvg
from p2pfl_tpu.core.pytree import tree_stack
from p2pfl_tpu.core.serialize import (
    decode_parameters,
    encode_parameters,
    own_params,
)
from p2pfl_tpu.obs import flight
from p2pfl_tpu.obs.trace import get_tracer
from p2pfl_tpu.p2p.aggd import SlotEntry, fuse_numpy
from p2pfl_tpu.parallel.federated import staleness_scale

Params = Any


class AggregationSession:
    """One round's aggregation state for one node."""

    def __init__(self, aggregator: Aggregator | None = None,
                 timeout_s: float = 60.0, reputation=None,
                 lane: str | None = None, min_received: float = 1.0,
                 staleness_beta: float = 0.0, masker=None):
        self.aggregator = aggregator or FedAvg()
        #: optional privacy.secagg.PairwiseMasker — when set, entries
        #: are pairwise-masked uint64 trees (weights already folded in
        #: at quantize time): fusion is the exact modular sum, and
        #: _finish unmasks/dequantizes against the round-start
        #: reference. config.schema refuses the planes that need raw
        #: per-entry updates (reputation scoring, the sidecar fuse).
        self.masker = masker
        self.timeout_s = timeout_s  # AGGREGATION_TIMEOUT
        #: async close quorum as a fraction of the expected train set;
        #: 1.0 = classic synchronous behavior (full coverage or timeout)
        self.min_received = float(min_received)
        #: staleness discount exponent (0 = stale entries weigh fresh)
        self.staleness_beta = float(staleness_beta)
        # obs: the owning node's trace lane (k nodes share a process
        # tracer in packed launch layouts — the lane attributes spans)
        self._tracer = get_tracer()
        self._lane = lane
        #: optional adversary.ReputationMonitor shared across rounds:
        #: scores this session's entries at finish time and rescales
        #: their weights by contributor trust (see _finish/_aggregate)
        self.reputation = reputation
        #: round-start params of the session's owner — the delta
        #: reference for reputation scoring (set_reference per round)
        self.reference: Params | None = None
        #: cumulative seconds spent fusing models (numpy/device/sidecar
        #: paths alike) — always-on plain-float accounting; the node's
        #: per-round critical-path snapshot diffs a round-start mark
        #: against it, so it deliberately survives clear()
        self.agg_wall_s = 0.0
        self.models: dict[frozenset[int], tuple[Params, float]] = {}
        self.train_set: frozenset[int] = frozenset()
        self.waiting = False
        self.done = asyncio.Event()
        self.result: tuple[Params, tuple[int, ...]] | None = None
        self._deadline: float | None = None
        # partial-aggregation memo: the gossip loop asks for the same
        # (store, peer-coverage) combination every tick and for every
        # same-coverage target, and each miss costs a tree_stack +
        # aggregator pass on device. Keyed by the peer's coverage set;
        # invalidated whenever the store changes.
        self._partial_memo: dict[
            frozenset[int], tuple[Params, tuple[int, ...], float] | None
        ] = {}

    # -- setup ----------------------------------------------------------
    def set_nodes_to_aggregate(self, train_set) -> None:
        self.train_set = frozenset(int(i) for i in train_set)
        self._deadline = time.monotonic() + self.timeout_s
        flight.record("session.open", lane=self._lane,
                      train_set=sorted(self.train_set),
                      quorum=self.quorum())

    def set_waiting_aggregated_model(self) -> None:
        """TRAINER/PROXY/IDLE: adopt the next aggregate received."""
        self.waiting = True

    def set_reference(self, params: Params) -> None:
        """Round-start params — what this node's cohort trained FROM.
        Entry deltas for reputation scoring are measured against it."""
        self.reference = params

    # -- state ----------------------------------------------------------
    @property
    def covered(self) -> frozenset[int]:
        out: frozenset[int] = frozenset()
        for key in self.models:
            out = out | key
        return out

    def timed_out(self) -> bool:
        return self._deadline is not None and time.monotonic() > self._deadline

    @property
    def async_mode(self) -> bool:
        return self.min_received < 1.0

    def quorum(self) -> int:
        """Entries-covered threshold that closes an async round."""
        n = len(self.train_set)
        if not self.async_mode:
            return n
        return max(1, math.ceil(self.min_received * n))

    def quorum_met(self) -> bool:
        return bool(self.train_set) and (
            len(self.covered & self.train_set) >= self.quorum()
        )

    # -- adding models ---------------------------------------------------
    def add_model(self, params: Params, contributors, weight: float,
                  staleness: float = 0.0,
                  parent: str | None = None) -> tuple[int, ...]:
        """Returns the contributors now covered (broadcast as
        MODELS_AGGREGATED, node.py:363-369). Empty tuple = rejected.

        ``staleness`` (rounds-behind, async mode) discounts the entry's
        weight by ``staleness_scale`` at entry time — see module doc.
        ``parent`` is the sender's wire-propagated tx span id (the
        ``tc`` header): when present, this span records it so the
        merged trace carries a true cross-process causal edge.
        """
        with self._tracer.span(
            "session.add_model", lane=self._lane,
            args={"parent": parent} if parent is not None else None,
        ):
            if staleness > 0.0 and self.staleness_beta > 0.0:
                weight = float(weight) * float(
                    staleness_scale(staleness, self.staleness_beta)
                )
            return self._add_model(params, contributors, weight)

    def _add_model(self, params: Params, contributors,
                   weight: float) -> tuple[int, ...]:
        contrib = frozenset(int(i) for i in contributors)
        if not contrib:
            return ()
        if self.waiting:
            # owning-copy boundary: the adopted tree's leaves are views
            # into the received wire blob — sever them so the blob is
            # collectable once the learner holds the params
            self.result = (own_params(params), tuple(sorted(contrib)))
            self.done.set()
            return tuple(sorted(contrib))
        if contrib <= self.covered:
            return ()  # nothing new (aggregator.py:149 overlap guard)
        # accept only if every contributor the incoming model shares
        # with our store is explained by stored models it supersedes
        # (k ⊆ contrib) — otherwise a partially-overlapping partial
        # (e.g. {B,C} arriving over stored {C,D}) would double-count
        # the shared contributor in the weighted mean
        evict = [k for k in self.models if k <= contrib]
        explained: frozenset[int] = frozenset()
        for k in evict:
            explained = explained | k
        if (contrib & self.covered) - explained:
            return ()  # overlapping but not superseding — reject
        for key in evict:
            self._evict_entry(self.models[key])
            del self.models[key]
        self.models[contrib] = (params, float(weight))
        self._partial_memo.clear()  # store changed; memoed partials stale
        if self.train_set and (
            self.covered >= self.train_set
            or (self.async_mode and self.quorum_met())
        ):
            if self.async_mode and not self.covered >= self.train_set:
                flight.record("session.quorum", lane=self._lane,
                              covered=sorted(self.covered),
                              quorum=self.quorum())
            self._finish()
        return tuple(sorted(self.covered))

    def _evict_entry(self, entry) -> None:
        """Hook: ``entry`` (a ``(params, weight)`` tuple) is about to
        be superseded out of the store. SidecarSession releases the
        entry's shm slot here; the inline session has nothing to do."""

    # -- partial aggregation for a peer ----------------------------------
    def get_partial_aggregation(
        self, peer_has
    ) -> tuple[Params, tuple[int, ...], float] | None:
        """Aggregate of stored models containing no contributor the
        peer already has; None if there is nothing new to send.
        Memoized per peer-coverage set until the store changes."""
        peer = frozenset(int(i) for i in peer_has)
        if peer in self._partial_memo:
            return self._partial_memo[peer]
        send = [
            (p, k, w) for k, (p, w) in self.models.items() if not (k & peer)
        ]
        if not send:
            self._partial_memo[peer] = None
            return None
        params, contribs, weight = self._aggregate(
            [(p, w) for p, k, w in send]
        )
        all_contrib: frozenset[int] = frozenset()
        for _, k, _ in send:
            all_contrib = all_contrib | k
        out = (params, tuple(sorted(all_contrib)), weight)
        self._partial_memo[peer] = out
        return out

    # -- completion -------------------------------------------------------
    def check_and_run(self) -> bool:
        """Called by the node loop: finish on coverage (async: quorum)
        or timeout with whatever arrived (aggregator.py:53-76)."""
        if self.done.is_set():
            return True
        if self.models and (
            (self.train_set and self.covered >= self.train_set)
            or (self.async_mode and self.quorum_met())
            or self.timed_out()
        ):
            self._finish()
            return True
        return False

    def _finish(self) -> None:
        # reputation applies ONLY at finish, never to the partial
        # aggregates gossiped mid-round: a partial is re-weighted again
        # inside every receiver's own finish, so scaling it at build
        # time would compound the trust discount sender x receiver
        keys = list(self.models.keys())
        if (self.masker is None
                and self.reputation is not None
                and self.reference is not None
                and len(self.models) >= 3):
            # observe BEFORE aggregating: unlike SPMD (where scores
            # come out of the jitted round fn and can only shape the
            # NEXT round's mix), both steps here are host-side at the
            # same instant — same-round exclusion costs nothing and
            # stops a first-round attacker before any poisoned
            # aggregate lands. Under 3 entries the cohort median/
            # direction is meaningless (2 rows score symmetrically) —
            # no observation, trust persists.
            self.reputation.observe_entries(
                self.reference,
                [(k, p) for k, (p, _) in self.models.items()],
            )
        params, contribs, total = self._aggregate(
            list(self.models.values()), keys=keys
        )
        if self.masker is not None:
            # quorum close is the ONLY point masked bits become a
            # model: reconstruct + subtract evicted members' mask
            # residue, then dequantize against the round-start
            # reference (the dtype/shape template)
            from p2pfl_tpu.privacy.secagg import SecaggError

            if self.reference is None:
                raise SecaggError(
                    "masked session closed without a round-start "
                    "reference (set_reference) to dequantize against"
                )
            params, unmasked_dead = self.masker.unmask(
                params, total, self.covered, self.reference
            )
            flight.record("secagg.unmask", lane=self._lane,
                          entries=len(keys),
                          covered=sorted(self.covered),
                          dead=unmasked_dead)
        # owning-copy boundary at session close: the multi-entry numpy
        # result already owns its accumulators (free pass-through), but
        # a single-entry round returns the stored tree as-is — its
        # leaves still view the received wire blob, and adopting views
        # would pin the whole blob for the life of the model
        self.result = (own_params(params), tuple(sorted(self.covered)))
        flight.record("session.close", lane=self._lane,
                      entries=len(keys), covered=sorted(self.covered),
                      timed_out=self.timed_out())
        self.done.set()

    def _aggregate(self, entries,
                   keys=None) -> tuple[Params, tuple[int, ...], float]:
        if len(entries) == 1:
            p, w = entries[0]
            return p, (), w
        if self.masker is not None:
            # masked entries carry their weight folded into the
            # quantized integers — fusion is the exact mod-2^64 tree
            # sum, NO re-weighting (a float weighted mean would
            # destroy mask cancellation). Partial aggregates built
            # here stay in the masked domain and compose downstream.
            from p2pfl_tpu.privacy.secagg import masked_sum

            t0 = time.perf_counter()
            with self._tracer.span(
                "session.aggregate", lane=self._lane,
                args={"path": "masked_modular", "n": len(entries)},
            ):
                tree, total = masked_sum(entries)
            self.agg_wall_s += time.perf_counter() - t0
            return tree, (), total
        # ONE effective-weights computation feeding BOTH execution
        # paths below — reputation (or any future weight shaping)
        # cannot be silently dropped by the numpy fast path
        weights = np.asarray([w for _, w in entries], np.float32)
        if keys is not None and self.reputation is not None:
            weights = weights * self.reputation.entry_scales(keys)
        if type(self.aggregator) is FedAvg:
            return self._aggregate_numpy(entries, weights)
        t0 = time.perf_counter()
        with self._tracer.span(
            "session.aggregate", lane=self._lane,
            args={"path": "stacked_device", "n": len(entries)},
        ):
            stacked = tree_stack(
                [jax.tree.map(np.asarray, p) for p, _ in entries]
            )
            agg = self.aggregator(stacked, weights)
            out = jax.tree.map(np.asarray, agg), (), float(weights.sum())
        self.agg_wall_s += time.perf_counter() - t0
        return out

    def _aggregate_numpy(self, entries, weights):
        # Host fast path. Models in the socket session are host
        # arrays on both sides (deserialized on arrival, re-encoded
        # on send), and the entry count varies with gossip timing —
        # pushing every combination through jnp.stack + eager XLA
        # reductions compiles a fresh program per distinct stack
        # size mid-round (measured: ~450 compiles / 2 rounds on the
        # 24-node uncapped bench, ~30% of wall). A numpy weighted
        # mean is shape-oblivious and stays off-device. The kernel
        # itself lives in p2p.aggd (fuse_numpy) so the sidecar worker
        # runs the IDENTICAL code — tolerance-0 parity by sharing.
        t0 = time.perf_counter()
        with self._tracer.span(
            "session.aggregate", lane=self._lane,
            args={"path": "numpy_fast", "n": len(entries)},
        ):
            tree, total = fuse_numpy([p for p, _ in entries], weights)
        self.agg_wall_s += time.perf_counter() - t0
        return tree, (), total

    def clear(self) -> None:
        """Reset for the next round (aggregator.py:231-238)."""
        self.models.clear()
        self._partial_memo.clear()
        self.reference = None  # reputation state itself persists
        self.train_set = frozenset()
        self.waiting = False
        self.result = None
        self.done = asyncio.Event()
        self._deadline = None


class SidecarSession(AggregationSession):
    """AggregationSession with the payload plane delegated to the
    shared-memory sidecar (p2p.aggd). node.py drives both session kinds
    through the same calls — set_nodes_to_aggregate / add_model /
    check_and_run / ``done`` + ``result`` — plus ``add_slot`` for
    payloads the protocol reader landed straight into the arena.

    Payload-plane differences from the base class:

    - entries are ``SlotEntry`` markers (undecoded payload bytes in
      the arena) or raw wire blobs (lease-failure fallback), never
      decoded trees: the event loop never touches payload bytes;
    - ``_finish`` ships the fuse request to the sidecar process and
      completes asynchronously — ``check_and_run`` reports False while
      the fuse is in flight so the node's gossip loop keeps ticking
      until ``done`` actually sets. The store is frozen once the fuse
      starts (late entries are rejected; their slots release), so the
      fused set and ``covered`` cannot diverge mid-flight;
    - reputation ``entry_scales`` still shape the effective weights
      (computed here, applied inside the worker's weighted mean), but
      ``observe_entries`` is skipped: scoring needs decoded trees,
      which this plane never has on the loop. config.schema refuses
      ``adversary.reputation`` + sidecar for exactly this reason;
    - partial gossip serves only the node's OWN model: the schema
      pins the sidecar plane to a fully-connected topology, where
      every contributor's update already reached every aggregator
      directly and re-forwarding third-party bytes is duplication;
    - a dead/stalled sidecar degrades loudly (``aggd.fallback`` flight
      event) to in-process aggregation off the loop — same kernel,
      same result, no round lost.
    """

    def __init__(self, aggregator: Aggregator | None = None,
                 timeout_s: float = 60.0, reputation=None,
                 lane: str | None = None, min_received: float = 1.0,
                 staleness_beta: float = 0.0, client=None, spawn=None):
        super().__init__(aggregator, timeout_s=timeout_s,
                         reputation=reputation, lane=lane,
                         min_received=min_received,
                         staleness_beta=staleness_beta)
        #: the host's shared aggd.SidecarClient (one per process)
        self.client = client
        #: task spawner with node._track_task's (coro, what) signature;
        #: None = tests driving the session without a node
        self._spawn = spawn
        # the node's own trained model, kept decoded for partial gossip
        self._own: tuple[Params, tuple[int, ...], float] | None = None
        self._fusing = False
        self._fuse_task = None

    # -- adding models ---------------------------------------------------
    def add_model(self, params: Params, contributors, weight: float,
                  staleness: float = 0.0,
                  parent: str | None = None) -> tuple[int, ...]:
        """Tree entry point — the node's OWN model (and the waiting
        adoption path, which defers to the base class). The tree is
        encoded into a leased slot so every fuse entry is slot-backed;
        if the arena can't take it, the wire blob itself is stored and
        ships to the worker through the descriptor queue."""
        if self.waiting:
            return super().add_model(params, contributors, weight,
                                     staleness, parent=parent)
        with self._tracer.span(
            "session.add_model", lane=self._lane,
            args={"parent": parent} if parent is not None else None,
        ):
            if staleness > 0.0 and self.staleness_beta > 0.0:
                weight = float(weight) * float(
                    staleness_scale(staleness, self.staleness_beta)
                )
            contrib = tuple(int(i) for i in contributors)
            blob = encode_parameters(params, contrib, max(1, int(weight)))
            entry: Any = blob
            lease = self.client.lease(len(blob)) if self.client else None
            if lease is not None:
                slot, mv = lease
                mv[: len(blob)] = blob
                entry = SlotEntry(slot, len(blob))
            covered = self._add_model(entry, contrib, weight)
            if covered:
                self._own = (params, contrib, float(weight))
            elif isinstance(entry, SlotEntry):
                self.client.release(entry.slot)
            return covered

    def add_slot(self, slot: int, length: int, contributors,
                 weight: float, staleness: float = 0.0,
                 parent: str | None = None) -> tuple[int, ...]:
        """Slot-backed add: the payload stays undecoded in the arena.
        Takes ownership of the slot — a rejected entry's slot is
        released here, an accepted one when its fuse (or clear/crash
        cleanup) consumes it. Never valid on a waiting session (the
        node routes adoption payloads through the decode path)."""
        with self._tracer.span(
            "session.add_model", lane=self._lane,
            args={"parent": parent} if parent is not None else None,
        ):
            if staleness > 0.0 and self.staleness_beta > 0.0:
                weight = float(weight) * float(
                    staleness_scale(staleness, self.staleness_beta)
                )
            covered = self._add_model(SlotEntry(slot, length),
                                      contributors, weight)
            if not covered and self.client is not None:
                self.client.release(slot)
            return covered

    def add_blob(self, blob, contributors, weight: float,
                 staleness: float = 0.0,
                 parent: str | None = None) -> tuple[int, ...]:
        """Raw-wire-blob add — the arena was exhausted when the socket
        sink asked, so the payload arrived as loop-side bytes. It still
        never gets decoded here: a lease retry may land it in a slot
        freed since (rounds release in bursts), otherwise the blob
        itself ships to the worker through the descriptor queue."""
        with self._tracer.span(
            "session.add_model", lane=self._lane,
            args={"parent": parent} if parent is not None else None,
        ):
            if staleness > 0.0 and self.staleness_beta > 0.0:
                weight = float(weight) * float(
                    staleness_scale(staleness, self.staleness_beta)
                )
            contrib = tuple(int(i) for i in contributors)
            entry: Any = bytes(blob)
            lease = self.client.lease(len(blob)) if self.client else None
            if lease is not None:
                slot, mv = lease
                mv[: len(blob)] = blob
                entry = SlotEntry(slot, len(blob))
            covered = self._add_model(entry, contrib, weight)
            if not covered and isinstance(entry, SlotEntry):
                self.client.release(entry.slot)
            return covered

    def _add_model(self, params, contributors, weight):
        if not self.waiting and (self._fusing or self.done.is_set()):
            # the round is closing: a late entry can't make this fuse,
            # and mutating the store mid-fuse would let a superseding
            # eviction release a slot the worker is still reading
            return ()
        return super()._add_model(params, contributors, weight)

    def _evict_entry(self, entry) -> None:
        p, _w = entry
        if isinstance(p, SlotEntry) and self.client is not None:
            self.client.release(p.slot)

    # -- partial aggregation for a peer ----------------------------------
    def get_partial_aggregation(self, peer_has):
        """Own-model-only: stored third-party entries are undecoded
        slots, and on the full mesh the schema enforces every one of
        them already reached the peer directly from its origin."""
        if self._own is None:
            return None
        params, contribs, weight = self._own
        if {int(i) for i in peer_has} & set(contribs):
            return None
        return params, contribs, weight

    # -- completion -------------------------------------------------------
    def check_and_run(self) -> bool:
        if self.done.is_set():
            return True
        if not self._fusing and self.models and (
            (self.train_set and self.covered >= self.train_set)
            or (self.async_mode and self.quorum_met())
            or self.timed_out()
        ):
            self._finish()
        # while the fuse is in flight this stays False — the gossip
        # loop keeps ticking until the result actually publishes
        return self.done.is_set()

    def _finish(self) -> None:
        if self._fusing or self.done.is_set():
            return
        self._fusing = True
        keys = list(self.models.keys())
        entries = list(self.models.values())
        covered = tuple(sorted(self.covered))
        weights = np.asarray([w for _, w in entries], np.float32)
        if self.reputation is not None:
            # entry_scales apply; observe_entries is structurally
            # impossible here (undecoded entries) — see class doc
            weights = weights * self.reputation.entry_scales(keys)
        coro = self._fuse_and_close(entries, weights, covered)
        if self._spawn is not None:
            self._spawn(coro, "aggd_fuse")
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (synchronous unit-test driver): fall back inline
            coro.close()
            params = self._fallback_fuse(entries, weights)
            self._release_entries(entries)
            self._publish(params, covered, len(entries))
            return
        self._fuse_task = loop.create_task(coro)

    async def _fuse_and_close(self, entries, weights, covered) -> None:
        loop = asyncio.get_running_loop()
        t_fuse0 = time.perf_counter()
        n = len(entries)
        req = []
        for (p, _w), w in zip(entries, weights):
            if isinstance(p, SlotEntry):
                req.append(("s", p.slot, p.length, float(w)))
            elif isinstance(p, (bytes, bytearray)):
                req.append(("b", bytes(p), float(w)))
            else:  # decoded tree (shouldn't occur; belt and braces)
                req.append(("b", encode_parameters(p, (), 1), float(w)))
        out = None
        if self.client is not None:
            out = await self.client.fuse(
                req, timeout_s=max(5.0, self.timeout_s))
        if out is not None:
            slot, length, _stats = out
            with self._tracer.span(
                "session.fuse", lane=self._lane,
                args={"path": "sidecar", "n": n},
            ):
                try:
                    payload = await loop.run_in_executor(
                        None, _decode_owned,
                        self.client.view(slot, length))
                finally:
                    self.client.release(slot)
            params = payload.params
        else:
            if self.client is not None:
                self.client.fallbacks += 1
            flight.record("aggd.fallback", lane=self._lane, entries=n)
            params = await loop.run_in_executor(
                None, self._fallback_fuse, entries, weights)
        self.agg_wall_s += time.perf_counter() - t_fuse0
        self._release_entries(entries)
        self._publish(params, covered, n)

    def _fallback_fuse(self, entries, weights):
        """In-process fuse over the session's own entries — same
        kernel (aggd.fuse_numpy), run off-loop, used when the sidecar
        is dead/stalled or there is no loop at all."""
        trees = []
        for p, _w in entries:
            if isinstance(p, SlotEntry):
                trees.append(_decode_owned(
                    self.client.view(p.slot, p.length)).params)
            elif isinstance(p, (bytes, bytearray)):
                trees.append(decode_parameters(p).release().params)
            else:
                trees.append(p)
        if len(trees) == 1:
            return trees[0]  # _aggregate's n==1 short-circuit parity
        tree, _total = fuse_numpy(trees, weights)
        return tree

    def _release_entries(self, entries) -> None:
        if self.client is None:
            return
        for p, _w in entries:
            if isinstance(p, SlotEntry):
                self.client.release(p.slot)
        # the store still names these slots for coverage bookkeeping;
        # null the markers so clear()/crash cleanup can't release a
        # slot that another session has since re-leased
        for k, (p, w) in list(self.models.items()):
            if isinstance(p, SlotEntry):
                self.models[k] = (None, w)

    def _publish(self, params, covered, n_entries) -> None:
        self.result = (own_params(params), covered)
        flight.record("session.close", lane=self._lane,
                      entries=n_entries, covered=list(covered),
                      timed_out=self.timed_out(), plane="sidecar")
        self.done.set()

    def release_entries(self) -> None:
        """Release every slot this session still holds — crash/stop
        teardown and the pre-round clear() both route through here so
        an interrupted round can't strand arena slots."""
        if self.client is None:
            return
        for k, (p, w) in list(self.models.items()):
            if isinstance(p, SlotEntry):
                self.client.release(p.slot)
                self.models[k] = (None, w)

    def clear(self) -> None:
        self.release_entries()
        super().clear()
        self._own = None
        self._fusing = False
        self._fuse_task = None


def _decode_owned(blob):
    """decode + sever in one executor hop: the returned payload's
    leaves own their memory, so the shm slot (or blob) backing the
    decode is immediately reusable."""
    return decode_parameters(blob).release()
