"""Parallel layer: device mesh, ICI transport, SPMD federated rounds.

This is the TPU-native replacement for the reference's entire P2P
runtime (fedstellar/base_node.py, node_connection.py, gossiper.py,
communication_protocol.py — threads, TCP sockets, 2 KB fragments,
pickle): federated node *i* lives at mesh position *i* along a
``nodes`` axis; a whole federated round (local epochs → neighbor
weight exchange → per-node aggregation → metrics) is ONE jit-compiled
XLA program. Weight "gossip" is a masked collective over ICI, not a
1 Hz socket loop.
"""

from p2pfl_tpu.parallel.mesh import (
    federation_mesh,
    shard_stacked,
    stacked_sharding,
)
from p2pfl_tpu.parallel.federated import (
    FederatedState,
    build_round_fn,
    build_round_fn_sparse,
    init_federation,
    make_mixing_matrix,
)
from p2pfl_tpu.parallel.transport import (
    MeshTransport,
    edge_offsets,
    neighbor_exchange,
)

__all__ = [
    "federation_mesh",
    "shard_stacked",
    "stacked_sharding",
    "FederatedState",
    "build_round_fn",
    "build_round_fn_sparse",
    "init_federation",
    "make_mixing_matrix",
    "MeshTransport",
    "edge_offsets",
    "neighbor_exchange",
]
