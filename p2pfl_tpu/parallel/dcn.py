"""Multi-host (DCN) federation: ``jax.distributed`` + one global mesh.

SURVEY.md §7 phase 6. The reference cannot span hosts without its TCP
socket mesh and hand-rolled wire grammar; here a multi-host federation
is the SAME SPMD round program, compiled over a global device mesh
that spans every process in a ``jax.distributed`` job — weight
exchange rides ICI within a host/slice and DCN across hosts, scheduled
by XLA's collectives, with no bespoke message layer on the data path.

Topology of a job: each host runs one process with its local devices;
``jax.distributed.initialize`` wires them into one runtime
(coordinator at process 0). Federated node *i* lives on global device
*i* — data for node *i* is materialized ONLY on the process that owns
that device (``jax.make_array_from_callback`` slices the host copy).

Two entry points:

- ``run_scenario(config_path)`` — the real mode: the FULL ``Scenario``
  surface (any topology/federation/aggregator, train-set votes, fault
  injection, checkpoint/resume, metrics + monitoring) over the global
  mesh. ``MeshTransport`` detects the multi-process runtime and places
  every array with ``make_array_from_callback``; per-node host reads
  ride ``process_allgather``; process 0 owns logs and checkpoints.
- ``run_federation(...)`` — the minimal hardcoded demo kept as a
  smoke target (fully-connected DFL FedAvg, one jit, no scenario
  machinery).

Simulation recipe (no cluster needed — the 2-process tests in
tests/test_dcn.py): run N processes on localhost, each with
``--xla_force_host_platform_device_count=K`` virtual CPU devices, all
pointing at the same coordinator:

    python -m p2pfl_tpu.parallel.dcn --coordinator 127.0.0.1:9911 \
        --num-processes 2 --process-id {0,1} --platform cpu \
        [--config scenario.json | --rounds 1]
"""

from __future__ import annotations

import argparse
import json
import sys


def initialize(coordinator_address: str, num_processes: int,
               process_id: int) -> None:
    """Join this process into the distributed runtime (idempotent).

    Must run before anything touches the XLA backend — so no
    ``jax.devices()``/``device_put`` before this.
    """
    import jax

    if jax.distributed.is_initialized() or num_processes == 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_global(x, sharding):
    """Materialize a host array as a global sharded array: each process
    fills only the shards it owns (the DCN-safe device_put).

    ``dtype`` is passed explicitly: a process whose devices all fall
    OUTSIDE the federation mesh (e.g. 6 nodes on 4 hosts x 2 devices —
    the divisor rule uses 6 of 8 devices, host 3 owns none) fills no
    shards, and make_array_from_callback cannot infer the dtype from
    an empty shard list."""
    import jax
    import numpy as np

    x = np.asarray(x)
    return jax.make_array_from_callback(
        x.shape, sharding, lambda idx: x[idx], dtype=x.dtype
    )


def run_federation(rounds: int = 1, dataset: str = "mnist",
                   model_name: str = "mnist-mlp",
                   samples_per_node: int = 150,
                   learning_rate: float = 0.05, seed: int = 0,
                   exchange_dtype: str | None = None) -> dict:
    """One federation spanning every device of every process: node i on
    global device i, fully-connected DFL FedAvg. Every process executes
    this same function (SPMD); returns globally-agreed metrics.

    ``exchange_dtype`` ("bf16") down-casts the mix contraction's
    inputs — the same wire-precision knob the single-host builders
    take, here shrinking the DCN (cross-host) exchange bytes.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from p2pfl_tpu.config.schema import DataConfig
    from p2pfl_tpu.datasets import FederatedDataset
    from p2pfl_tpu.learning.learner import make_step_fns
    from p2pfl_tpu.models import get_model
    from p2pfl_tpu.parallel.federated import (
        build_eval_fn,
        build_round_fn,
        init_federation,
        make_round_plan,
    )
    from p2pfl_tpu.parallel.mesh import NODES_AXIS, federation_mesh
    from p2pfl_tpu.topology.topology import generate_topology

    n = len(jax.devices())  # ALL global devices — one federated node each
    mesh = federation_mesh()
    stacked = NamedSharding(mesh, P(NODES_AXIS))
    replicated = NamedSharding(mesh, P())

    # identical on every process (deterministic seeds) — each process
    # materializes only its own devices' node shards
    ds = FederatedDataset.make(
        DataConfig(dataset=dataset, samples_per_node=samples_per_node), n
    )
    x, y, smask, nsamp = ds.stacked()
    fns = make_step_fns(get_model(model_name), learning_rate=learning_rate,
                        batch_size=32)
    topo = generate_topology("fully", n)
    plan = make_round_plan(topo, ["aggregator"] * n, "DFL")

    def g(a):
        return make_global(a, stacked)

    fed_host = jax.tree.map(np.asarray, init_federation(
        fns, jnp.asarray(np.asarray(x)[0, :1]), n, seed=seed))
    fed = jax.tree.map(
        lambda a: g(a) if a.ndim >= 1 and a.shape[0] == n
        else make_global(a, replicated),
        fed_host,
    )
    args = [g(a) for a in (x, y, smask, nsamp, plan.mix, plan.adopt,
                           plan.trains)]
    ex_dt = jnp.bfloat16 if exchange_dtype in ("bf16", "int8") else None
    round_fn = jax.jit(build_round_fn(fns, epochs=1, exchange_dtype=ex_dt),
                       donate_argnums=(0,))
    eval_fn = jax.jit(build_eval_fn(fns))

    for _ in range(rounds):
        fed, metrics = round_fn(fed, *args)
    losses = multihost_utils.process_allgather(metrics["train_loss"], tiled=True)
    x_test = make_global(ds.x_test[:1000], replicated)
    y_test = make_global(ds.y_test[:1000], replicated)
    acc = multihost_utils.process_allgather(
        eval_fn(fed, x_test, y_test)["accuracy"], tiled=True
    )
    # fully-connected DFL FedAvg: params must agree ACROSS processes
    leaf = jax.tree.leaves(fed.states.params)[0]
    leaf_all = multihost_utils.process_allgather(leaf, tiled=True)
    spread = float(np.max(np.abs(
        leaf_all.reshape(n, -1) - leaf_all.reshape(n, -1)[0]
    )))
    return {
        "process": jax.process_index(),
        "n_processes": jax.process_count(),
        "n_nodes": n,
        "rounds": rounds,
        "mean_loss": float(np.mean(losses)),
        "mean_accuracy": float(np.mean(acc)),
        "cross_process_param_spread": spread,
    }


def run_scenario(config_path: str) -> dict:
    """The REAL DCN mode: drive a full ``Scenario`` — topology,
    federation scheme, robust aggregators, train-set votes, fault
    injection, checkpoint/resume, metrics/monitoring — over the global
    multi-process mesh. ``jax.distributed`` must be initialized first;
    every process calls this with the same scenario file and executes
    the same SPMD round program (MeshTransport places each node's
    shards only on the process that owns its device; process 0 owns
    the log artifacts)."""
    import jax
    import numpy as np

    from p2pfl_tpu.config.schema import ScenarioConfig
    from p2pfl_tpu.federation.scenario import Scenario

    cfg = ScenarioConfig.load(config_path)
    scenario = Scenario(cfg)
    result = scenario.run()
    scenario.close()
    return {
        "process": jax.process_index(),
        "n_processes": jax.process_count(),
        "n_nodes": cfg.n_nodes,
        "federation": cfg.federation,
        "topology": cfg.topology,
        "aggregator": cfg.aggregator,
        "sparse_transport": scenario.sparse_transport,
        "rounds": result.rounds_run,
        "final_accuracy": round(float(result.final_accuracy), 4),
        "min_accuracy": round(float(result.min_accuracy), 4),
        "mean_round_s": round(
            float(np.mean(result.round_times_s)), 4
        ) if result.round_times_s else None,
        "leader": scenario.leader,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.parallel.dcn")
    ap.add_argument("--coordinator", default="127.0.0.1:9911")
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (cpu for the simulation recipe)")
    ap.add_argument("--rounds", type=int, default=1)
    ap.add_argument("--dataset", default="mnist")
    ap.add_argument("--model", default="mnist-mlp")
    ap.add_argument("--exchange-dtype", default=None,
                    choices=("f32", "bf16"),
                    help="wire precision for the demo federation's "
                         "exchange (the config knob is wire_dtype)")
    ap.add_argument("--config", default=None,
                    help="ScenarioConfig JSON: run the FULL scenario "
                         "surface over the global mesh instead of the "
                         "minimal demo federation")
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    initialize(args.coordinator, args.num_processes, args.process_id)
    if args.config:
        result = run_scenario(args.config)
    else:
        result = run_federation(rounds=args.rounds, dataset=args.dataset,
                                model_name=args.model,
                                exchange_dtype=args.exchange_dtype)
    print("P2PFL_DCN_RESULT " + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
