"""The federated round as one SPMD program.

Reference semantics being reproduced (fedstellar/node.py round state
machine, SURVEY.md §3.3-3.4), re-expressed as fixed-shape device math:

- every node trains local epochs      → vmapped ``lax.scan`` training
- weights flow along topology edges   → masked collective (an einsum
  over the gathered node axis; XLA lowers the gather to all-gather
  over ICI when the node axis is sharded)
- each aggregator fuses what arrived  → per-row weighted FedAvg (or a
  robust aggregator vmapped over rows)
- trainers/idle adopt an aggregate    → ``adopt`` index gather
- dead nodes (heartbeat eviction / fault injection) → ``alive`` mask:
  they neither contribute weight nor update their own params.

Per-round *data* (who aggregates whom ``M``, whose aggregate each node
adopts ``adopt``, who is alive) are device arrays, not compile-time
constants — so DFL, CFL, SDFL leadership rotation, and mid-run faults
all reuse ONE compiled program.

The three federation schemes map as (node.py:427-524 role branches):
- DFL:  M = adjacency + self-loops; adopt = identity.
- CFL:  M[server] = everyone; adopt = server for all nodes.
- SDFL: like CFL with the current leader; leader rotates on the host
        (node.py:649-686 TRANSFER_LEADERSHIP analog).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from p2pfl_tpu.core.aggregators import Aggregator, FedAvg
from p2pfl_tpu.learning.learner import StepFns, TrainState
from p2pfl_tpu.topology.topology import Topology

Params = Any


class FederatedState(struct.PyTreeNode):
    """Whole-federation state: every leaf has a leading ``[n]`` axis.

    ``stale`` is the double buffer for ``exchange_overlap="staged"``:
    ``(prev post-fit params stack, prev contribution weights [n])`` —
    what round r ships to neighbors while round r's fit is still
    running. ``None`` (the default) everywhere the mode is off, so
    existing constructors, specs and tests are untouched."""

    states: TrainState  # stacked per-node TrainState
    alive: jax.Array  # [n] bool
    round: jax.Array  # scalar int32
    stale: Any = None  # (params stack, weights [n]) | None


@dataclasses.dataclass(frozen=True)
class RoundPlan:
    """Host-computed per-round schedule, fed to the jitted round fn.

    ``mix``    [n,n] float32 — row i: relative weight of node j's model
               in i's aggregate (0 = no edge). Sample-count and alive
               weighting are folded in by the round fn.
    ``adopt``  [n] int32 — node i installs the aggregate computed at
               row ``adopt[i]`` (identity for DFL; leader for CFL/SDFL).
    ``trains`` [n] bool — which nodes run local SGD this round
               (trainer/aggregator/server yes; proxy/idle no —
               node.py:492-524).
    """

    mix: np.ndarray
    adopt: np.ndarray
    trains: np.ndarray


def make_round_plan(
    topology: Topology,
    roles: list[str],
    federation: str = "DFL",
    leader: int = 0,
) -> RoundPlan:
    n = topology.n
    trains = np.array([r in ("trainer", "aggregator", "server") for r in roles])
    if federation == "DFL":
        mix = topology.adjacency.astype(np.float32) + np.eye(n, dtype=np.float32)
        adopt = np.arange(n, dtype=np.int32)
    elif federation in ("CFL", "SDFL"):
        mix = np.zeros((n, n), np.float32)
        mix[leader] = 1.0  # leader aggregates everyone (incl. itself)
        adopt = np.full((n,), leader, np.int32)
    else:
        raise ValueError(f"unknown federation {federation!r}")
    return RoundPlan(mix=mix, adopt=adopt.astype(np.int32), trains=trains)


def make_mixing_matrix(topology: Topology, scheme: str = "uniform") -> np.ndarray:
    """Expose Topology.mixing_matrix at this layer (decentralized-
    averaging weights; ``W^k`` powers emulate k gossip ticks/round)."""
    return topology.mixing_matrix(scheme).astype(np.float32)


def staleness_scale(staleness, beta: float) -> np.ndarray:
    """Staleness discount ``1 / (1 + s)^beta`` (round 11, elastic
    federation) — THE formula for folding late updates into an
    aggregate, shared verbatim by both planes so their weighting is
    bit-comparable: the socket plane applies it per-entry in
    ``AggregationSession._aggregate``, the SPMD plane as a column scale
    on the mixing matrix (``Scenario._plan_args``), both on the host in
    float32. ``staleness`` is rounds-behind (0 = fresh); negative
    values clamp to fresh; ``beta=0`` is the identity."""
    s = np.maximum(np.asarray(staleness, np.float32), 0.0)
    if beta == 0.0:
        return np.ones_like(s)
    return (1.0 / np.power(1.0 + s, np.float32(beta))).astype(np.float32)


def _tree_sel(cond: jax.Array, a, b):
    """Per-node select: cond [n] broadcast over each stacked leaf."""

    def leaf(x, y):
        c = cond.reshape((cond.shape[0],) + (1,) * (x.ndim - 1))
        return jnp.where(c, x, y)

    return jax.tree.map(leaf, a, b)


def _train_and_select(fns: StepFns, states: TrainState, alive, trains,
                      x, y, smask, epochs: int):
    """Local epochs on every node, keeping updates only where
    ``trains & alive`` (proxy/idle/dead nodes stay frozen —
    node.py:492-524). Shared by the dense and sparse round builders so
    training-selection semantics can't drift between them.

    The selection rides into the SGD step as a per-node update gate
    (learner.train_epochs ``gate``) rather than a post-hoc full-tree
    ``where`` — gated-off params are bit-exact and the round saves two
    whole-model memory passes (~12 ms at the 64-node north star). Only
    the small rng/step leaves still need explicit selection."""
    sel = jnp.logical_and(trains, alive)
    new_states, train_metrics = jax.vmap(
        fns.train_epochs, in_axes=(0, 0, 0, 0, None, 0)
    )(states, x, y, smask, epochs, sel.astype(jnp.float32))
    states = TrainState(
        params=new_states.params,
        opt_state=new_states.opt_state,
        rng=jnp.where(sel[:, None], new_states.rng, states.rng),
        step=jnp.where(sel, new_states.step, states.step),
    )
    return states, train_metrics


def init_federation(
    fns: StepFns, sample_x: jax.Array, n_nodes: int, seed: int = 0,
    same_init: bool = True,
) -> FederatedState:
    """Stacked init. ``same_init=True`` reproduces the reference's
    initial-model diffusion (node.py:299: every node starts from the
    initializer's weights) without the gossip: init once, broadcast."""
    # the pallas_gemm auto-select gate measures candidate kernels at
    # the VMAPPED shape — tell it the federation width before any
    # model application traces (docs/perf.md §6.4)
    from p2pfl_tpu.ops import pallas_gemm

    pallas_gemm.set_nodes_hint(n_nodes)
    rngs = (
        jnp.stack([jax.random.PRNGKey(seed)] * n_nodes)
        if same_init
        else jax.random.split(jax.random.PRNGKey(seed), n_nodes)
    )
    states = jax.vmap(fns.init, in_axes=(0, None))(rngs, sample_x)
    if same_init:
        # distinct per-node training rngs even with identical params
        states = states.replace(
            rng=jax.vmap(jax.random.fold_in, in_axes=(0, 0))(
                states.rng, jnp.arange(n_nodes)
            )
        )
    return FederatedState(
        states=states,
        alive=jnp.ones((n_nodes,), bool),
        round=jnp.int32(0),
    )


def reseed_params(fed: FederatedState, fns: StepFns,
                  params: Params) -> FederatedState:
    """Restart a federation from ONE param tree: every node adopts
    ``params`` with FRESH optimizer state (``fns.tx.init`` per node),
    keeping rng/step/alive/round. The pretrain -> fine-tune handoff of
    the lora bench phase: both A/B arms resume from the identical
    full-weight (or adapter) snapshot, so their accuracies differ only
    by what federation ships, not by where training started."""
    n = fed.alive.shape[0]
    stack = jax.tree.map(
        lambda x: jnp.broadcast_to(
            jnp.asarray(x), (n,) + jnp.shape(jnp.asarray(x))
        ).copy(),
        params,
    )
    states = TrainState(
        params=stack,
        opt_state=jax.vmap(fns.tx.init)(stack),
        rng=fed.states.rng,
        step=fed.states.step,
    )
    return fed.replace(states=states)


def with_staged_buffer(fed: FederatedState) -> FederatedState:
    """Seed the staged-exchange double buffer: the CURRENT params at
    ZERO contribution weight. The first staged round then mixes nothing
    from neighbors (denominator = own fresh weight only) and reduces to
    pure local training — the well-defined cold start of one-round-
    stale gossip (tests pin this)."""
    # copied, not aliased: the round fn donates its input state, and a
    # buffer appearing twice in the donated tree is an XLA error
    return fed.replace(
        stale=(
            jax.tree.map(jnp.copy, fed.states.params),
            jnp.zeros((fed.alive.shape[0],), jnp.float32),
        )
    )


def build_round_fn(
    fns: StepFns,
    aggregator: Aggregator | None = None,
    epochs: int = 1,
    exchange_dtype: Any | None = None,
    shared_aggregate: bool = False,
    identity_adopt: bool = False,
    attack=None,
    malicious: np.ndarray | None = None,
    update_stats: bool = False,
    exchange_overlap: str = "off",
    dp=None,
    dp_mask: np.ndarray | None = None,
) -> Callable:
    """Build the jittable ``round_fn(fed, x, y, mask, n_samples, plan
    arrays) -> (fed, metrics)``.

    FedAvg gets the fast path: per-leaf ``einsum('ij,j...->i...')`` —
    one MXU-friendly contraction per leaf, with the row-normalized
    weight matrix folding topology × alive × sample counts. Robust
    aggregators (Krum/median/trimmed mean) are vmapped per row over the
    gathered stack.

    ``exchange_dtype`` (e.g. ``jnp.bfloat16``) down-casts the model
    stack entering the FedAvg contraction — halving the exchange's HBM
    (and, sharded, ICI) bytes; accumulation stays f32 via
    ``preferred_element_type``. The reference moves f32 pickles
    (lightninglearner.py:73-77); bf16-rounding gossip inputs costs
    ~0.4% relative weight error, re-trained away within the next local
    epoch — the bench's rounds-to-80% guards the claim empirically.
    ``None`` keeps the exchange in full precision (the parity-test
    default).

    ``shared_aggregate=True`` computes ONE robust aggregate from the
    union of the mixing rows instead of one per row — for plans whose
    aggregating rows are all identical (fully-connected DFL, or
    CFL/SDFL where only the leader's row is nonzero). The vmapped
    per-row path is O(n) redundant aggregations and O(n x |params|)
    transient memory for those plans; on big models (ViT + Krum at 32
    nodes) that redundancy is the difference between fitting and
    faulting. Semantically identical where the contract holds; rows
    with no incoming weight still keep their own params.

    ``identity_adopt=True`` is the caller's PROMISE that every plan fed
    to this round fn has ``adopt == arange(n)`` (always true for DFL,
    make_round_plan): the ``agg[adopt]`` gather is a full extra
    read+write pass over the model stack that XLA cannot elide for a
    runtime index array, so the promise buys one whole-stack memory
    pass per round (~4 ms at the 64-node north star). CFL/SDFL route
    through a leader and must keep the default.

    ``attack`` + ``malicious`` inject adversarial nodes: after local
    training and BEFORE the weight exchange, the rows of the params
    stack selected by the STATIC host mask ``malicious`` are replaced
    by ``adversary.poison_update`` of themselves — the same transform
    the socket node applies to its outgoing params, keyed by
    (attack.seed, node index, fed.round) so the two paths poison
    bit-identically. The mask is a compile-time constant (changing the
    malicious cohort recompiles — it is scenario config, not round
    data). ``update_stats=True`` additionally returns per-node trust
    observations (``metrics["trust_obs"]``, adversary.cohort_scores of
    each node's delta vs the round-start params) for the host-side
    ReputationMonitor. The sparse round builder below supports
    neither: it never materializes the full params stack, so there is
    no pre-exchange hook — robustness runs use this dense builder.

    ``dp`` (a ``privacy.dp.DPSpec``) + ``dp_mask`` privatize outgoing
    updates AFTER any attack injection and before the exchange: the
    rows selected by the STATIC host mask ``dp_mask`` are replaced by
    ``privacy.dp.privatize_stacked`` of themselves vs the round-start
    params — clip to L2 ``clip_norm``, add Gaussian noise of std
    ``clip_norm * noise_multiplier``, keyed by (dp.seed, node index,
    fed.round) exactly like the socket node privatizing its learner
    post-fit, so the two planes are bit-identical. Ordering matters:
    poison-then-privatize means DP clipping also bounds what a
    malicious row can inject, which is the deployment semantics.

    ``exchange_overlap="staged"`` double-buffers the exchange: the
    off-diagonal mix terms read the PREVIOUS round's post-fit params
    (``fed.stale``, seeded by :func:`with_staged_buffer`) at their then
    contribution weights, while the self term stays this round's fresh
    fit — one-round-stale gossip. The shipped buffer is final at round
    start, so the exchange has no data dependence on the current fit
    and the scheduler can hide it under the local epochs. Requires the
    FedAvg fast path and composes with neither attack injection nor
    trust scoring (both are defined on what a node ships THIS round).
    """
    aggregator = aggregator or FedAvg()
    fedavg_fast = type(aggregator) is FedAvg
    attack_active = (
        attack is not None
        and malicious is not None
        and bool(np.any(malicious))
        and getattr(attack, "poisons_updates", False)
    )
    dp_active = (
        dp is not None
        and dp_mask is not None
        and bool(np.any(dp_mask))
    )
    if exchange_overlap not in ("off", "staged"):
        raise ValueError(
            f"unknown exchange_overlap {exchange_overlap!r}; "
            "have ('off', 'staged')"
        )
    staged = exchange_overlap == "staged"
    if staged and not fedavg_fast:
        raise ValueError(
            "exchange_overlap='staged' requires the FedAvg fast path — "
            "robust aggregators score THIS round's updates"
        )
    if staged and (attack_active or update_stats):
        raise ValueError(
            "exchange_overlap='staged' composes with neither attack "
            "injection nor trust scoring: both are defined on the "
            "fresh update a node ships this round"
        )

    def round_fn(fed: FederatedState, x, y, smask, n_samples, mix, adopt, trains):
        alive = fed.alive

        # ---- local training (every node; results masked in afterward)
        ref_params = fed.states.params  # round-start params (delta ref)
        states, train_metrics = _train_and_select(
            fns, fed.states, alive, trains, x, y, smask, epochs
        )

        # ---- adversarial injection: malicious rows poison their
        # outgoing update before it enters ANY mix (incl. their own row,
        # matching the socket node poisoning its learner post-fit)
        if attack_active:
            from p2pfl_tpu.adversary.attacks import poison_stacked

            states = states.replace(
                params=poison_stacked(
                    states.params, ref_params, malicious, fed.round, attack
                )
            )

        # ---- DP-FedAvg: masked rows privatize their outgoing update
        # (clip + noise vs round-start params) before it enters ANY mix
        # — after poisoning, so the clip also bounds injected updates,
        # and before the staged buffer capture, so stale hops ship
        # privatized params too (matching the socket node privatizing
        # its learner post-fit)
        if dp_active:
            from p2pfl_tpu.privacy.dp import privatize_stacked

            states = states.replace(
                params=privatize_stacked(
                    states.params, ref_params, dp_mask, fed.round, dp
                )
            )

        # ---- weight exchange + aggregation
        # contribution gate: only alive *training* nodes inject models
        # (proxy/idle forward/adopt but never contribute — node.py:492-524)
        contrib = jnp.logical_and(trains, alive)
        w_fresh = n_samples.astype(jnp.float32) * contrib
        new_stale = fed.stale
        if staged:
            # double buffer: off-diagonal terms weigh the PREVIOUS
            # round's post-fit params at their then weights; only the
            # self term reads this round's fresh fit. A zero stale
            # weight (with_staged_buffer's seed, or a node dead last
            # round) contributes nothing — round 0 is pure local SGD.
            stale_params, stale_w = fed.stale
            eye = jnp.eye(alive.shape[0], dtype=jnp.float32)
            w = mix * ((1.0 - eye) * stale_w[None, :]
                       + eye * w_fresh[None, :])
            new_stale = (states.params, w_fresh)
        else:
            w = mix * w_fresh[None, :]
        if fedavg_fast:
            denom = jnp.maximum(jnp.sum(w, axis=1, keepdims=True), 1e-9)
            wn = w / denom
            # identity-adopt fast path: keep is known BEFORE mixing, so
            # the keep-select fuses into the mix epilogue — one output
            # pass instead of a separate whole-stack where (~2 ms at
            # the 64-node north star)
            keep_early = (
                jnp.logical_and(alive, jnp.sum(w, axis=1) > 0)
                if identity_adopt else None
            )
            mix_dt = exchange_dtype or jnp.float32

            def _keep(mixed, p):
                if keep_early is None:
                    return mixed
                c = keep_early.reshape(
                    (keep_early.shape[0],) + (1,) * (p.ndim - 1))
                return jnp.where(c, mixed, p)

            if staged:
                wn_off = wn * (1.0 - eye)
                wn_diag = jnp.diagonal(wn)

                def leaf_mix_staged(p, ps):
                    flat_s = ps.reshape(ps.shape[0], -1).astype(mix_dt)
                    flat_f = p.reshape(p.shape[0], -1).astype(mix_dt)
                    out = jax.lax.dot(  # stale hops: no fit dependence
                        wn_off.astype(mix_dt), flat_s,
                        preferred_element_type=jnp.float32,
                    )
                    out = out + wn_diag[:, None] * flat_f.astype(
                        jnp.float32)
                    return _keep(out.reshape(p.shape).astype(p.dtype), p)

                agg = jax.tree.map(leaf_mix_staged, states.params,
                                   stale_params)
            else:
                def leaf_mix(p):
                    flat = p.reshape(p.shape[0], -1).astype(mix_dt)
                    out = jax.lax.dot(  # [n,n]@[n,d] — MXU, f32 accum
                        wn.astype(mix_dt), flat,
                        preferred_element_type=jnp.float32,
                    )
                    return _keep(out.reshape(p.shape).astype(p.dtype), p)

                agg = jax.tree.map(leaf_mix, states.params)
        else:
            # wire-precision semantics for robust aggregators too: the
            # stack entering aggregation is what crosses the "wire"
            stack_ex = (
                states.params if exchange_dtype is None
                else jax.tree.map(lambda p: p.astype(exchange_dtype),
                                  states.params)
            )
            if shared_aggregate:
                # uniform-row contract: one aggregate serves everyone
                w_union = jnp.max(w, axis=0)
                out = aggregator.aggregate(
                    stack_ex, n_samples.astype(jnp.float32),
                    mask=w_union > 0,
                )
                agg = jax.tree.map(
                    lambda o, p: jnp.broadcast_to(
                        o.astype(p.dtype)[None], p.shape
                    ),
                    out, states.params,
                )
            else:
                def per_row(row_w):
                    out = aggregator.aggregate(
                        stack_ex, n_samples.astype(jnp.float32),
                        mask=row_w > 0,
                    )
                    return jax.tree.map(
                        lambda o, p: o.astype(p.dtype), out, states.params
                    )

                agg = jax.vmap(per_row)(w)

        # nodes with an all-zero row (nothing arrived before "timeout",
        # aggregator.py:53-76) keep their own params
        got_any = jnp.sum(w, axis=1) > 0
        if identity_adopt and fedavg_fast:
            params = agg  # keep-select already fused into leaf_mix
        else:
            if identity_adopt:
                pass  # adopt == arange(n) by contract: gather elided
            elif not (shared_aggregate and not fedavg_fast):
                # shared aggregates are already identical across rows,
                # so the adopt gather would only copy
                agg = jax.tree.map(lambda a: a[adopt], agg)
            keep = jnp.logical_and(
                alive, got_any if identity_adopt else got_any[adopt])
            params = _tree_sel(keep, agg, states.params)

        fed = FederatedState(
            states=states.replace(params=params),
            alive=alive,
            round=fed.round + 1,
            stale=new_stale,
        )
        metrics = {
            "train_loss": train_metrics["loss"],  # [n]
            "alive": alive,
        }
        if update_stats:
            from p2pfl_tpu.adversary.reputation import spmd_trust_obs

            # scored on the post-attack params — what each node "sent"
            metrics["trust_obs"] = spmd_trust_obs(
                states.params, ref_params, contrib
            )
        return fed, metrics

    return round_fn


def build_round_fn_sparse(
    fns: StepFns,
    topology: Topology,
    mesh,
    epochs: int = 1,
    exchange_dtype: Any | None = None,
    exchange_overlap: str = "off",
) -> Callable:
    """The sparse-topology round: O(degree) ``ppermute`` hops over ICI
    instead of the dense all-gather einsum.

    One federated node per mesh slot (requires ``topology.n ==
    mesh.size``), DFL only (``adopt`` must be the identity — CFL/SDFL
    route everything through one leader, where a gather is the natural
    collective, so they stay on :func:`build_round_fn`). The per-round
    plan arrays keep the SAME signature as the dense round fn, so the
    two programs are drop-in interchangeable and parity-testable
    (exact parity with ``exchange_dtype=None``; a wire dtype rounds
    wire payloads identically on both paths but the dense einsum
    additionally rounds the [n,n] weight matrix — see
    ``neighbor_exchange``).

    On a ring (the reference's watts_strogatz(n,2,0) topology,
    topologymanager.py:213-228) this moves 2 × |params| per node per
    round instead of n × |params| — the reference's per-neighbor TCP
    sends (node.py:726-809) become exactly #offsets ppermutes.
    """
    from jax.sharding import PartitionSpec

    from p2pfl_tpu.parallel.mesh import NODES_AXIS, shard_map_compat
    from p2pfl_tpu.parallel.transport import neighbor_exchange

    if topology.n != mesh.size:
        raise ValueError(
            f"sparse round needs one node per mesh slot: "
            f"{topology.n} nodes vs {mesh.size} devices"
        )
    if exchange_overlap not in ("off", "staged"):
        raise ValueError(
            f"unknown exchange_overlap {exchange_overlap!r}; "
            "have ('off', 'staged')"
        )
    staged = exchange_overlap == "staged"

    Pn = PartitionSpec(NODES_AXIS)
    Pr = PartitionSpec()
    fed_spec = FederatedState(
        states=Pn, alive=Pn, round=Pr,
        stale=(Pn, Pn) if staged else None,
    )

    def round_body(fed: FederatedState, x, y, smask, n_samples, mix, adopt, trains):
        # every block arrives with a leading node axis of size 1
        del adopt  # identity by contract (DFL)
        alive = fed.alive

        states, train_metrics = _train_and_select(
            fns, fed.states, alive, trains, x, y, smask, epochs
        )

        contrib = jnp.logical_and(trains, alive)
        my_w = (n_samples.astype(jnp.float32) * contrib)[0]
        local = jax.tree.map(lambda p: p[0], states.params)
        if staged:
            # ship the PREVIOUS round's post-fit buffer on the hops —
            # ready at round start, so the ppermutes need not wait for
            # this round's fit (see neighbor_exchange)
            stale_p, stale_w = fed.stale
            agg, total = neighbor_exchange(
                local, my_w, mix[0], topology, NODES_AXIS,
                exchange_dtype=exchange_dtype,
                stale_params=jax.tree.map(lambda p: p[0], stale_p),
                stale_weight=stale_w[0],
            )
            new_stale = (states.params, my_w[None])
        else:
            agg, total = neighbor_exchange(
                local, my_w, mix[0], topology, NODES_AXIS,
                exchange_dtype=exchange_dtype,
            )
            new_stale = fed.stale
        keep = jnp.logical_and(alive[0], total > 0)
        params = jax.tree.map(
            lambda a, p: jnp.where(keep, a.astype(p.dtype), p[0])[None],
            agg, states.params,
        )
        fed = FederatedState(
            states=states.replace(params=params),
            alive=alive,
            round=fed.round + 1,
            stale=new_stale,
        )
        metrics = {"train_loss": train_metrics["loss"], "alive": alive}
        return fed, metrics

    sharded = shard_map_compat(
        round_body,
        mesh=mesh,
        in_specs=(fed_spec, Pn, Pn, Pn, Pn, Pn, Pn, Pn),
        out_specs=(fed_spec, {"train_loss": Pn, "alive": Pn}),
    )
    return sharded


def cross_device_wn(c_sizes, c_alive):
    """Globally normalized FedAvg weights over ALL ``C x n_slots``
    sampled clients, plus the empty-round flag. Shared by the monolithic
    scan, both sharded arms, and the streamed driver so the weighting —
    and therefore the aggregate — cannot drift between them."""
    w = c_sizes.astype(jnp.float32) * c_alive  # [C, n_slots]
    denom = jnp.maximum(jnp.sum(w), 1e-9)
    return w / denom, jnp.sum(w) > 0


def _cross_device_plan(params0, fused_accumulate: bool):
    """Per-leaf route for the fit-epilogue accumulate: ``True`` sends
    the leaf through the fused ``pallas_gemm.fedavg_accum`` stream,
    ``False`` keeps the exact-XLA gemm-row contraction. The key is the
    learner's ``_fused_sgd_step`` key verbatim (same per-slot 2-D
    shape, same ``sgd_accum`` kind, same nodes hint), so one measured
    decision covers both call sites. Off-TPU the gate forces xla
    (unless the ``P2PFL_PALLAS_GEMM`` env knob forces pallas — the
    interpret-mode parity-test route), so tier-1 numerics are
    unchanged. Plan is all-False for the unfused layout: the reference
    arm stays the reference."""
    from p2pfl_tpu.ops import pallas_gemm

    def leaf_plan(p):
        if not fused_accumulate or p.ndim < 2:
            return False  # per-slot scalar: nothing to stream
        leaf = p.shape[1:]
        rows = int(np.prod(leaf[:-1], dtype=np.int64)) if len(leaf) > 1 else 1
        shape2 = (rows, int(leaf[-1]))
        return pallas_gemm.choose(
            "sgd_accum", (shape2, shape2), p.dtype) == "pallas"

    return jax.tree.map(leaf_plan, params0)


def _cross_device_acc0(params0, fused_accumulate: bool, plan):
    """Zero accumulators, one per leaf, in the layout the route wants:
    pallas leaves carry a per-slot 2-D stream ``[n_slots, rows, cols]``
    (summed over slots once at round end), fused-gemm leaves ONE flat
    f32 row ``[1, d]``, unfused leaves the full ``[n_slots, d]``."""

    def leaf0(p, use_pallas):
        if use_pallas:
            leaf = p.shape[1:]
            rows = (int(np.prod(leaf[:-1], dtype=np.int64))
                    if len(leaf) > 1 else 1)
            return jnp.zeros((p.shape[0], rows, int(leaf[-1])),
                             jnp.float32)
        rows = 1 if fused_accumulate else p.shape[0]
        return jnp.zeros(
            (rows, int(np.prod(p.shape[1:], dtype=np.int64))),
            jnp.float32)

    return jax.tree.map(leaf0, params0, plan)


def _cross_device_body(fns: StepFns, epochs: int, mix_dt,
                       fused_accumulate: bool, params0, n_slots: int,
                       plan) -> Callable:
    """One cohort step of the cross-device scan: train the cohort from
    the round-start ``params0``, fold its weighted contribution into
    the accumulator. THE body — the monolithic scan, both sharded
    arms, and the streamed driver all run exactly this function, which
    is what makes their per-step values bit-identical by construction
    rather than by test luck."""
    trains = jnp.ones((n_slots,), bool)
    from p2pfl_tpu.ops.pallas_gemm import fedavg_accum

    def body(carry, inputs):
        opt_state, rng, step, acc = carry
        x_t, y_t, m_t, alive_t, wn_t = inputs
        states_t = TrainState(
            params=params0, opt_state=opt_state, rng=rng, step=step
        )
        states_t, tm = _train_and_select(
            fns, states_t, alive_t, trains, x_t, y_t, m_t, epochs
        )

        # hoisted out of the leaf loop: one weight operand per step,
        # not one broadcast+cast per leaf
        w_t = jnp.broadcast_to(
            wn_t[None, :], (n_slots, n_slots)
        ).astype(mix_dt)

        def leaf_acc(a, p, use_pallas):
            if use_pallas:
                # per-slot fused stream: acc[s] += wn[s] * p[s] in one
                # pass through the sgd_accum kernel (null optimizer
                # half); the slot axis collapses once at round end
                return jax.vmap(
                    lambda ai, pi, wi: fedavg_accum(
                        pi.reshape(ai.shape).astype(mix_dt), ai, wi)
                )(a, p, wn_t)
            flat = p.reshape(p.shape[0], -1).astype(mix_dt)
            partial = jax.lax.dot(
                w_t, flat,
                preferred_element_type=jnp.float32,
            )
            if fused_accumulate:
                # the barrier pins the gemm before the row slice —
                # without it XLA may turn slice-of-dot into a gemv
                # whose reduction order is 1 ulp off the gemm row,
                # breaking the tolerance-0 parity gates
                partial = jax.lax.optimization_barrier(partial)[0:1]
            return a + partial

        acc = jax.tree.map(leaf_acc, acc, states_t.params, plan)
        carry = (states_t.opt_state, states_t.rng, states_t.step, acc)
        return carry, tm["loss"]

    return body


def _cross_device_leaf_out(keep, n_slots: int, fused_accumulate: bool):
    """Round-end epilogue per leaf: collapse the accumulator back to
    the ``[n_slots, ...]`` param stack, keeping the old params where
    the round was empty or the slot dead."""

    def leaf_out(a, p, use_pallas):
        if use_pallas:
            # per-slot partials [n_slots, rows, cols]: the slot sum IS
            # the sum over all C x n_slots clients (weights were
            # globally normalized up front)
            row = a.sum(axis=0).reshape((1,) + p.shape[1:]).astype(p.dtype)
            out = jnp.broadcast_to(row, p.shape)
        elif fused_accumulate:
            row = a.reshape((1,) + p.shape[1:]).astype(p.dtype)
            out = jnp.broadcast_to(row, p.shape)
        else:
            out = a.reshape(p.shape).astype(p.dtype)
        c = keep.reshape((n_slots,) + (1,) * (p.ndim - 1))
        return jnp.where(c, out, p)

    return leaf_out


def _ordered_chunk_sum(stacked, n_chunks: int):
    """Sum a ``[D, ...]`` stack of per-chunk partials chunk 0 first —
    an unrolled, order-pinned add chain, identical code whether the
    stack came off the shard_map or the single-device chunk scan. This
    is the deterministic re-association of the cross-chunk psum: by
    doing the reduce OUTSIDE the mapped region in a fixed order, the
    sharded and single-device arms produce bit-identical sums instead
    of collective-implementation-defined ones."""
    total = stacked[0]
    for i in range(1, n_chunks):
        total = total + stacked[i]
    return total


def build_round_fn_cross_device(
    fns: StepFns,
    epochs: int = 1,
    exchange_dtype: Any | None = None,
    fused_accumulate: bool = True,
    cohort_shards: int = 1,
    cohort_mesh: Any | None = None,
) -> Callable:
    """The cross-device round (round 13): one compiled program runs a
    ``lax.scan`` over stacked cohorts, so an ``n_slots``-wide mesh
    simulates ``cohort_size x n_slots`` sampled participants per round.

    Signature: ``round_fn(fed, cx, cy, cmask, c_sizes, c_alive) ->
    (fed, metrics)`` with cohort-stacked data ``cx [C, n_slots, S,
    ...]``, ``cy/cmask [C, n_slots, S]``, ``c_sizes/c_alive [C,
    n_slots]`` (``C = cohort_size``). ``fed`` is the GLOBAL model
    broadcast across slots (init_federation same_init) — clients are
    transient, so every scan step trains its cohort from the
    round-start params, and the example-weighted FedAvg sums over all
    ``C x n_slots`` sampled clients at once against the globally
    normalized weights ``wn = w / max(sum(w), 1e-9)``.

    Two accumulation layouts produce that sum (round 17):

    * ``fused_accumulate=True`` (default): every slot of the aggregate
      is identical by construction, so the scan carries ONE flat f32
      row per leaf (``[1, d]``) instead of the full ``[n_slots, d]``
      accumulator — per step the cohort's weighted partial is folded
      into the fit epilogue as ``acc += dot(W_t, flat_t)[0:1]``. The
      slice sits behind an ``optimization_barrier`` so XLA cannot
      rewrite slice-of-dot into a gemv with a different reduction
      order: the dot INSTRUCTION is byte-identical to the unfused
      reference's, which is what makes tolerance-0 parity hold at
      every shape rather than by backend-kernel coincidence (a
      ``[1, n] @ [n, d]`` row-dot is 1 ulp off the gemm row at some
      CPU shapes). The carry (and its zeros init) is ``n_slots`` times
      smaller, the read-modify-write of the accumulator per scan step
      drops from ``2 * n_slots * d`` to ``2 * d`` floats, and the
      round-end broadcast back to ``[n_slots, ...]`` happens once in
      the keep/where epilogue.
    * ``fused_accumulate=False``: the round-13 reference — per step
      ``dot(W_t, flat_t)`` where every row of ``W_t`` is the cohort's
      weight slice, accumulated at full ``[n_slots, d]``. Kept as the
      parity anchor; the tolerance-0 gate in tests/test_cross_device.py
      pins fused == unfused (params AND opt_state).

    Both layouts run the SAME ``[n_slots, n_slots] @ [n_slots, d]``
    dot with f32 accumulation — deliberately the dot shape of the
    dense round's ``leaf_mix``, so at ``cohort_size == 1`` with every
    client sampled the cross-device round stays bit-identical to the
    dense stacked round (the round-13 parity gate) under either
    layout.

    A sampled-but-dead client (``c_alive`` false — membership clock
    composition) trains nothing (the ``_train_and_select`` gate) and
    carries zero aggregation weight; its slot's data that step is inert
    padding. Optimizer state / rng / step thread through the scan as
    slot-level carries (cross-device clients own no persistent state).
    ``exchange_dtype`` rounds each cohort's params entering the
    accumulation dot, mirroring the dense wire-precision knob.

    All shapes are fixed by ``(n_slots, C, shard_size)`` — resampling
    clients each round never recompiles (the crossdev_xla_recompiles
    bench key pins this, for both layouts).

    **Sharded cohort scan (round 20).** ``cohort_shards = D > 1``
    splits the C cohort steps into D contiguous chunks; each chunk
    scans from the SAME round-start carry (params0, opt_state0, rng0,
    step0, zero accumulator), so chunks are independent and can run on
    D devices at once. The chunk structure is part of the round's
    *semantics*, not a layout detail: the final opt_state/rng/step come
    from the LAST chunk, and the D per-chunk accumulator partials are
    reduced by an order-pinned unrolled add chain (chunk 0 first) —
    the cross-chunk psum deterministically re-associated OUTSIDE the
    mapped region. Both arms — ``cohort_mesh=None`` (an outer
    ``lax.scan`` over chunks on one device) and a ``cohort_shard_mesh``
    (``shard_map`` over the ``cohorts`` axis) — run the identical body
    and the identical reduce, which is what makes sharded vs
    single-device bit-for-bit (params AND opt_state, tolerance 0) at
    the same ``cohort_shards``, with zero post-warm-up recompiles on
    either arm. ``D = 1`` degenerates to exactly the monolithic scan
    above — the round-13/17 gates are untouched. Requires
    ``C % cohort_shards == 0``.

    **Fused accumulate route (round 20, closing the round-17 loose
    end).** Per leaf, the measured ``pallas_gemm.choose("sgd_accum")``
    gate may route the fit-epilogue accumulate through
    ``fedavg_accum`` — the learner's fused SGD+accumulate kernel with
    the optimizer half nulled — as a per-slot streaming
    ``acc[s] += wn[s] * p[s]`` whose slot axis collapses once at round
    end. Exact-XLA gemm fallback per leaf; CPU resolves xla so tier-1
    numerics are bit-unchanged. The pallas route re-associates the
    ``[n,n]@[n,d]`` contraction (sum over slots then steps), so its
    parity vs the gemm path is allclose, not tolerance-0 — pinned by
    tests/test_cross_device.py with the env knob forcing both ways.
    """
    mix_dt = exchange_dtype or jnp.float32
    if cohort_shards < 1:
        raise ValueError(f"cohort_shards must be >= 1, got {cohort_shards}")
    if cohort_mesh is not None and cohort_mesh.size != cohort_shards:
        raise ValueError(
            f"cohort_mesh has {cohort_mesh.size} devices but "
            f"cohort_shards={cohort_shards}")

    def round_fn(fed: FederatedState, cx, cy, cmask, c_sizes, c_alive):
        n_slots = fed.alive.shape[0]
        params0 = fed.states.params  # round-start global model

        # FedAvg weights over ALL C x n_slots sampled clients,
        # normalized once — the per-step dots then just accumulate
        wn, got_any = cross_device_wn(c_sizes, c_alive)

        plan = _cross_device_plan(params0, fused_accumulate)
        acc0 = _cross_device_acc0(params0, fused_accumulate, plan)
        carry0 = (fed.states.opt_state, fed.states.rng, fed.states.step,
                  acc0)
        body = _cross_device_body(fns, epochs, mix_dt, fused_accumulate,
                                  params0, n_slots, plan)

        n_cohorts = cx.shape[0]
        if cohort_shards == 1:
            carry, losses = jax.lax.scan(
                body, carry0, (cx, cy, cmask, c_alive, wn)
            )
            opt_state, rng, step, acc = carry
        else:
            d = cohort_shards
            if n_cohorts % d != 0:
                raise ValueError(
                    f"cohort_size {n_cohorts} not divisible by "
                    f"cohort_shards {d}")
            chunked = jax.tree.map(
                lambda a: a.reshape((d, n_cohorts // d) + a.shape[1:]),
                (cx, cy, cmask, c_alive, wn),
            )
            if cohort_mesh is None:
                # single-device arm: the chunks run sequentially from
                # the SAME chunk-local carries as the mesh arm. The
                # loop is Python-unrolled rather than an outer
                # lax.scan: nesting the chunk scan inside a while
                # loop changes how XLA fuses the training body and
                # drifts ~1 ulp from the shard_map program; unrolled,
                # each chunk compiles at top level exactly like one
                # device's shard_map shard, and the arms are
                # bit-identical (d is a small static constant)
                outs = []
                for i in range(d):
                    chunk_i = jax.tree.map(lambda a, i=i: a[i],
                                           chunked)
                    carry, losses_c = jax.lax.scan(
                        body, carry0, chunk_i)
                    outs.append((jax.tree.map(lambda t: t[None],
                                              carry),
                                 losses_c[None]))
                carries, losses_d = jax.tree.map(
                    lambda *xs: jnp.concatenate(xs, axis=0), *outs)
            else:
                from p2pfl_tpu.parallel.mesh import (
                    COHORTS_AXIS, shard_map_compat)
                from jax.sharding import PartitionSpec

                Pc = PartitionSpec(COHORTS_AXIS)
                Pr = PartitionSpec()

                # params0/carry0 are passed explicitly (replicated):
                # shard_map must not close over tracers
                def shard_body(params0_, carry0_, x_c, y_c, m_c, a_c,
                               w_c):
                    body_ = _cross_device_body(
                        fns, epochs, mix_dt, fused_accumulate,
                        params0_, n_slots, plan)
                    # local view: one chunk with a leading axis of 1
                    carry, losses_c = jax.lax.scan(
                        body_, carry0_,
                        (x_c[0], y_c[0], m_c[0], a_c[0], w_c[0]))
                    return (jax.tree.map(lambda t: t[None], carry),
                            losses_c[None])

                sharded = shard_map_compat(
                    shard_body,
                    mesh=cohort_mesh,
                    in_specs=(Pr, Pr, Pc, Pc, Pc, Pc, Pc),
                    out_specs=(Pc, Pc),
                )
                carries, losses_d = sharded(params0, carry0, *chunked)
            # finals from the LAST chunk; accumulator partials reduced
            # chunk 0 first — the order-pinned psum re-association
            opt_state = jax.tree.map(lambda t: t[-1], carries[0])
            rng = carries[1][-1]
            step = carries[2][-1]
            acc = jax.tree.map(lambda s: _ordered_chunk_sum(s, d),
                               carries[3])
            losses = losses_d.reshape((n_cohorts,) + losses_d.shape[2:])

        # an empty round (every sampled client dead) keeps the global
        # model — the cross-device analog of the dense got_any keep
        keep = jnp.logical_and(fed.alive, got_any)
        leaf_out = _cross_device_leaf_out(keep, n_slots,
                                          fused_accumulate)
        params = jax.tree.map(leaf_out, acc, params0, plan)
        fed = FederatedState(
            states=TrainState(
                params=params, opt_state=opt_state, rng=rng, step=step
            ),
            alive=fed.alive,
            round=fed.round + 1,
            stale=fed.stale,
        )
        metrics = {
            "train_loss": losses,  # [C, n_slots] per-cohort-step
            "alive": fed.alive,
        }
        return fed, metrics

    return round_fn


def build_cross_device_stream_fns(
    fns: StepFns,
    epochs: int = 1,
    exchange_dtype: Any | None = None,
    fused_accumulate: bool = True,
) -> tuple[Callable, Callable, Callable]:
    """The cross-device round unrolled for streamed client state
    (round 20): ``(init_carry, step, finalize)`` instead of one scan
    over pre-materialized cohorts, so the host can gather and
    ``device_put`` cohort t+1 while the device trains cohort t
    (``CrossDeviceScenario``'s double-buffered prefetch seam) — an
    N=100k..1M round materializes TWO cohorts of client data at any
    instant instead of all C.

    ``step(params0, carry, x_t, y_t, m_t, alive_t, wn_t)`` is exactly
    one ``_cross_device_body`` step — the SAME body the monolithic scan
    runs — so a streamed round is bit-identical to
    ``build_round_fn_cross_device`` at ``cohort_shards=1`` with the
    same cohort assignment. ``wn_t`` rows come from
    ``cross_device_wn`` over the full ``[C, n_slots]`` sizes/alive
    (client sizes need no client data — ``CrossDeviceData.
    client_sizes`` is host metadata), computed once at round start.
    ``finalize(fed, carry, got_any)`` runs the keep/where epilogue and
    advances the round counter. The caller jits ``step`` once
    (``donate_argnums`` the carry) and calls it C times per round —
    fixed shapes, zero recompiles after warm-up.
    """
    mix_dt = exchange_dtype or jnp.float32

    def init_carry(fed: FederatedState):
        plan = _cross_device_plan(fed.states.params, fused_accumulate)
        acc0 = _cross_device_acc0(fed.states.params, fused_accumulate,
                                  plan)
        return (fed.states.opt_state, fed.states.rng, fed.states.step,
                acc0)

    def step(params0, carry, x_t, y_t, m_t, alive_t, wn_t):
        n_slots = alive_t.shape[0]
        plan = _cross_device_plan(params0, fused_accumulate)
        body = _cross_device_body(fns, epochs, mix_dt, fused_accumulate,
                                  params0, n_slots, plan)
        return body(carry, (x_t, y_t, m_t, alive_t, wn_t))

    def finalize(fed: FederatedState, carry, got_any):
        opt_state, rng, step_, acc = carry
        n_slots = fed.alive.shape[0]
        plan = _cross_device_plan(fed.states.params, fused_accumulate)
        keep = jnp.logical_and(fed.alive, got_any)
        leaf_out = _cross_device_leaf_out(keep, n_slots,
                                          fused_accumulate)
        params = jax.tree.map(leaf_out, acc, fed.states.params, plan)
        return FederatedState(
            states=TrainState(
                params=params, opt_state=opt_state, rng=rng, step=step_
            ),
            alive=fed.alive,
            round=fed.round + 1,
            stale=fed.stale,
        )

    return init_carry, step, finalize


def build_eval_fn(fns: StepFns) -> Callable:
    """Evaluate every node's model on the (replicated) test set.

    Returns per-node metrics ``{loss: [n], accuracy: [n]}`` — the
    federated analog of the reference's per-node ``__evaluate``
    (node.py:435, Trainer.test per process).
    """

    def eval_fn(fed: FederatedState, x_test, y_test):
        mask = jnp.ones((x_test.shape[0],), bool)
        return jax.vmap(fns.evaluate, in_axes=(0, None, None, None))(
            fed.states.params, x_test, y_test, mask
        )

    return eval_fn


def round_flops(round_jit, fed: FederatedState, *args) -> float | None:
    """Counted FLOPs of one compiled federated round program.

    Thin adapter over ``obs.cost_model.program_flops`` so the round-fn
    layer and the live devprof gauge share one cost model with the
    bench (same cost_analysis read, same caveats — see cost_model's
    docstring). Lowers at avals: no device work is queued. Callers
    cache — shapes are fixed for a scenario's lifetime, so the answer
    never changes mid-run."""
    from p2pfl_tpu.obs import cost_model

    return cost_model.program_flops(
        round_jit, *cost_model.avals((fed, *args)))
