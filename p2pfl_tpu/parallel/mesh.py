"""Device mesh construction and sharding for federations.

The federation's unit of placement: a 1-D ``nodes`` mesh axis. With N
federated nodes on D devices, the stacked node axis (leading axis of
every federation array — params, data shards, masks) is sharded over
``nodes``; when N > D each device carries N/D nodes and XLA runs the
inner vmap locally. When D == 1 (a single TPU chip) the same program
runs fully local — the collectives degenerate to copies, so one code
path covers chip, slice, and the 8-device virtual CPU CI mesh.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODES_AXIS = "nodes"


def federation_mesh(n_devices: int | None = None,
                    devices: list | None = None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if n_devices > len(devices):
                raise ValueError(
                    f"asked for {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODES_AXIS,))


def stacked_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays whose leading axis is the node axis."""
    return NamedSharding(mesh, P(NODES_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_stacked(tree, mesh: Mesh):
    """Place a stacked pytree (leading node axis on every leaf) onto the
    mesh. Requires the node count to divide evenly over devices."""
    sh = stacked_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
