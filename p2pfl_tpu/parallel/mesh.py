"""Device mesh construction and sharding for federations.

The federation's unit of placement: a 1-D ``nodes`` mesh axis. With N
federated nodes on D devices, the stacked node axis (leading axis of
every federation array — params, data shards, masks) is sharded over
``nodes``; when N > D each device carries N/D nodes and XLA runs the
inner vmap locally. When D == 1 (a single TPU chip) the same program
runs fully local — the collectives degenerate to copies, so one code
path covers chip, slice, and the 8-device virtual CPU CI mesh.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODES_AXIS = "nodes"

#: the cross-device round's second placement axis (round 20): cohort
#: CHUNKS, not nodes. The cohort scan's C steps split into D
#: contiguous chunks, one per device; each device scans its chunk from
#: the same round-start params. Deliberately a separate 1-D mesh from
#: ``federation_mesh`` — the cross-device plane has no persistent node
#: axis to shard (slots are transient), so the whole mesh goes to the
#: cohort axis.
COHORTS_AXIS = "cohorts"


def cohort_shard_mesh(n_devices: int,
                      devices: list | None = None) -> Mesh:
    """A 1-D ``cohorts`` mesh over ``n_devices`` for the sharded
    cross-device scan (``build_round_fn_cross_device`` with
    ``cohort_shards > 1``)."""
    if devices is None:
        devices = jax.devices()
        if n_devices > len(devices):
            raise ValueError(
                f"asked for {n_devices} cohort-shard devices, "
                f"have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (COHORTS_AXIS,))


def federation_mesh(n_devices: int | None = None,
                    devices: list | None = None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if n_devices > len(devices):
                raise ValueError(
                    f"asked for {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODES_AXIS,))


def stacked_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays whose leading axis is the node axis."""
    return NamedSharding(mesh, P(NODES_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_stacked(tree, mesh: Mesh):
    """Place a stacked pytree (leading node axis on every leaf) onto the
    mesh. Requires the node count to divide evenly over devices."""
    sh = stacked_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across JAX versions: the top-level export (and its
    ``check_vma`` flag) only exist on newer JAX; 0.4.x has
    ``jax.experimental.shard_map`` with ``check_rep``. Replication
    checking is disabled on both — the round programs mix collectives
    the checker rejects spuriously."""
    try:
        from jax import shard_map  # new JAX

        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map

        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def fetch_global(x) -> np.ndarray:
    """Device array -> full host copy, valid on EVERY process of a
    multi-process job — including processes that own no device of the
    array's (sub)mesh (e.g. 6 federated nodes over 4 hosts x 2 devices:
    the divisor rule meshes 6 of 8 devices and host 3 holds nothing).

    ``process_allgather`` alone cannot serve a meshless process: its
    gather runs (and leaves its output) on the ARRAY's mesh, so a
    process outside that mesh can neither read a replicated shard nor
    fetch the gathered result. When the array's devices are a strict
    subset of the global devices, shard-owning processes resolve the
    full value locally (shard read for replicated, allgather for
    sharded) and ``broadcast_one_to_all`` — a true global collective —
    ships process 0's copy everywhere (process 0 owns mesh device 0 by
    construction, so it always has the value).

    Every branch below that leads to a COLLECTIVE must be decided from
    metadata that is identical on all processes (process_count, the
    array's device_set vs the global device list). Deciding from
    ``is_fully_addressable`` deadlocks: with n_nodes <= devices-per-
    host the whole submesh lives on host 0, host 0 sees a fully-
    addressable array and returns early, while every other host walks
    into ``broadcast_one_to_all`` and blocks alone.
    """
    if jax.process_count() == 1 or not hasattr(x, "sharding"):
        return np.asarray(x)  # single process / plain host value
    from jax.experimental import multihost_utils

    submesh = len(x.sharding.device_set) < len(jax.devices())
    if not submesh:
        # full mesh: every process owns shards, allgather serves all
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    # submesh: shard owners resolve locally, everyone joins the
    # broadcast (including owners — it is a global collective)
    if x.is_fully_addressable:
        local = np.asarray(x)
    elif x.addressable_shards:
        if x.sharding.is_fully_replicated:
            local = np.asarray(x.addressable_shards[0].data)
        else:
            local = np.asarray(
                multihost_utils.process_allgather(x, tiled=True)
            )
    else:
        local = np.zeros(x.shape, x.dtype)  # ignored: not the source
    return np.asarray(multihost_utils.broadcast_one_to_all(local))
