"""ICI transport: explicit collective schedules for weight exchange.

The reference moves weights with per-peer TCP threads + 2 KB fragments
(node_connection.py:146-242, communication_protocol.py:737-769). Here
the "wire" is the TPU interconnect, and a topology is a *collective
schedule*:

- dense graphs → one all-gather + masked einsum (what
  federated.build_round_fn emits through XLA's SPMD partitioner);
- ring graphs → two ``ppermute`` hops (left+right neighbor), O(degree)
  ICI traffic instead of O(n) — this module's ``neighbor_exchange``;
- arbitrary sparse graphs → a sequence of ``ppermute`` steps, one per
  distinct edge offset (a ring with chords of offset k adds one
  ppermute of shift k).

``MeshTransport`` wraps a mesh + jitted round/eval fns with the right
input shardings, so callers (federation.Scenario) never touch
jax.sharding directly.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from p2pfl_tpu.parallel.mesh import (
    NODES_AXIS,
    federation_mesh,
    replicated_sharding,
    stacked_sharding,
)
from p2pfl_tpu.topology.topology import Topology


def edge_offsets(topology: Topology) -> list[int]:
    """Distinct circulant offsets present in the adjacency matrix.

    For ring/torus-like graphs this is a short list (ring: {1, n-1});
    each offset becomes one ``ppermute`` in ``neighbor_exchange``. For
    non-circulant graphs this over-approximates (an offset is included
    if ANY node has that edge) — correctness is preserved because
    per-edge masks zero out non-edges after the permute.
    """
    a = topology.adjacency
    n = topology.n
    offs = []
    for k in range(1, n):
        if any(a[i, (i + k) % n] for i in range(n)):
            offs.append(k)
    return offs


def neighbor_exchange(
    params: Any,
    my_weight: jnp.ndarray,
    row: jnp.ndarray,
    topology: Topology,
    axis_name: str = NODES_AXIS,
    exchange_dtype: Any | None = None,
    stale_params: Any | None = None,
    stale_weight: jnp.ndarray | None = None,
) -> tuple[Any, jnp.ndarray]:
    """Weighted neighborhood average via ``ppermute`` — for use inside
    ``shard_map`` with one node per mesh slot.

    ``params``: this node's (unstacked) pytree; ``my_weight``: this
    node's contribution weight (sample count × alive × trains — zero
    means "I contribute nothing", matching the round fn's contribution
    gate); ``row``: this node's full mixing row ``[n]`` (0 = no edge).

    Each circulant offset k contributes one ppermute shifting every
    node's (params, weight) k steps around the mesh; receivers scale by
    ``row[sender] * sender_weight``. Offsets over-approximate on
    non-circulant graphs, but ``row`` zeroes non-edges, so correctness
    holds. Total ICI traffic = (#offsets) × |params| instead of
    all-gather's n × |params| — O(degree) for rings/chords.

    Returns ``(mean_f32, total_weight)``; the caller keeps its own
    params where ``total_weight == 0`` (the nothing-arrived timeout
    analog, aggregator.py:53-76).

    ``exchange_dtype`` (e.g. bf16) down-casts params before each
    ``ppermute`` — halving ICI bytes per hop; accumulation stays f32.
    The self contribution goes through the same wire cast so every
    model entering the aggregation saw identical rounding (matching
    the dense einsum's whole-stack cast). Exact dense/sparse parity
    holds for ``exchange_dtype=None`` (the default): with a wire dtype
    the two schedules still agree on what crosses the wire but differ
    in weight rounding and accumulation order.

    ``stale_params``/``stale_weight`` switch the hops to DOUBLE-
    BUFFERED (staged) mode: what crosses the wire is the PREVIOUS
    round's post-fit tree at its then contribution weight, while the
    self contribution stays this round's fresh ``params``/``my_weight``
    — one-round-stale gossip. The point is scheduling freedom: the
    shipped buffer is already final when the round starts, so XLA can
    hoist the ppermute sends before/under the local fit instead of
    fencing them behind it (exchange_overlap="staged",
    docs/perf.md §11). A zero ``stale_weight`` round (the seeded
    buffer) degenerates to pure local training.
    """
    n = topology.n
    idx = jax.lax.axis_index(axis_name)
    w_self = row[idx] * my_weight

    def cast(tree):
        return (
            tree if exchange_dtype is None
            else jax.tree.map(lambda p: p.astype(exchange_dtype), tree)
        )

    wire = cast(params)
    if stale_params is not None:
        hop_tree, hop_w = cast(stale_params), stale_weight
    else:
        hop_tree, hop_w = wire, my_weight
    acc = jax.tree.map(lambda p: p.astype(jnp.float32) * w_self, wire)
    total = w_self
    for k in edge_offsets(topology):
        perm = [(i, (i + k) % n) for i in range(n)]  # src -> dst
        shifted = jax.tree.map(
            lambda p: jax.lax.ppermute(p, axis_name, perm), hop_tree
        )
        w_recv = jax.lax.ppermute(hop_w, axis_name, perm)
        sender = (idx - k) % n
        wk = row[sender] * w_recv
        acc = jax.tree.map(
            lambda a, s: a + s.astype(jnp.float32) * wk, acc, shifted
        )
        total = total + wk
    denom = jnp.maximum(total, 1e-9)
    return jax.tree.map(lambda a: a / denom, acc), total


class MeshTransport:
    """Places federation arrays on a device mesh and jit-compiles round
    programs with node-axis shardings.

    This is the runtime seam the reference fills with BaseNode's socket
    listener + NodeConnection threads (base_node.py:70-79, 197-232):
    `start()` there opens sockets; here it builds a Mesh. `broadcast()`
    there writes to N sockets; here a round's exchange IS the program.
    """

    def __init__(self, n_nodes: int, n_devices: int | None = None):
        devices = jax.devices()
        if n_devices is None:
            # largest device count ≤ n_nodes that divides n_nodes evenly
            n_devices = min(len(devices), n_nodes)
            while n_nodes % n_devices:
                n_devices -= 1
        self.mesh = federation_mesh(n_devices)
        self.n_nodes = n_nodes
        self.n_devices = n_devices
        self._stacked = stacked_sharding(self.mesh)
        self._replicated = replicated_sharding(self.mesh)

    def _place(self, x, sharding):
        """DCN-safe placement: in a multi-process (jax.distributed)
        job, ``device_put`` cannot target non-addressable devices, so
        each process fills only the shards it owns via
        ``make_array_from_callback`` (the dcn.make_global recipe) —
        straight from the HOST copy, never bouncing through a local
        device first. Single-process keeps the direct put."""
        if jax.process_count() > 1:
            import numpy as np

            arr = np.asarray(x)
            # explicit dtype: a process whose devices all fall outside
            # the federation mesh fills no shards, and the dtype can't
            # be inferred from an empty shard list (dcn.make_global)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx], dtype=arr.dtype
            )
        return jax.device_put(jnp.asarray(x), sharding)

    def put_stacked(self, tree):
        """Shard each leaf's leading node axis; replicate scalars and
        leaves that don't carry the node axis (e.g. FederatedState.round)."""

        def place(x):
            shape = getattr(x, "shape", None)
            if shape is None:
                shape = jnp.asarray(x).shape
            if len(shape) >= 1 and shape[0] == self.n_nodes:
                return self._place(x, self._stacked)
            return self._place(x, self._replicated)

        return jax.tree.map(place, tree)

    @property
    def replicated(self):
        """The mesh-replicated sharding, for callers that place buffers
        with a raw ``jax.device_put`` (the cross-device streamed
        prefetch seam) and must land on the transport's device set."""
        return self._replicated

    def put_replicated(self, tree):
        return jax.tree.map(
            lambda x: self._place(x, self._replicated), tree
        )

    def compile_round(self, round_fn: Callable):
        """jit a round fn. Shardings are inferred from the committed
        input arrays (put_stacked/put_replicated), the idiomatic
        jax.sharding flow; donating the federation state buys in-place
        param buffers on device."""
        return jax.jit(round_fn, donate_argnums=(0,))

    def compile_eval(self, eval_fn: Callable):
        return jax.jit(eval_fn)
