"""Scenario web dashboard — the L5 frontend, stdlib-only.

The reference's largest subsystem is a Flask app + SQLite
(webserver/app.py:260-714, database.py): scenario list, live node
monitoring, log viewers, REST intake. This module delivers that
*capability* with no service dependencies: a `http.server` app that
reads the same on-disk artifacts the framework already writes
(`status/` records, `metrics.jsonl`, `logs/*.log`) and serves

- ``/``                    — scenario list (every run under the log root)
- ``/scenario/<name>``     — live node table (auto-refreshing) + links
- ``/api/scenarios``       — JSON scenario index
- ``/api/scenario/<name>`` — JSON node statuses (the monitoring feed)
- ``/api/metrics/<name>``  — JSON tail of the metrics stream
- ``/logs/<name>/<file>``  — tail of a node's log file, rendered

The filesystem IS the database: node upserts are the atomic
``node_*.status.json`` replaces (webserver/database.py:253-274's
role), so the dashboard needs no writer process and works for
in-process scenarios, socket federations, and compose deployments
sharing a log volume.

Run: ``python -m p2pfl_tpu.webapp <log_root> [--port 8666]``
"""

from __future__ import annotations

import argparse
import html
import json
import pathlib
import sys
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote

from p2pfl_tpu.utils.monitor import (
    DEFAULT_LIVENESS_S,
    read_statuses,
    render_table_html,
)

_STYLE = """
body{font-family:monospace;background:#111;color:#ddd;padding:1em}
a{color:#7cf} table{border-collapse:collapse}
td,th{padding:.3em .8em;border:1px solid #333} th{background:#222}
pre{background:#000;padding:1em;overflow-x:auto}
"""


def _page(title: str, body: str, refresh: int | None = None) -> bytes:
    meta = (
        f'<meta http-equiv="refresh" content="{refresh}">' if refresh else ""
    )
    return (
        f"<!doctype html><html><head>{meta}<title>{title}</title>"
        f"<style>{_STYLE}</style></head><body><h2>{title}</h2>{body}"
        "</body></html>"
    ).encode()


def list_scenarios(root: pathlib.Path) -> list[dict]:
    """Scenario index (the SQLite ``scenarios`` table's role,
    database.py:317-357): every log-root subdir that looks like a run."""
    out = []
    if not root.is_dir():
        return out
    for d in sorted(root.iterdir()):
        if not d.is_dir():
            continue
        statuses = read_statuses(d / "status")
        newest = max((s.get("ts", 0.0) for s in statuses), default=0.0)
        age = time.time() - newest if newest else None
        out.append(
            {
                "name": d.name,
                "n_nodes": len(statuses),
                "running": age is not None and age <= DEFAULT_LIVENESS_S,
                "has_metrics": (d / "metrics.jsonl").exists(),
                "last_seen_s": round(age, 1) if age is not None else None,
            }
        )
    return out


def _tail_text(path: pathlib.Path, max_bytes: int = 65536) -> str:
    """Last ``max_bytes`` of a file without reading the whole thing —
    dashboards auto-refresh every few seconds against logs that grow
    unboundedly, so tails must be O(window), not O(file)."""
    with open(path, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        data = f.read()
    text = data.decode("utf-8", errors="replace")
    # drop the first (likely partial) line when the window is clipped
    if size > max_bytes and "\n" in text:
        text = text.split("\n", 1)[1]
    return text


def tail_metrics(root: pathlib.Path, name: str, n: int = 200) -> list[dict]:
    path = root / name / "metrics.jsonl"
    if not path.exists():
        return []
    lines = _tail_text(path, max_bytes=256 * 1024).splitlines()[-n:]
    out = []
    for line in lines:
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


class DashboardHandler(BaseHTTPRequestHandler):
    root: pathlib.Path  # set by make_server

    def log_message(self, *args) -> None:  # quiet
        pass

    def _send(self, body: bytes, ctype: str = "text/html",
              code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj) -> None:
        self._send(json.dumps(obj).encode(), "application/json")

    def _safe_child(self, *parts: str) -> pathlib.Path | None:
        """Resolve a path strictly under the log root: every segment
        must be a single clean path component (no separators — URL
        %2F-decoding happens before this — and no dot-dots), and the
        resolved path must still live under the root (symlink guard)."""
        for part in parts:
            if (not part or part in (".", "..")
                    or "/" in part or "\\" in part or "\x00" in part):
                return None
        p = self.root.joinpath(*parts).resolve()
        return p if p.is_relative_to(self.root.resolve()) else None

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        parts = [unquote(p) for p in self.path.split("?")[0].split("/") if p]
        try:
            self._route(parts)
        except BrokenPipeError:
            pass
        except Exception as e:  # any handler bug -> 500, keep serving
            self._send(_page("error", f"<pre>{html.escape(str(e))}</pre>"),
                       code=500)

    def _route(self, parts: list[str]) -> None:
        if not parts:
            return self._index()
        if parts[0] == "api":
            if len(parts) == 2 and parts[1] == "scenarios":
                return self._json(list_scenarios(self.root))
            if len(parts) == 3 and parts[1] == "scenario":
                safe = self._safe_child(parts[2], "status")
                if safe is None:
                    return self._json([])
                return self._json(read_statuses(safe))
            if len(parts) == 3 and parts[1] == "metrics":
                if self._safe_child(parts[2]) is None:
                    return self._json([])
                return self._json(tail_metrics(self.root, parts[2]))
        if len(parts) == 2 and parts[0] == "scenario":
            return self._scenario(parts[1])
        if len(parts) == 2 and parts[0] == "topology":
            path = self._safe_child(parts[1], "topology.png")
            if path is not None and path.is_file():
                return self._send(path.read_bytes(), "image/png")
        if len(parts) == 3 and parts[0] == "logs":
            return self._logfile(parts[1], parts[2])
        self._send(_page("not found", "<p>404</p>"), code=404)

    def _index(self) -> None:
        rows = "".join(
            "<tr><td><a href='/scenario/{n}'>{n}</a></td><td>{c}</td>"
            "<td>{r}</td><td>{m}</td></tr>".format(
                n=html.escape(s["name"]), c=s["n_nodes"],
                r="running" if s["running"] else "stopped",
                m="yes" if s["has_metrics"] else "-",
            )
            for s in list_scenarios(self.root)
        )
        body = (
            "<table><tr><th>SCENARIO</th><th>NODES</th><th>STATE</th>"
            f"<th>METRICS</th></tr>{rows}</table>"
        )
        self._send(_page("p2pfl_tpu scenarios", body, refresh=5))

    def _scenario(self, name: str) -> None:
        safe = self._safe_child(name)
        if safe is None or not safe.is_dir():
            return self._send(_page("not found", "<p>404</p>"), code=404)
        statuses = read_statuses(safe / "status")
        inner = render_table_html(statuses)
        logs = sorted((safe / "logs").glob("*.log")) if (
            safe / "logs").is_dir() else []
        links = " | ".join(
            f"<a href='/logs/{html.escape(name)}/{p.name}'>{p.name}</a>"
            for p in logs
        )
        body = (
            inner
            + f"<p><a href='/api/metrics/{html.escape(name)}'>metrics</a>"
            + (f" | logs: {links}" if links else "")
            + "</p>"
        )
        if (safe / "topology.png").is_file():
            body += (
                f"<p><img src='/topology/{html.escape(name)}' "
                "alt='topology' style='max-width:480px'></p>"
            )
        self._send(_page(f"scenario {html.escape(name)}", body, refresh=2))

    def _logfile(self, name: str, fname: str) -> None:
        path = self._safe_child(name, "logs", fname)
        if path is None or not path.is_file():
            return self._send(_page("not found", "<p>404</p>"), code=404)
        # bounded tail with escaping (the reference's ANSI->HTML log
        # viewer, webserver/app.py:443-500; our logs carry no ANSI codes)
        tail = "\n".join(_tail_text(path).splitlines()[-500:])
        self._send(
            _page(f"{html.escape(fname)}",
                  f"<pre>{html.escape(tail)}</pre>", refresh=5)
        )


def make_server(log_root: str | pathlib.Path, port: int = 8666,
                host: str = "127.0.0.1") -> ThreadingHTTPServer:
    handler = type(
        "BoundHandler", (DashboardHandler,),
        {"root": pathlib.Path(log_root)},
    )
    return ThreadingHTTPServer((host, port), handler)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.webapp")
    ap.add_argument("log_root", help="the scenarios' log_dir root")
    ap.add_argument("--port", type=int, default=8666)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)
    server = make_server(args.log_root, args.port, args.host)
    print(f"dashboard on http://{args.host}:{server.server_address[1]}/")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
