"""Scenario web dashboard — the L5 frontend, stdlib-only.

The reference's largest subsystem is a Flask app + SQLite
(webserver/app.py:260-714, database.py): scenario list, live node
monitoring, log viewers, REST intake. This module delivers that
*capability* with no service dependencies: a `http.server` app that
reads the same on-disk artifacts the framework already writes
(`status/` records, `metrics.jsonl`, `logs/*.log`) and serves

- ``/``                    — scenario list (every run under the log root)
- ``/scenario/<name>``     — live node table (auto-refreshing) + links
- ``/designer``            — scenario designer form (deployment.html's
                             role) that deploys through the run endpoint
- ``/api/scenarios``       — JSON scenario index
- ``/api/scenario/<name>`` — JSON node statuses (the monitoring feed)
- ``/api/metrics/<name>``  — JSON tail of the metrics stream
- ``/logs/<name>/<file>``  — tail of a node's log file, rendered

Write routes (authenticated two ways, mirroring the reference's two
client classes: browsers get login/session-cookie auth with role-gated
user administration — webserver/app.py:195-254, users table
database.py:54-120 — via ``/login`` + a ``users.json`` store
(`p2pfl_tpu.users`); automation keeps the shared bearer token as
``Authorization: Bearer <token>`` / ``X-Auth-Token`` header / ``token``
form field):

- ``POST /api/scenario/run``          — deploy: accepts a ScenarioConfig
  JSON body (or the designer's form), stamps it under the log root and
  launches ``python -m p2pfl_tpu.run`` as a child process (the
  deployment-run endpoint, app.py:602-691)
- ``POST /api/scenario/<name>/stop``  — terminate a deployed run
  (app.py:532-543)
- ``POST /api/scenario/<name>/remove``— stop + delete its artifacts
  (app.py:545-555)
- ``POST /api/scenario/<name>/reload``— re-deploy from the scenario's
  saved config (app.py:694-714)

Session surface (enabled by ``--users users.json``):

- ``GET/POST /login`` — login form; sets an HttpOnly session cookie
- ``POST /logout``    — drops the session
- ``GET /admin/users``, ``POST /api/users/add|remove`` — admin-role
  user CRUD (the reference's user administration, app.py:222-254)
- with a user store configured, the READ surface (index, charts,
  metrics, log tails, downloads) also requires a session or the
  bearer token — matching the reference's login-gated views; without
  one, reads stay open (token-only automation servers)
- cookie-authenticated state-changing POSTs carry a per-session CSRF
  token (hidden form field / ``csrf`` JSON key) on top of
  ``SameSite=Strict``; bearer-token calls are exempt (no cookie to
  ride)

Charts: ``/charts/<name>`` renders per-node scalar curves (loss,
accuracy, ...) from ``metrics.jsonl`` as inline SVG — the role of the
reference's proxied TensorBoard statistics frontend
(controller.py:184-202, webserver/app.py:562-583) without spawning a
server per scenario.

The filesystem IS the database: node upserts are the atomic
``node_*.status.json`` replaces (webserver/database.py:253-274's
role), so the dashboard needs no writer process and works for
in-process scenarios, socket federations, and compose deployments
sharing a log volume.

Run: ``python -m p2pfl_tpu.webapp <log_root> [--port 8666] [--token T]``
(no ``--token`` mints one and prints it at startup).
"""

from __future__ import annotations

import argparse
import hashlib
import html
import json
import math
import pathlib
import secrets
import shutil
import subprocess
import sys
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote

from p2pfl_tpu.obs.health import HealthEngine, evaluate_dir
from p2pfl_tpu.utils.monitor import (
    DEFAULT_LIVENESS_S,
    read_statuses,
    render_alerts_html,
    render_table_html,
)

_STYLE = """
body{font-family:monospace;background:#111;color:#ddd;padding:1em}
a{color:#7cf} table{border-collapse:collapse}
td,th{padding:.3em .8em;border:1px solid #333} th{background:#222}
.alerts{margin:.6em 0} .alerts li.crit{color:#f55}
.alerts li.warn{color:#fb0} .alerts.ok{color:#5a5}
pre{background:#000;padding:1em;overflow-x:auto}
"""


def _page(title: str, body: str, refresh: int | None = None) -> bytes:
    meta = (
        f'<meta http-equiv="refresh" content="{refresh}">' if refresh else ""
    )
    return (
        f"<!doctype html><html><head>{meta}<title>{title}</title>"
        f"<style>{_STYLE}</style></head><body><h2>{title}</h2>{body}"
        "</body></html>"
    ).encode()


def list_scenarios(root: pathlib.Path) -> list[dict]:
    """Scenario index (the SQLite ``scenarios`` table's role,
    database.py:317-357): every log-root subdir that looks like a run."""
    out = []
    if not root.is_dir():
        return out
    for d in sorted(root.iterdir()):
        if not d.is_dir():
            continue
        statuses = read_statuses(d / "status")
        newest = max((s.get("ts", 0.0) for s in statuses), default=0.0)
        age = time.time() - newest if newest else None
        out.append(
            {
                "name": d.name,
                "n_nodes": len(statuses),
                "running": age is not None and age <= DEFAULT_LIVENESS_S,
                "has_metrics": (d / "metrics.jsonl").exists(),
                "last_seen_s": round(age, 1) if age is not None else None,
            }
        )
    return out


def _tail_text(path: pathlib.Path, max_bytes: int = 65536) -> str:
    """Last ``max_bytes`` of a file without reading the whole thing —
    dashboards auto-refresh every few seconds against logs that grow
    unboundedly, so tails must be O(window), not O(file)."""
    with open(path, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        data = f.read()
    text = data.decode("utf-8", errors="replace")
    # drop the first (likely partial) line when the window is clipped
    if size > max_bytes and "\n" in text:
        text = text.split("\n", 1)[1]
    return text


def tail_metrics(root: pathlib.Path, name: str, n: int = 200) -> list[dict]:
    path = root / name / "metrics.jsonl"
    if not path.exists():
        return []
    lines = _tail_text(
        path, max_bytes=max(256 * 1024, n * 256)
    ).splitlines()[-n:]
    out = []
    for line in lines:
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


class Sessions:
    """In-memory session cookies (the reference keeps Flask server-side
    sessions; a dashboard restart logging everyone out is acceptable —
    and means no session secrets ever touch disk)."""

    def __init__(self, ttl_s: float = 12 * 3600):
        import threading

        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._sessions: dict[str, dict] = {}

    def create(self, user: str, role: str) -> str:
        token = secrets.token_urlsafe(24)
        with self._lock:
            self._sessions[token] = {
                "user": user, "role": role,
                "expires": time.time() + self.ttl_s,
            }
        return token

    def get(self, token: str | None) -> dict | None:
        if not token:
            return None
        with self._lock:
            s = self._sessions.get(token)
            if s is None:
                return None
            if s["expires"] < time.time():
                del self._sessions[token]
                return None
            return dict(s)

    def drop(self, token: str | None) -> None:
        with self._lock:
            self._sessions.pop(token, None)

    def drop_user(self, user: str) -> None:
        """Invalidate every session of one user — removal or a password
        change must not leave a live cookie with write access."""
        with self._lock:
            for token in [t for t, s in self._sessions.items()
                          if s["user"] == user]:
                del self._sessions[token]


# ---- SVG scalar charts (the TensorBoard-statistics role) ----------------

# Validated dark categorical palette (adjacent-pairlist, dark chart
# surface #1a1a19) — fixed slot order, assigned per node id, never
# cycled past 8: beyond 8 nodes the per-node lines fold to a muted
# single hue with the federation mean as the one highlighted series.
_SERIES = ("#3987e5", "#d95926", "#199e70", "#c98500",
           "#d55181", "#008300", "#9085e9", "#e66767")
_CHART_SURFACE = "#1a1a19"
_GRID, _AXIS, _MUTED, _INK = "#2c2c2a", "#383835", "#898781", "#e8e6dd"
_MAX_COLORED_SERIES = 8
_MAX_POINTS_PER_SERIES = 240


def _metric_series(records: list[dict]) -> dict[str, dict[str, list]]:
    """metric -> series-label -> [(step, value)], from metrics.jsonl
    records. ``node: None`` records become the "federation" series;
    the ``round_boundary`` markers are not scalar curves."""
    out: dict[str, dict[str, list]] = {}
    for rec in records:
        node = rec.get("node")
        label = "federation" if node is None else f"node {node}"
        step = rec.get("step", 0)
        if not isinstance(step, (int, float)):
            continue  # foreign writer on the shared log volume
        for key, val in rec.items():
            if key in ("ts", "step", "round", "node", "round_boundary"):
                continue
            if not isinstance(val, (int, float)):
                continue
            # a diverged node's NaN/Inf (json.dumps happily writes bare
            # NaN) must not poison the shared y-scale and blank every
            # healthy node's curve
            if not (math.isfinite(val) and math.isfinite(step)):
                continue
            out.setdefault(key, {}).setdefault(label, []).append(
                (float(step), float(val))
            )
    return out


def _ticks(lo: float, hi: float, n: int = 4) -> list[float]:
    if hi <= lo:
        return [lo]
    span = hi - lo
    return [lo + span * i / n for i in range(n + 1)]


def _fmt(v: float) -> str:
    a = abs(v)
    if a and (a < 0.01 or a >= 10000):
        return f"{v:.2e}"
    return f"{v:.4g}"


def _svg_chart(title: str, series: dict[str, list], w: int = 460,
               h: int = 220) -> str:
    """One scalar chart: 2px polylines on the validated dark surface,
    hairline grid, muted axis labels, per-point <title> hover readout.
    <= 8 series get the fixed categorical slots + a legend; more fold
    to muted lines with the federation mean highlighted (identity is
    then in the hover layer, not color)."""
    pts = [p for s in series.values() for p in s]
    if not pts:
        return ""
    ml, mr, mt, mb = 52, 10, 8, 22  # margins: left, right, top, bottom
    x0, x1 = min(p[0] for p in pts), max(p[0] for p in pts)
    y0, y1 = min(p[1] for p in pts), max(p[1] for p in pts)
    if y1 == y0:
        y0, y1 = y0 - 0.5, y1 + 0.5
    if x1 == x0:
        x1 = x0 + 1.0

    def sx(x):
        return round(ml + (x - x0) / (x1 - x0) * (w - ml - mr), 1)

    def sy(y):
        return round(h - mb - (y - y0) / (y1 - y0) * (h - mt - mb), 1)

    grid = "".join(
        f"<line x1='{ml}' y1='{sy(t)}' x2='{w - mr}' y2='{sy(t)}' "
        f"stroke='{_GRID}' stroke-width='1'/>"
        f"<text x='{ml - 6}' y='{sy(t) + 3}' fill='{_MUTED}' "
        f"font-size='10' text-anchor='end'>{_fmt(t)}</text>"
        for t in _ticks(y0, y1)
    )
    grid += "".join(
        f"<text x='{sx(t)}' y='{h - 6}' fill='{_MUTED}' font-size='10' "
        f"text-anchor='middle'>{_fmt(t)}</text>"
        for t in _ticks(x0, x1, 3)
    )

    labels = sorted(series, key=lambda s: (s == "federation", s))
    many = len(labels) > _MAX_COLORED_SERIES
    lines, legend = [], []
    for i, label in enumerate(labels):
        data = sorted(series[label])
        if len(data) > _MAX_POINTS_PER_SERIES:
            # decimate long runs: the page is rebuilt per auto-refresh,
            # and 10k hover circles per chart serve nobody. Keep the
            # endpoints, stride the middle.
            stride = (len(data) - 1) // (_MAX_POINTS_PER_SERIES - 1) + 1
            data = data[::stride] + [data[-1]]
        if many:
            color = _SERIES[0] if label == "federation" else _MUTED
            width = 2 if label == "federation" else 1
        else:
            color, width = _SERIES[i % len(_SERIES)], 2
        path = " ".join(f"{sx(x)},{sy(y)}" for x, y in data)
        lines.append(
            f"<polyline points='{path}' fill='none' stroke='{color}' "
            f"stroke-width='{width}' stroke-linejoin='round'/>"
        )
        esc = html.escape(label)
        lines.extend(
            f"<circle cx='{sx(x)}' cy='{sy(y)}' r='5' fill='transparent' "
            f"stroke='none'><title>{esc}: {_fmt(y)} @ step {_fmt(x)}"
            f"</title></circle>"
            for x, y in data
        )
        if not many or label == "federation":
            legend.append(
                f"<span style='color:{_INK}'><span style='color:{color}'>"
                f"&#9644;</span> {esc}</span>"
            )
    if many:
        n_nodes = sum(1 for s in labels if s != "federation")
        legend.append(
            f"<span style='color:{_MUTED}'>&#9644; {n_nodes} nodes "
            "(hover a point for identity)</span>"
        )
    return (
        f"<div style='display:inline-block;margin:.4em'>"
        f"<div style='color:{_INK};font-size:12px;padding:2px 0'>"
        f"{html.escape(title)}</div>"
        f"<svg width='{w}' height='{h}' role='img' "
        f"aria-label='{html.escape(title)}'>"
        f"<rect width='{w}' height='{h}' fill='{_CHART_SURFACE}'/>"
        f"{grid}"
        f"<line x1='{ml}' y1='{h - mb}' x2='{w - mr}' y2='{h - mb}' "
        f"stroke='{_AXIS}' stroke-width='1'/>"
        f"<line x1='{ml}' y1='{mt}' x2='{ml}' y2='{h - mb}' "
        f"stroke='{_AXIS}' stroke-width='1'/>"
        f"{''.join(lines)}</svg>"
        f"<div style='font-size:11px'>{' '.join(legend)}</div></div>"
    )


# per-round critical-path components -> bar colors (round-18 pane);
# order matters: it is the stacking order of the bar segments
_CRITPATH_COMPONENTS = (
    ("fit", "critpath_fit_s", "#3987e5"),
    ("wire", "critpath_wire_s", "#d95926"),
    ("wait", "critpath_wait_s", "#c98500"),
    ("agg", "critpath_agg_s", "#199e70"),
    ("other", "critpath_other_s", "#898781"),
)


def critpath_pane(statuses: list[dict]) -> str:
    """Per-round critical-path breakdown pane for the scenario page:
    one row per node showing where its LAST closed round's wall went
    (the ``critpath_*`` gauges launch.py publishes), with a stacked
    proportional bar. Empty string until any node reports a closed
    round — scenarios run untraced-era builds too."""
    rows = []
    for rec in statuses:
        wall = rec.get("critpath_round_s")
        if not wall:
            continue
        segs, cells = [], []
        for label, key, color in _CRITPATH_COMPONENTS:
            v = float(rec.get(key) or 0.0)
            pct = 100.0 * v / float(wall)
            segs.append(
                f"<span style='display:inline-block;background:{color};"
                f"height:10px;width:{pct:.1f}%' "
                f"title='{label} {v:.3f}s ({pct:.0f}%)'></span>"
            )
            cells.append(f"<td>{v:.3f}</td>")
        rows.append(
            "<tr><td>{n}</td><td>{r}</td><td>{w:.3f}</td>{cells}"
            "<td style='min-width:160px'><div style='width:160px;"
            "background:#000'>{bar}</div></td></tr>".format(
                n=rec.get("node", "?"),
                r=rec.get("critpath_round", "?"),
                w=float(wall), cells="".join(cells), bar="".join(segs),
            )
        )
    if not rows:
        return ""
    legend = " ".join(
        f"<span style='color:{color}'>&#9644;</span> {label}"
        for label, _, color in _CRITPATH_COMPONENTS
    )
    head = "".join(
        f"<th>{h}</th>"
        for h in ("NODE", "ROUND", "ROUND_S", "FIT", "WIRE", "WAIT",
                  "AGG", "OTHER", "")
    )
    return (
        "<h3>round critical path</h3>"
        f"<div style='font-size:11px'>{legend}</div>"
        f"<table><tr>{head}</tr>{''.join(rows)}</table>"
    )


def devprof_pane(statuses: list[dict]) -> str:
    """Device-profiling pane (round 22): per-node live utilization —
    MFU as a filled bar against the chip peak (achieved TFLOP/s shown
    bare when the backend has no peak table entry, e.g. CPU) and the
    HBM high-water against its limit (host RSS fallback). Empty string
    until any node publishes ``devprof_*`` gauges (P2PFL_DEVPROF)."""
    rows = []
    for rec in statuses:
        if rec.get("devprof_fit_s") is None:
            continue
        mfu = rec.get("devprof_mfu")
        tflops = rec.get("devprof_tflops")
        if mfu is not None:
            pct = min(100.0 * float(mfu), 100.0)
            util = (
                f"<td>{float(mfu) * 100:.1f}%</td><td style='min-width:"
                "120px'><div style='width:120px;background:#000'>"
                f"<span style='display:inline-block;background:#3987e5;"
                f"height:10px;width:{pct:.1f}%'></span></div></td>"
            )
        else:
            util = ("<td>{}</td><td></td>".format(
                f"{float(tflops):.2f}T" if tflops is not None else "-"))
        peak = rec.get("devprof_hbm_peak_mb")
        limit = rec.get("devprof_hbm_limit_mb")
        rss = rec.get("devprof_rss_peak_mb")
        if peak is not None and limit:
            hpct = min(100.0 * float(peak) / float(limit), 100.0)
            color = "#d95926" if hpct >= 85.0 else "#199e70"
            mem = (
                f"<td>{float(peak):.0f}/{float(limit):.0f}M</td>"
                "<td style='min-width:120px'><div style='width:120px;"
                f"background:#000'><span style='display:inline-block;"
                f"background:{color};height:10px;width:{hpct:.1f}%'>"
                "</span></div></td>"
            )
        elif peak is not None:
            mem = f"<td>{float(peak):.0f}M</td><td></td>"
        else:
            mem = ("<td>{}</td><td></td>".format(
                f"rss {float(rss):.0f}M" if rss is not None else "-"))
        rows.append(
            "<tr><td>{n}</td><td>{f:.3f}</td>{util}{mem}</tr>".format(
                n=rec.get("node", "?"),
                f=float(rec["devprof_fit_s"]), util=util, mem=mem,
            )
        )
    if not rows:
        return ""
    head = "".join(
        f"<th>{h}</th>"
        for h in ("NODE", "FIT_S", "MFU", "", "HBM", "")
    )
    return (
        "<h3>device profile (MFU / memory)</h3>"
        f"<table><tr>{head}</tr>{''.join(rows)}</table>"
    )


class Deployments:
    """Child processes launched through the run endpoint, by scenario
    name (the Controller-in-process role, app.py:679-681 — here a
    subprocess so a crashing scenario cannot take the dashboard down)."""

    def __init__(self):
        import threading

        self.procs: dict[str, subprocess.Popen] = {}
        # ThreadingHTTPServer handles requests concurrently: without
        # the lock a double-submitted deploy passes the poll() check
        # twice and orphans the first child
        self._lock = threading.Lock()

    def launch(self, name: str, config_path: pathlib.Path,
               scenario_dir: pathlib.Path, platform: str | None) -> int:
        with self._lock:
            old = self.procs.get(name)
            if old is not None and old.poll() is None:
                raise RuntimeError(f"scenario {name!r} is already running")
            cmd = [sys.executable, "-m", "p2pfl_tpu.run", str(config_path)]
            if platform:
                cmd += ["--platform", platform]
            out = open(scenario_dir / "run.log", "ab")
            proc = subprocess.Popen(cmd, stdout=out,
                                    stderr=subprocess.STDOUT)
            out.close()  # the child holds its own fd
            self.procs[name] = proc
            return proc.pid

    def stop(self, name: str) -> bool:
        proc = self.procs.get(name)
        if proc is None or proc.poll() is not None:
            return False
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        return True

    def state(self, name: str) -> str | None:
        proc = self.procs.get(name)
        if proc is None:
            return None
        return "running" if proc.poll() is None else f"exited({proc.poll()})"


class DashboardHandler(BaseHTTPRequestHandler):
    root: pathlib.Path  # set by make_server
    token: str | None = None  # write-route auth; None disables writes
    deployments: Deployments  # set by make_server
    users = None  # UserStore; None disables login/session auth
    sessions: Sessions  # set by make_server

    def log_message(self, *args) -> None:  # quiet
        pass

    def _send(self, body: bytes, ctype: str = "text/html",
              code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj) -> None:
        self._send(json.dumps(obj).encode(), "application/json")

    def _safe_child(self, *parts: str) -> pathlib.Path | None:
        """Resolve a path strictly under the log root: every segment
        must be a single clean path component (no separators — URL
        %2F-decoding happens before this — and no dot-dots), and the
        resolved path must still live under the root (symlink guard)."""
        for part in parts:
            if (not part or part in (".", "..")
                    or "/" in part or "\\" in part
                    or '"' in part
                    or any(ord(c) < 0x20 or ord(c) == 0x7F for c in part)):
                return None
        p = self.root.joinpath(*parts).resolve()
        return p if p.is_relative_to(self.root.resolve()) else None

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        parts = [unquote(p) for p in self.path.split("?")[0].split("/") if p]
        try:
            self._route(parts)
        except BrokenPipeError:
            pass
        except Exception as e:  # any handler bug -> 500, keep serving
            self._send(_page("error", f"<pre>{html.escape(str(e))}</pre>"),
                       code=500)

    # ---- write surface ---------------------------------------------------

    def _read_body(self) -> bytes | None:
        """Request body, or None after replying 413: a truncated read
        would parse as broken JSON (opaque 500) and leave the unread
        bytes on the keep-alive connection to corrupt the next
        pipelined request, so oversized bodies are rejected outright
        and the connection closed."""
        # clamp below 0: read(-1) would block to EOF on a keep-alive
        # socket, tying a server thread up indefinitely
        length = max(0, int(self.headers.get("Content-Length") or 0))
        if length > (1 << 20):
            self.close_connection = True
            self._json_code({"error": "body too large (1 MiB cap)"}, 413)
            return None
        return self.rfile.read(length) if length else b""

    def _session_token(self) -> str | None:
        cookie = self.headers.get("Cookie") or ""
        for part in cookie.split(";"):
            k, _, v = part.strip().partition("=")
            if k == "p2pfl_session":
                return v
        return None

    def _session(self) -> dict | None:
        """Session record from the request's cookie, if valid."""
        return self.sessions.get(self._session_token())

    def _token_ok(self, form: dict | None = None) -> bool:
        """Shared bearer-token check (API clients / automation).
        Constant-time compare; no configured token = no token auth."""
        if self.token is None:
            return False
        auth = self.headers.get("Authorization") or ""
        candidates = [
            auth[7:] if auth.startswith("Bearer ") else auth,
            self.headers.get("X-Auth-Token") or "",
        ]
        if form:
            candidates.extend(form.get("token", []))
        return any(
            c and secrets.compare_digest(c, self.token) for c in candidates
        )

    def _authorized(self, form: dict | None = None) -> bool:
        """Mutating routes: a valid login session (any role) or the
        shared bearer token (the reference gates writes behind session
        auth, app.py:195-254; the token keeps automation working). A
        server with neither a token nor a user store refuses writes
        outright rather than running them open."""
        return self._token_ok(form) or self._session() is not None

    def _admin_ok(self, form: dict | None = None) -> bool:
        """User CRUD: admin-role session, or the bearer token (the
        operator who configured the server owns its user store)."""
        if self._token_ok(form):
            return True
        s = self._session()
        return s is not None and s.get("role") == "admin"

    def _read_ok(self) -> bool:
        """Read routes: open when no user store is configured (token-
        only servers match rounds 1-3 behavior), but once ``--users``
        exists the whole read surface (charts, log tails, metrics,
        downloads) requires a session or the bearer token — the
        reference gates ALL views behind login (app.py:195-254), and
        metrics/logs must not be more exposed here than there."""
        return (self.users is None or self._session() is not None
                or self._token_ok())

    @staticmethod
    def _derive_csrf(session_token: str) -> str:
        """Per-session CSRF token, derived (not stored): a hidden form
        field the attacker's cross-site form cannot know. SameSite is
        the first line; this covers older/non-conforming clients."""
        return hashlib.sha256(b"csrf:" + session_token.encode()).hexdigest()[:32]

    def _csrf_field(self) -> str:
        """Hidden input for cookie-authenticated HTML forms."""
        tok = self._session_token()
        if self.sessions.get(tok) is None:
            return ""
        return (f"<input type='hidden' name='csrf' "
                f"value='{self._derive_csrf(tok)}'>")

    def _csrf_ok(self, body: bytes, form: dict | None) -> bool:
        """State-changing POSTs authorized by a session COOKIE must
        carry the session's CSRF token; bearer-token callers are not
        cookie-authenticated, so no cross-site form can ride them."""
        if self._token_ok(form):
            return True
        tok = self._session_token()
        if self.sessions.get(tok) is None:
            return False  # unauthenticated — the auth check 401s first
        supplied = self._field(body, form, "csrf")
        return bool(supplied) and secrets.compare_digest(
            supplied, self._derive_csrf(tok))

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        parts = [unquote(p) for p in self.path.split("?")[0].split("/") if p]
        try:
            body = self._read_body()
            if body is None:
                return  # 413 already sent
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            # urllib and curl default the content type to urlencoded even
            # for JSON bodies — sniff the payload, don't trust the header
            looks_json = body.lstrip()[:1] in (b"{", b"[")
            form = (
                parse_qs(body.decode("utf-8", errors="replace"))
                if ctype == "application/x-www-form-urlencoded"
                and body and not looks_json else None
            )
            if parts == ["login"]:
                # PRE-SESSION endpoint: no session cookie exists yet, so
                # the derived per-session token cannot apply. Login CSRF
                # ("log the victim into the attacker's account") is
                # covered by the SameSite=Strict session cookie set in
                # _login — a cross-site form never sends it, and this
                # app has no pre-auth state worth riding. A double-
                # submit pre-session token would only re-cover ancient
                # non-SameSite clients; documented in docs/webapp.md.
                return self._login(body, form)
            if parts == ["logout"]:
                # logout is state-changing and cookie-authenticated:
                # it requires the derived CSRF token like every other
                # session POST (a cross-site form could otherwise kill
                # the session — a nuisance-class but real CSRF). With
                # no session there is nothing to forge: plain redirect.
                if (self.sessions.get(self._session_token()) is not None
                        and not self._csrf_ok(body, form)):
                    return self._json_code({"error": "bad csrf token"}, 403)
                return self._logout()
            if len(parts) == 3 and parts[:2] == ["api", "users"]:
                if not self._admin_ok(form):
                    return self._json_code({"error": "admin required"}, 401)
                if not self._csrf_ok(body, form):
                    return self._json_code({"error": "bad csrf token"}, 403)
                return self._users_crud(parts[2], body, form)
            if not self._authorized(form):
                return self._json_code(
                    {"error": "missing or bad auth token"}, 401
                )
            if not self._csrf_ok(body, form):
                return self._json_code({"error": "bad csrf token"}, 403)
            if parts == ["api", "scenario", "run"] or parts == [
                "scenario", "deployment", "run"
            ]:
                return self._run_scenario(body, form)
            if len(parts) == 4 and parts[:2] == ["api", "scenario"]:
                name, action = parts[2], parts[3]
                if self._safe_child(name) is None:
                    return self._json_code({"error": "bad scenario name"}, 400)
                if action == "stop":
                    stopped = self.deployments.stop(name)
                    return self._json({"name": name, "stopped": stopped})
                if action == "remove":
                    self.deployments.stop(name)
                    target = self._safe_child(name)
                    if target is not None and target.is_dir():
                        shutil.rmtree(target)
                        return self._json({"name": name, "removed": True})
                    return self._json({"name": name, "removed": False})
                if action == "reload":
                    return self._reload_scenario(name, form)
            self._send(_page("not found", "<p>404</p>"), code=404)
        except BrokenPipeError:
            pass
        except Exception as e:
            self._json_code({"error": str(e)}, 500)

    def _json_code(self, obj, code: int) -> None:
        self._send(json.dumps(obj).encode(), "application/json", code=code)

    def _config_from_request(self, body: bytes, form: dict | None):
        """ScenarioConfig from a JSON body (automation) or the designer
        form (app.py:649-673 builds participant JSONs the same way)."""
        from p2pfl_tpu.config.schema import (
            DataConfig,
            ModelConfig,
            ScenarioConfig,
            TrainingConfig,
        )

        if form is None:
            d = json.loads(body.decode())
            # auth fields ride the same JSON body for cookie-session
            # clients; they are not scenario knobs
            if isinstance(d, dict):
                d.pop("csrf", None)
                d.pop("token", None)
            return ScenarioConfig.from_dict(d)

        def one(key, default=None):
            vals = form.get(key)
            return vals[0] if vals else default

        return ScenarioConfig(
            name=one("name", "scenario"),
            federation=one("federation", "DFL"),
            topology=one("topology", "fully"),
            n_nodes=int(one("nodes", 2)),
            data=DataConfig(
                dataset=one("dataset", "mnist"),
                partition=one("partition", "iid"),
                samples_per_node=(
                    int(one("samples_per_node"))
                    if one("samples_per_node") else None
                ),
            ),
            model=ModelConfig(model=one("model", "mnist-mlp")),
            training=TrainingConfig(
                rounds=int(one("rounds", 3)),
                epochs_per_round=int(one("epochs", 1)),
                learning_rate=float(one("lr", 0.1)),
            ),
            aggregator=one("aggregator", "fedavg"),
        )

    def _run_scenario(self, body: bytes, form: dict | None) -> None:
        cfg = self._config_from_request(body, form)
        if self._safe_child(cfg.name) is None:
            return self._json_code({"error": "bad scenario name"}, 400)
        scenario_dir = self.root / cfg.name
        scenario_dir.mkdir(parents=True, exist_ok=True)
        # the child logs into the dashboard's own root, so this page
        # monitors what it launched (controller stamping, app.py:649-673)
        cfg.log_dir = str(self.root)
        config_path = scenario_dir / "scenario.json"
        cfg.save(config_path)
        platform = None
        if form and form.get("platform"):
            platform = form["platform"][0]
        elif form is None:
            platform = (self.headers.get("X-Platform") or None)
        pid = self.deployments.launch(cfg.name, config_path, scenario_dir,
                                      platform)
        if form is not None:  # designer: bounce to the live page
            self.send_response(303)
            self.send_header("Location", f"/scenario/{cfg.name}")
            self.end_headers()
            return
        self._json({"name": cfg.name, "pid": pid, "started": True})

    def _reload_scenario(self, name: str, form: dict | None) -> None:
        """Re-deploy from the saved config (app.py:694-714)."""
        config_path = self._safe_child(name, "scenario.json")
        if config_path is None or not config_path.is_file():
            return self._json_code({"error": "no saved config"}, 404)
        scenario_dir = config_path.parent
        platform = form["platform"][0] if form and form.get("platform") \
            else (self.headers.get("X-Platform") or None)
        pid = self.deployments.launch(name, config_path, scenario_dir,
                                      platform)
        self._json({"name": name, "pid": pid, "started": True})

    # ---- sessions + user CRUD (app.py:195-254, database.py:54-120) ------

    def _field(self, body: bytes, form: dict | None, key: str) -> str:
        if form is not None:
            vals = form.get(key)
            return vals[0] if vals else ""
        try:
            obj = json.loads(body.decode() or "{}")
            if not isinstance(obj, dict):  # JSON array/scalar body
                return ""
            val = obj.get(key, "")
            return val if isinstance(val, str) else ""
        except ValueError:
            return ""

    def _login(self, body: bytes, form: dict | None) -> None:
        if self.users is None:
            return self._json_code({"error": "no user store configured"}, 404)
        user = self._field(body, form, "user")
        password = self._field(body, form, "password")
        role = self.users.verify(user, password)
        if role is None:
            return self._send(
                _page("login failed",
                      "<p>bad username or password</p>"
                      "<p><a href='/login'>try again</a></p>"),
                code=401,
            )
        token = self.sessions.create(user, role)
        self.send_response(303)
        self.send_header("Location", "/")
        self.send_header(
            "Set-Cookie",
            f"p2pfl_session={token}; HttpOnly; SameSite=Strict; Path=/",
        )
        self.end_headers()

    def _logout(self) -> None:
        self.sessions.drop(self._session_token())
        self.send_response(303)
        self.send_header("Location", "/")
        self.send_header(
            "Set-Cookie",
            "p2pfl_session=; Max-Age=0; HttpOnly; SameSite=Strict; Path=/",
        )
        self.end_headers()

    def _users_crud(self, action: str, body: bytes,
                    form: dict | None) -> None:
        if self.users is None:
            return self._json_code({"error": "no user store configured"}, 404)
        user = self._field(body, form, "user")
        if action == "add":
            password = self._field(body, form, "password")
            # omitted role -> None: UserStore preserves an existing
            # user's role (password reset must not demote an admin)
            role = self._field(body, form, "role") or None
            try:
                self.users.add(user, password, role)
            except ValueError as e:
                return self._json_code({"error": str(e)}, 400)
            # a credential/role change invalidates the user's live
            # sessions — the next request must authenticate freshly
            self.sessions.drop_user(user)
            if form is not None:
                self.send_response(303)
                self.send_header("Location", "/admin/users")
                self.end_headers()
                return
            return self._json({"user": user,
                               "role": self.users.list().get(user),
                               "added": True})
        if action == "remove":
            removed = self.users.remove(user)
            self.sessions.drop_user(user)  # no 12h ghost write access
            if form is not None:
                self.send_response(303)
                self.send_header("Location", "/admin/users")
                self.end_headers()
                return
            return self._json({"user": user, "removed": removed})
        self._json_code({"error": f"unknown user action {action!r}"}, 404)

    def _login_page(self) -> None:
        if self.users is None:
            body = ("<p>no user store configured — start the dashboard "
                    "with <code>--users users.json</code>; the API token "
                    "still authenticates automation</p>")
        else:
            body = (
                "<form method='post' action='/login'>"
                "<p><label>user <input name='user'></label></p>"
                "<p><label>password <input name='password' "
                "type='password'></label></p>"
                "<p><button>log in</button></p></form>"
            )
        self._send(_page("login", body))

    def _admin_users_page(self) -> None:
        if self.users is None:
            return self._send(
                _page("user administration",
                      "<p>no user store configured (--users)</p>"),
                code=404,
            )
        if not self._admin_ok():
            return self._send(
                _page("forbidden",
                      "<p>admin login required — <a href='/login'>log in"
                      "</a></p>"),
                code=401,
            )
        csrf = self._csrf_field()
        rows = "".join(
            f"<tr><td>{html.escape(u)}</td><td>{html.escape(r)}</td>"
            f"<td><form method='post' action='/api/users/remove' "
            f"style='margin:0'><input type='hidden' name='user' "
            f"value='{html.escape(u, quote=True)}'>{csrf}"
            f"<button>remove</button></form></td></tr>"
            for u, r in self.users.list().items()
        )
        body = (
            f"<table><tr><th>USER</th><th>ROLE</th><th></th></tr>{rows}"
            "</table><h3>add / update user</h3>"
            "<form method='post' action='/api/users/add'>"
            "<label>user <input name='user'></label> "
            "<label>password <input name='password' type='password'>"
            "</label> <label>role <select name='role'>"
            "<option value=''>(keep existing / user)</option>"
            "<option>user</option><option>admin</option></select></label> "
            f"{csrf}<button>save</button></form>"
        )
        self._send(_page("user administration", body))

    def _charts(self, name: str) -> None:
        """Per-node scalar curves from metrics.jsonl (the reference's
        TensorBoard statistics view, app.py:562-583)."""
        safe = self._safe_child(name)
        if safe is None or not safe.is_dir():
            return self._send(_page("not found", "<p>404</p>"), code=404)
        records = tail_metrics(self.root, name, n=5000)
        charts = "".join(
            _svg_chart(metric, series)
            for metric, series in sorted(_metric_series(records).items())
        )
        body = (
            charts or "<p>no metrics recorded yet</p>"
        ) + (
            f"<p><a href='/scenario/{html.escape(name)}'>back</a> | "
            f"<a href='/api/metrics/{html.escape(name)}'>table view "
            "(JSON)</a></p>"
        )
        self._send(_page(f"charts — {html.escape(name)}", body, refresh=10))

    def _route(self, parts: list[str]) -> None:
        if parts == ["login"]:
            return self._login_page()
        if not self._read_ok():
            if parts and parts[0] == "api":
                return self._json_code({"error": "login required"}, 401)
            self.send_response(303)
            self.send_header("Location", "/login")
            self.end_headers()
            return
        if not parts:
            return self._index()
        if parts == ["admin", "users"]:
            return self._admin_users_page()
        if len(parts) == 2 and parts[0] == "charts":
            return self._charts(parts[1])
        if parts[0] == "api":
            if len(parts) == 2 and parts[1] == "scenarios":
                return self._json(list_scenarios(self.root))
            if len(parts) == 3 and parts[1] == "scenario":
                safe = self._safe_child(parts[2], "status")
                if safe is None:
                    return self._json([])
                return self._json(read_statuses(safe))
            if len(parts) == 3 and parts[1] == "metrics":
                if self._safe_child(parts[2]) is None:
                    return self._json([])
                return self._json(tail_metrics(self.root, parts[2]))
            if len(parts) == 3 and parts[1] == "health":
                safe = self._safe_child(parts[2])
                if safe is None:
                    return self._json({})
                # one-shot engine: the HTTP surface is stateless, each
                # GET re-judges the current snapshot (transition history
                # lives in the healthcheck CLI / monitor watchers)
                alerts, eng = evaluate_dir(safe, engine=HealthEngine())
                return self._json({
                    "severity": eng.worst(),
                    "alerts": [a.to_dict() for a in alerts],
                })
            if len(parts) == 3 and parts[1] == "topology3d":
                path = self._safe_child(parts[2], "topology_3d.json")
                if path is not None and path.is_file():
                    return self._send(path.read_bytes(), "application/json")
                return self._json({})
            if len(parts) == 3 and parts[1] == "download":
                return self._download(parts[2])
        if parts == ["designer"]:
            return self._designer()
        if len(parts) == 2 and parts[0] == "scenario":
            return self._scenario(parts[1])
        if len(parts) == 2 and parts[0] == "topology":
            path = self._safe_child(parts[1], "topology.png")
            if path is not None and path.is_file():
                return self._send(path.read_bytes(), "image/png")
        if len(parts) == 3 and parts[0] == "logs":
            return self._logfile(parts[1], parts[2])
        self._send(_page("not found", "<p>404</p>"), code=404)

    def _index(self) -> None:
        rows = "".join(
            "<tr><td><a href='/scenario/{n}'>{n}</a></td><td>{c}</td>"
            "<td>{r}</td><td>{d}</td><td>{m}</td></tr>".format(
                n=html.escape(s["name"]), c=s["n_nodes"],
                r="running" if s["running"] else "stopped",
                d=html.escape(self.deployments.state(s["name"]) or "-"),
                m=("<a href='/charts/%s'>charts</a>" % html.escape(s["name"])
                   if s["has_metrics"] else "-"),
            )
            for s in list_scenarios(self.root)
        )
        session = self._session()
        if session is not None:
            who = (
                f"logged in as {html.escape(session['user'])} "
                f"({html.escape(session['role'])}) "
                "<form method='post' action='/logout' "
                "style='display:inline;margin:0'>"
                f"{self._csrf_field()}<button>log out</button>"
                "</form>"
                + (" | <a href='/admin/users'>users</a>"
                   if session["role"] == "admin" else "")
            )
        elif self.users is not None:
            who = "<a href='/login'>log in</a>"
        else:
            who = ""
        body = (
            (f"<p>{who}</p>" if who else "")
            + "<p><a href='/designer'>deploy a new scenario</a></p>"
            "<table><tr><th>SCENARIO</th><th>NODES</th><th>STATE</th>"
            f"<th>DEPLOYMENT</th><th>METRICS</th></tr>{rows}</table>"
        )
        self._send(_page("p2pfl_tpu scenarios", body, refresh=5))

    def _designer(self) -> None:
        """Scenario designer (deployment.html's role) — POSTs to the
        deployment-run endpoint with the shared token."""
        def select(name, options):
            opts = "".join(f"<option>{o}</option>" for o in options)
            return f"<label>{name} <select name='{name}'>{opts}</select></label>"

        body = (
            "<form method='post' action='/scenario/deployment/run'>"
            "<p><label>name <input name='name' value='web-run'></label> "
            "<label>nodes <input name='nodes' value='2' size='3'></label> "
            + select("federation", ["DFL", "CFL", "SDFL"])
            + select("topology", ["fully", "ring", "random", "star"])
            + "</p><p>"
            + select("dataset", ["mnist", "femnist", "cifar10", "syscall",
                                 "wadi"])
            + "<label>model <input name='model' value='mnist-mlp'></label> "
            + select("partition", ["iid", "sorted", "dirichlet"])
            + select("aggregator", ["fedavg", "median", "trimmedmean",
                                    "krum"])
            + "</p><p>"
            "<label>rounds <input name='rounds' value='3' size='3'></label> "
            "<label>epochs <input name='epochs' value='1' size='3'></label> "
            "<label>lr <input name='lr' value='0.1' size='5'></label> "
            "<label>samples/node <input name='samples_per_node' value='256' "
            "size='6'></label>"
            "</p><p><label>auth token <input name='token' type='password'>"
            f"</label> {self._csrf_field()}<button>deploy</button></p></form>"
        )
        self._send(_page("scenario designer", body))

    def _scenario(self, name: str) -> None:
        safe = self._safe_child(name)
        if safe is None or not safe.is_dir():
            return self._send(_page("not found", "<p>404</p>"), code=404)
        statuses = read_statuses(safe / "status")
        alerts, _ = evaluate_dir(safe, engine=HealthEngine())
        inner = render_alerts_html(alerts) + render_table_html(
            statuses, alerts=alerts
        ) + critpath_pane(statuses) + devprof_pane(statuses)
        logs = sorted((safe / "logs").glob("*.log")) if (
            safe / "logs").is_dir() else []
        links = " | ".join(
            f"<a href='/logs/{html.escape(name)}/{p.name}'>{p.name}</a>"
            for p in logs
        )
        body = (
            inner
            + f"<p><a href='/charts/{html.escape(name)}'>charts</a>"
            + f" | <a href='/api/metrics/{html.escape(name)}'>metrics</a>"
            + f" | <a href='/api/download/{html.escape(name)}'>download zip</a>"
            + (f" | logs: {links}" if links else "")
            + "</p>"
        )
        if (safe / "topology.png").is_file():
            body += (
                f"<p><img src='/topology/{html.escape(name)}' "
                "alt='topology' style='max-width:480px'></p>"
            )
        body += self._geo_map(safe, name)
        self._send(_page(f"scenario {html.escape(name)}", body, refresh=2))

    def _geo_map(self, safe: pathlib.Path, name: str) -> str:
        """Inline SVG geo map of the federation (the reference's
        monitoring map, monitoring.html + topologymanager.py:151-173):
        nodes at their lat/lon, edges as lines."""
        path = safe / "topology_3d.json"
        if not path.is_file():
            return ""
        try:
            topo = json.loads(path.read_text())
            nodes = topo.get("nodes", [])
            if not nodes or "lat" not in nodes[0]:
                return ""
            lats = [n["lat"] for n in nodes]
            lons = [n["lon"] for n in nodes]
            la0, la1 = min(lats), max(lats)
            lo0, lo1 = min(lons), max(lons)
            w, h, pad = 420, 260, 20

            def xy(node):
                x = pad + (node["lon"] - lo0) / max(lo1 - lo0, 1e-9) * (w - 2 * pad)
                y = h - pad - (node["lat"] - la0) / max(la1 - la0, 1e-9) * (h - 2 * pad)
                return round(x, 1), round(y, 1)

            pts = [xy(n) for n in nodes]
            lines = "".join(
                f"<line x1='{pts[i][0]}' y1='{pts[i][1]}' "
                f"x2='{pts[j][0]}' y2='{pts[j][1]}' stroke='#345'/>"
                for i, j in topo.get("edges", [])
            )
            dots = "".join(
                f"<circle cx='{x}' cy='{y}' r='4' fill='#7cf'>"
                f"<title>node {n['id']} ({n['lat']}, {n['lon']})</title>"
                f"</circle>"
                for (x, y), n in zip(pts, nodes)
            )
            return (
                f"<p>geo map (<a href='/api/topology3d/{html.escape(name)}'>"
                f"3-D json</a>):</p><svg width='{w}' height='{h}' "
                f"style='background:#181c20'>{lines}{dots}</svg>"
            )
        except Exception:
            return ""

    def _download(self, name: str) -> None:
        """Zip the scenario's artifacts for offline analysis (the
        metrics-zip download, webserver/app.py:586-594). Streams from
        an in-memory archive of metrics/statuses/config/topology —
        logs excluded (they can be huge; the log viewer tails them)."""
        import io
        import zipfile

        safe = self._safe_child(name)
        if safe is None or not safe.is_dir():
            return self._send(_page("not found", "<p>404</p>"), code=404)
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for rel in ("metrics.jsonl", "metrics.csv", "scenario.json",
                        "topology.png", "topology_3d.json"):
                p = safe / rel
                if p.is_file():
                    z.write(p, f"{name}/{rel}")
            status_dir = safe / "status"
            if status_dir.is_dir():
                for p in sorted(status_dir.glob("*.json")):
                    z.write(p, f"{name}/status/{p.name}")
        body = buf.getvalue()
        self.send_response(200)
        self.send_header("Content-Type", "application/zip")
        # fixed filename: a header built from the (request-supplied)
        # scenario name would be a response-splitting vector
        self.send_header("Content-Disposition",
                         'attachment; filename="metrics.zip"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _logfile(self, name: str, fname: str) -> None:
        path = self._safe_child(name, "logs", fname)
        if path is None or not path.is_file():
            return self._send(_page("not found", "<p>404</p>"), code=404)
        # bounded tail with escaping (the reference's ANSI->HTML log
        # viewer, webserver/app.py:443-500; our logs carry no ANSI codes)
        tail = "\n".join(_tail_text(path).splitlines()[-500:])
        self._send(
            _page(f"{html.escape(fname)}",
                  f"<pre>{html.escape(tail)}</pre>", refresh=5)
        )


def make_server(log_root: str | pathlib.Path, port: int = 8666,
                host: str = "127.0.0.1",
                token: str | None = None,
                users=None) -> ThreadingHTTPServer:
    """``token`` enables the write routes (deploy/stop/remove/reload)
    for API clients; ``users`` (a ``UserStore`` or a path to one)
    enables browser login/session auth; with neither, the dashboard is
    read-only."""
    from p2pfl_tpu.users import UserStore

    root = pathlib.Path(log_root)
    root.mkdir(parents=True, exist_ok=True)
    if users is not None and not isinstance(users, UserStore):
        users = UserStore(users)
    handler = type(
        "BoundHandler", (DashboardHandler,),
        {"root": root, "token": token, "deployments": Deployments(),
         "users": users, "sessions": Sessions()},
    )
    return ThreadingHTTPServer((host, port), handler)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.webapp")
    ap.add_argument("log_root", help="the scenarios' log_dir root")
    ap.add_argument("--port", type=int, default=8666)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--token", default=None,
                    help="shared auth token for the write routes; "
                         "omitted = a fresh one is minted and printed")
    ap.add_argument("--users", default=None, metavar="USERS_JSON",
                    help="user store enabling browser login/session auth")
    ap.add_argument("--add-user", default=None, metavar="NAME",
                    help="with --users: add/update this user in the "
                         "store (prompts for the password) and exit")
    ap.add_argument("--password", default=None,
                    help="password for --add-user (omitted = prompt)")
    ap.add_argument("--role", default=None, choices=["user", "admin"],
                    help="role for --add-user; omitted = keep the "
                         "existing user's role (new users get 'user')")
    ap.add_argument("--read-only", action="store_true",
                    help="disable the write routes entirely")
    args = ap.parse_args(argv)

    if args.add_user:
        from p2pfl_tpu.users import UserStore

        if not args.users:
            ap.error("--add-user requires --users")
        password = args.password
        if password is None:
            import getpass

            password = getpass.getpass(f"password for {args.add_user}: ")
        store = UserStore(args.users)
        store.add(args.add_user, password, args.role)
        effective = store.list().get(args.add_user)
        print(f"user {args.add_user!r} ({effective}) saved to {args.users}")
        return 0

    token = None if args.read_only else (args.token or secrets.token_urlsafe(24))
    server = make_server(args.log_root, args.port, args.host, token=token,
                         users=None if args.read_only else args.users)
    print(f"dashboard on http://{args.host}:{server.server_address[1]}/")
    if token is not None and not args.token:
        print(f"write-route auth token: {token}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
