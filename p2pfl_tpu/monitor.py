"""``python -m p2pfl_tpu.monitor <status-dir>`` — live federation view.

The terminal/HTML successor of the reference's Flask monitoring page
(webserver/app.py:291-364). Point it at a running scenario's status
directory (``<log_dir>/<name>/status``).
"""

from __future__ import annotations

import argparse
import sys

from p2pfl_tpu.utils.monitor import DEFAULT_LIVENESS_S, watch


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.monitor")
    ap.add_argument("status_dir", help="scenario status directory")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--html", default=None,
                    help="also write a self-refreshing dashboard page here")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    ap.add_argument("--liveness", type=float, default=DEFAULT_LIVENESS_S,
                    help="seconds before a silent node renders as DEAD")
    args = ap.parse_args(argv)
    try:
        watch(args.status_dir, interval_s=args.interval, html_out=args.html,
              once=args.once, liveness_s=args.liveness)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
