"""NodeLearner contract + JaxLearner.

``NodeLearner`` reproduces the reference's template interface
(fedstellar/learning/learner.py:24-177: set_model/set_data/
encode_parameters/decode_parameters/check_parameters/set_parameters/
get_parameters/set_epochs/fit/interrupt_fit/evaluate/get_num_samples/
init/close/finalize_round/create_trainer) so the federation layer is
decoupled from the ML stack exactly as in the reference.

``JaxLearner`` is the TPU instance (the reference's is
lightninglearner.py on PyTorch Lightning). Everything hot is built as
**pure jittable functions** (`make_step_fns`) over an explicit
``TrainState`` pytree; the class is a thin host-side shell. That split
is what lets the federation run N learners as one vmapped/shard_mapped
XLA program instead of N Lightning Trainers in N processes.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct

from p2pfl_tpu.ops import pallas_gemm

from p2pfl_tpu.core.serialize import (
    check_parameters,
    decode_parameters,
    encode_parameters,
)
from p2pfl_tpu.learning.objectives import (
    NO_ACCURACY_OBJECTIVES,
    get_objective,
    masked_accuracy,
    ocsvm_penalty,
)
from p2pfl_tpu.obs import devprof
from p2pfl_tpu.obs.trace import get_tracer


class TrainState(struct.PyTreeNode):
    """Carry for one node's training: params + opt state + rng + step."""

    params: Any
    opt_state: Any
    rng: jax.Array
    step: jnp.int32


def make_optimizer(name: str = "sgd", learning_rate: float = 0.1,
                   momentum: float = 0.9, weight_decay: float = 0.0,
                   momentum_dtype: str | None = None):
    """Optimizer factory (TrainingConfig.optimizer).

    ``momentum_dtype="bf16"`` stores the SGD momentum accumulator in
    bfloat16: each training step streams every node's full optimizer
    state through HBM (docs/perf.md §2 regime 1), so halving the
    accumulator bytes buys measured round time (~5% on the north-star
    config) for a tiny, SGD-tolerated precision loss. f32 default.
    """
    name = name.lower()
    if momentum_dtype in (None, "f32", "float32"):
        acc_dt = None
    elif momentum_dtype in ("bf16", "bfloat16"):
        acc_dt = jnp.bfloat16
    else:
        # an unrecognized value silently training in f32 would record
        # an optimization that never ran (bench config JSON carries
        # the string) — reject loudly instead
        raise ValueError(
            f"momentum_dtype must be None/'f32'/'bf16', got "
            f"{momentum_dtype!r}"
        )
    if name == "sgd":
        tx = optax.sgd(learning_rate, momentum=momentum,
                       accumulator_dtype=acc_dt)
    elif name == "adam":
        tx = optax.adam(learning_rate, mu_dtype=acc_dt)
    elif name == "adamw":
        tx = optax.adamw(learning_rate, weight_decay=weight_decay,
                         mu_dtype=acc_dt)
        return tx
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


@dataclasses.dataclass(frozen=True)
class StepFns:
    """The pure-function core of a learner — safe to vmap/shard_map.

    The ``prepare_epoch``/``forward``/``backward``/``apply_update``
    quartet is the SAME step split into its phases (obs.devprof's
    step-profiling pipeline): ``forward`` returns the ``jax.vjp``
    residual closure so ``backward`` is the true cotangent pass —
    no forward recompute inflating either span."""

    init: Callable  # (rng, sample_x) -> TrainState
    train_epochs: Callable  # (state, x, y, mask, epochs, gate=None)
    # -> (state, metrics); gate: per-node 1.0/0.0 update scale
    evaluate: Callable  # (params, x, y, mask) -> metrics dict
    tx: Any
    # devprof phase split (None on hand-built StepFns that predate it)
    prepare_epoch: Callable | None = None  # (state, x, y, mask)
    # -> (rng', (bx, by, bm))
    forward: Callable | None = None  # (params, bx, by, bm) -> (loss, vjp)
    backward: Callable | None = None  # (vjp) -> grads
    apply_update: Callable | None = None  # (state, grads, gate=None)


def make_step_fns(
    model,
    objective: str = "classification",
    optimizer: str = "sgd",
    learning_rate: float = 0.1,
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    momentum_dtype: str | None = None,
    batch_size: int = 32,
    eval_batch_size: int = 512,
) -> StepFns:
    """Build jit-able init / train / eval for a flax model.

    Training an epoch is one ``lax.scan`` over batches: a fresh
    permutation of the shard each epoch, fixed batch count (drop
    remainder — the reference's DataLoader default), masked loss so
    padded rows are inert. Epochs themselves are an outer ``lax.scan``,
    so "fit(E epochs)" is a single XLA program — the moral opposite of
    the reference building a fresh Lightning Trainer per round
    (lightninglearner.py:167-193).
    """
    loss_fn = get_objective(objective)
    # decay applied to the explicit gradient below, NOT via an
    # add_decayed_weights chain: the chain turns zero (gated-off)
    # grads back into wd*params inside tx.update, silently feeding
    # momentum on frozen nodes. adamw keeps its decoupled decay — its
    # decay rides the updates, which the gate also zeroes.
    explicit_decay = weight_decay if optimizer.lower() != "adamw" else 0.0
    tx = make_optimizer(
        optimizer, learning_rate, momentum,
        weight_decay if optimizer.lower() == "adamw" else 0.0,
        momentum_dtype=momentum_dtype,
    )
    # plain SGD's update is a pure elementwise stream the Pallas
    # sgd_accum kernel can fuse — one pass over params/trace/grads per
    # step instead of optax's per-transform tree traversals. Only the
    # exact optax.sgd chain (trace + scale_by_learning_rate; decay is
    # already folded into explicit grads above) is replicated, so
    # anything else keeps tx.update untouched.
    fuse_sgd = optimizer.lower() == "sgd"

    def _fused_sgd_step(st, grads, gate, on):
        """Route SGD leaves the measured gate picks through the fused
        Pallas stream. Returns None whenever the fusion does not apply
        — unexpected optax state shape, or no leaf picked pallas
        (always the case off-TPU, where the gate forces xla) — so the
        caller falls back to the bit-identical ``tx.update`` path."""
        opt_state = st.opt_state
        if not (isinstance(opt_state, (tuple, list)) and len(opt_state) == 2
                and hasattr(opt_state[0], "trace")
                and hasattr(opt_state[0], "_replace")):
            return None
        plan = jax.tree.map(
            lambda p: pallas_gemm.choose(
                "sgd_accum",
                ((math.prod(p.shape[:-1]) if p.ndim > 1 else 1,
                  p.shape[-1] if p.ndim else 1),) * 2,
                p.dtype,
            ) == "pallas",
            st.params,
        )
        if not any(jax.tree.leaves(plan)):
            return None
        # the federation gate folds into the learning rate: a
        # gated-off node's update is exactly +/-0.0, keeping params
        # bit-exact while momentum decays — the ``where`` semantics
        # below without a second tree pass
        lr_eff = learning_rate if gate is None else learning_rate * gate

        def leaf(p, m, g, use_pallas):
            if use_pallas:
                return pallas_gemm.sgd_accum(p, m, g, lr_eff,
                                             momentum=momentum)
            # leaves the gate left on XLA replicate optax.sgd term by
            # term: f32 trace update, uncast update scaled by -lr,
            # stored trace cast to the accumulator dtype
            m_new = g + momentum * m
            u = m_new * -learning_rate
            if on is not None:
                u = jnp.where(on, u, jnp.zeros_like(u))
            return (p + u).astype(p.dtype), m_new.astype(m.dtype)

        out = jax.tree.map(leaf, st.params, opt_state[0].trace, grads, plan)
        params, new_trace = jax.tree.transpose(
            jax.tree.structure(st.params), jax.tree.structure((0, 0)), out)
        return params, (opt_state[0]._replace(trace=new_trace), opt_state[1])

    def init(rng, sample_x) -> TrainState:
        params = model.init(rng, sample_x)
        return TrainState(
            params=params,
            opt_state=tx.init(params),
            rng=jax.random.fold_in(rng, 1),
            step=jnp.int32(0),
        )

    def batch_loss(params, bx, by, bmask):
        out = model.apply(params, bx)
        if objective == "autoencoder":
            return loss_fn(out, bx, bmask)
        if objective == "ocsvm":
            return loss_fn(out, by, bmask) + ocsvm_penalty(params)
        return loss_fn(out, by, bmask)

    def _shuffle(x, perm):
        """Per-epoch reshuffle of the shard. TPU row-gathers of small
        rows serialize badly (~27 ms/epoch for the 64-node north-star
        workload); a one-hot matmul does the same permutation on the
        MXU at memory speed (~4 ms measured). Exact for float inputs:
        each output row is 1.0 * one source row, and f32*1.0 followed
        by a sum of zeros is bit-exact. Integer/bool inputs (labels,
        masks, token ids) keep the gather — their rows are tiny.

        Precondition: finite inputs. 0.0 * Inf/NaN is NaN, so one
        non-finite sample row would poison its column in EVERY output
        row, where the gather kept corruption local to one sample. The
        dataset loaders normalize real files to finite pixel ranges;
        the exactness claim and this containment boundary are pinned
        by tests/test_learner_shuffle.py."""
        # one-hot is O(s^2) in shard size — a federated shard (<=4k
        # rows) wins big, but a single-node learner training a whole
        # 20k-row dataset would materialize a [20k,20k] matrix; the
        # gather is the right tool there
        if (not jnp.issubdtype(x.dtype, jnp.floating) or x.ndim < 2
                or x.shape[0] > 4096):
            return x[perm]
        oh = jax.nn.one_hot(perm, x.shape[0], dtype=x.dtype)
        flat = x.reshape(x.shape[0], -1)
        # HIGHEST precision: TPU matmuls default to bf16-truncated
        # inputs, which would silently round every pixel each epoch;
        # full-precision passes keep the claim above true at a cost
        # that is still far below the row-gather being replaced
        out = jax.lax.dot(oh, flat, precision=jax.lax.Precision.HIGHEST)
        return out.reshape((perm.shape[0],) + x.shape[1:])

    def apply_update(st: TrainState, grads, gate=None) -> TrainState:
        """The optimizer-update phase of one step: explicit decay,
        gating, fused-SGD routing, optax fallback — everything after
        the gradient. ``train_one_epoch``'s scan body calls this, and
        obs.devprof jits it standalone as the ``devprof.update`` span,
        so the profiled pipeline applies the production update."""
        if explicit_decay:
            grads = jax.tree.map(
                lambda g, p: g + explicit_decay * p, grads, st.params)
        on = None
        if gate is not None:
            # zero grads AND updates instead of where-selecting whole
            # trees afterward: params stay bit-exact for gated-off
            # nodes (x + 0 == x) without an extra full-tree memory
            # pass, and no real gradient leaks into momentum.
            # ``where``, not ``* gate``: 0.0 * NaN is NaN, and a
            # gated-off node whose shard produces a non-finite grad
            # must stay frozen, not poisoned
            on = gate > 0
            grads = jax.tree.map(
                lambda g: jnp.where(on, g, jnp.zeros_like(g)), grads)
        fused = (_fused_sgd_step(st, grads, gate, on)
                 if fuse_sgd else None)
        if fused is not None:
            params, opt_state = fused
        else:
            updates, opt_state = tx.update(grads, st.opt_state, st.params)
            if gate is not None:
                updates = jax.tree.map(
                    lambda u: jnp.where(on, u, jnp.zeros_like(u)),
                    updates)
            params = optax.apply_updates(st.params, updates)
        return st.replace(params=params, opt_state=opt_state,
                          step=st.step + 1)

    def prepare_epoch(state: TrainState, x, y, mask):
        """The data/host-gather phase: fresh permutation + batch
        layout for one epoch. ``train_one_epoch`` runs it inline;
        devprof jits it standalone as the ``devprof.data`` span."""
        s = x.shape[0]
        bsz = min(batch_size, s)  # shards smaller than a batch still train
        steps = s // bsz
        used = steps * bsz
        rng, perm_rng = jax.random.split(state.rng)
        perm = jax.random.permutation(perm_rng, s)[:used]
        bx = _shuffle(x, perm).reshape((steps, bsz) + x.shape[1:])
        by = y[perm].reshape(steps, bsz)
        bm = mask[perm].reshape(steps, bsz)
        return rng, (bx, by, bm)

    def forward(params, bx, by, bm):
        """devprof forward phase: the primal pass, returning the vjp
        residual closure (a jit-able Partial pytree) so the backward
        phase is measured without recomputing the forward."""
        return jax.vjp(lambda p: batch_loss(p, bx, by, bm), params)

    def backward(vjp_fn, loss):
        """devprof backward phase: the cotangent pass alone. ``loss``
        rides along only to shape/dtype the seed cotangent."""
        (grads,) = vjp_fn(jnp.ones_like(loss))
        return grads

    def train_one_epoch(state: TrainState, xym, gate):
        x, y, mask = xym
        rng, (bx, by, bm) = prepare_epoch(state, x, y, mask)
        steps = bx.shape[0]

        def step(carry, batch):
            st, loss_sum = carry
            xb, yb, mb = batch
            loss, grads = jax.value_and_grad(batch_loss)(st.params, xb, yb, mb)
            st = apply_update(st, grads, gate)
            return (st, loss_sum + loss), None

        (state, loss_sum), _ = jax.lax.scan(step, (state, 0.0), (bx, by, bm))
        state = state.replace(rng=rng)
        return state, loss_sum / steps

    def train_epochs(state: TrainState, x, y, mask, epochs: int, gate=None):
        """``gate`` (optional f32 scalar, 1.0/0.0) scales every SGD
        update — the federated layer's trains∧alive selection folded
        into the step so frozen nodes cost no extra tree traffic.
        Gated-off nodes keep params exactly; their momentum decays,
        matching the reference's per-round optimizer reset
        (lightninglearner.py:167-193 builds a fresh Trainer per fit)."""

        def body(st, _):
            st, loss = train_one_epoch(st, (x, y, mask), gate)
            return st, loss

        state, losses = jax.lax.scan(body, state, None, length=epochs)
        return state, {"loss": losses[-1], "loss_per_epoch": losses}

    def evaluate(params, x, y, mask):
        """Batched eval via scan (bounds device memory on big test sets)."""
        s = x.shape[0]
        bsz = min(eval_batch_size, s)
        steps = (s + bsz - 1) // bsz
        pad = steps * bsz - s
        xp = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
        yp = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
        mp = jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)])
        bx = xp.reshape((steps, bsz) + x.shape[1:])
        by = yp.reshape(steps, bsz)
        bm = mp.reshape(steps, bsz)

        def step(carry, batch):
            loss_sum, correct_sum, count = carry
            xb, yb, mb = batch
            out = model.apply(params, xb)
            w = mb.astype(jnp.float32)
            cnt = jnp.sum(w)
            if objective == "autoencoder":
                loss = loss_fn(out, xb, mb)
            elif objective == "ocsvm":
                loss = loss_fn(out, yb, mb) + ocsvm_penalty(params)
            else:
                loss = loss_fn(out, yb, mb)
            if objective in NO_ACCURACY_OBJECTIVES:
                acc = jnp.float32(0.0)  # outputs aren't class logits
            else:
                acc = masked_accuracy(out, yb, mb)
            return (loss_sum + loss * cnt, correct_sum + acc * cnt,
                    count + cnt), None

        (loss_sum, correct_sum, count), _ = jax.lax.scan(
            step, (jnp.float32(0), jnp.float32(0), jnp.float32(0)), (bx, by, bm)
        )
        count = jnp.maximum(count, 1.0)
        return {"loss": loss_sum / count, "accuracy": correct_sum / count}

    return StepFns(init=init, train_epochs=train_epochs, evaluate=evaluate,
                   tx=tx, prepare_epoch=prepare_epoch, forward=forward,
                   backward=backward, apply_update=apply_update)


class NodeLearner:
    """The learner template (learner.py:24-177 parity). Methods raise
    until a concrete learner implements them."""

    def set_model(self, model) -> None: raise NotImplementedError
    def set_data(self, data) -> None: raise NotImplementedError
    def encode_parameters(self, params=None, contributors=None, weight=1) -> bytes:
        raise NotImplementedError
    def decode_parameters(self, data: bytes): raise NotImplementedError
    def check_parameters(self, params) -> bool: raise NotImplementedError
    def set_parameters(self, params) -> None: raise NotImplementedError
    def get_parameters(self): raise NotImplementedError
    def set_epochs(self, epochs: int) -> None: raise NotImplementedError
    def create_trainer(self) -> None: raise NotImplementedError
    def fit(self) -> None: raise NotImplementedError
    def interrupt_fit(self) -> None: raise NotImplementedError
    def evaluate(self): raise NotImplementedError
    def get_num_samples(self) -> tuple[int, int]: raise NotImplementedError
    def init(self) -> None: raise NotImplementedError
    def close(self) -> None: raise NotImplementedError
    def finalize_round(self) -> None: raise NotImplementedError


class SharedTrainer:
    """One compiled trainer shared by many same-config learners.

    An in-process simulation runs N ``JaxLearner``s whose models are
    identical; letting each build its own ``make_step_fns`` closures
    would compile N copies of the same XLA program (jit caches key on
    the function object). Build one of these and pass it to every
    ``JaxLearner(trainer=...)`` — one compile serves the federation.
    """

    def __init__(self, model, objective="classification", optimizer="sgd",
                 learning_rate=0.1, momentum=0.9, weight_decay=0.0,
                 momentum_dtype=None, batch_size=32):
        self.fns = make_step_fns(
            model, objective=objective, optimizer=optimizer,
            learning_rate=learning_rate, momentum=momentum,
            weight_decay=weight_decay, momentum_dtype=momentum_dtype,
            batch_size=batch_size,
        )
        self.train_jit = jax.jit(self.fns.train_epochs,
                                 static_argnames=("epochs",))
        self.eval_jit = jax.jit(self.fns.evaluate)
        self.init_jit = jax.jit(self.fns.init)


class JaxLearner(NodeLearner):
    """Single-node JAX learner (lightninglearner.py parity).

    Used standalone for one node on one device; federations instead
    vmap the same ``StepFns`` (see p2pfl_tpu.parallel.federated). Keeps
    the reference's FL-aware step bookkeeping: ``global_step`` grows by
    the number of local steps each round
    (lightninglearner.py:162-165 / statisticslogger.py:131-153).
    """

    def __init__(self, model=None, data=None, objective="classification",
                 optimizer="sgd", learning_rate=0.1, momentum=0.9,
                 weight_decay=0.0, momentum_dtype=None, batch_size=32,
                 seed=0, logger=None,
                 trainer: SharedTrainer | None = None):
        self.model = model
        self.data = data
        self.objective = objective
        self.optimizer_name = optimizer
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.momentum_dtype = momentum_dtype
        self.batch_size = batch_size
        self.seed = seed
        self.logger = logger
        self.epochs = 1
        self.state: TrainState | None = None
        self.fns: StepFns | None = None
        self._shared = trainer
        self.global_step = 0
        self.local_step = 0
        self.round = 0
        self._interrupted = False
        # last fit's devprof_* gauges (MFU/TFLOPs/HBM) for the status
        # publisher; empty until a fit completes with devprof enabled
        self.devprof_last: dict = {}

    # -- wiring ----------------------------------------------------------
    def set_model(self, model) -> None:
        self.model = model
        self.fns = None

    def set_data(self, data) -> None:
        self.data = data

    def create_trainer(self) -> None:
        """Build + jit the step functions (Trainer-construction analog).
        With a ``SharedTrainer`` the compiled programs are reused."""
        if self._shared is not None:
            self.fns = self._shared.fns
            self._train_jit = self._shared.train_jit
            self._eval_jit = self._shared.eval_jit
            self._init_jit = self._shared.init_jit
            return
        self.fns = make_step_fns(
            self.model, objective=self.objective,
            optimizer=self.optimizer_name, learning_rate=self.learning_rate,
            momentum=self.momentum, weight_decay=self.weight_decay,
            momentum_dtype=self.momentum_dtype,
            batch_size=self.batch_size,
        )
        self._train_jit = jax.jit(self.fns.train_epochs,
                                  static_argnames=("epochs",))
        self._eval_jit = jax.jit(self.fns.evaluate)
        self._init_jit = jax.jit(self.fns.init)

    def init(self) -> None:
        if self.fns is None:
            self.create_trainer()
        rng = jax.random.PRNGKey(self.seed)
        sample = jnp.asarray(self.data.x[:1])
        self.state = self._init_jit(rng, sample)

    # -- parameters ------------------------------------------------------
    def get_parameters(self):
        return self.state.params

    def set_parameters(self, params) -> None:
        check_parameters(params, self.state.params)
        params = jax.tree.map(
            lambda new, old: jnp.asarray(new, old.dtype), params,
            self.state.params,
        )
        self.state = self.state.replace(params=params)

    def check_parameters(self, params) -> bool:
        try:
            check_parameters(params, self.state.params)
            return True
        except Exception:
            return False

    def encode_parameters(self, params=None, contributors=None, weight=1) -> bytes:
        if params is None:
            params = self.get_parameters()
        return encode_parameters(params, tuple(contributors or ()), weight)

    def decode_parameters(self, data: bytes):
        return decode_parameters(data)

    # -- training --------------------------------------------------------
    def set_epochs(self, epochs: int) -> None:
        self.epochs = epochs

    def _fit_args(self):
        """fit()'s device-call arguments — one definition shared with
        warm_up() so the warmed shapes are exactly the ones fit hits."""
        x = jnp.asarray(self.data.x)
        y = jnp.asarray(self.data.y)
        return x, y, jnp.ones(len(self.data.x), bool)

    def _eval_args(self):
        """evaluate()'s device-call arguments (val split when present)."""
        x = jnp.asarray(
            self.data.x_val if len(self.data.x_val) else self.data.x
        )
        y = jnp.asarray(
            self.data.y_val if len(self.data.x_val) else self.data.y
        )
        return x, y, jnp.ones(len(x), bool)

    def fit(self) -> None:
        if self.epochs <= 0:
            return
        if self._interrupted:  # honor a pending interrupt_fit()
            self._interrupted = False
            return
        with get_tracer().span("learner.fit",
                               args={"round": self.round,
                                     "epochs": self.epochs}):
            self._fit_traced()
        # gauges AFTER the span closes: the once-per-shape FLOP probe
        # compiles a program, and that compile must not bill itself to
        # learner.fit (the devprof phase-sum gate checks against it)
        if devprof.enabled() and getattr(self, "_devprof_wall", 0):
            self.devprof_last = devprof.fit_gauges(
                self, self._devprof_wall, self._devprof_epochs)

    def _fit_traced(self) -> None:
        x, y, mask = self._fit_args()
        t0 = time.monotonic()
        self._devprof_wall = 0.0  # stays 0 on an interrupted fit
        # step-level devprof swaps in the phase-split pipeline (separate
        # jitted phase programs, each drained inside its span); the
        # default path runs the fused production program untouched
        step_prof = (devprof.step_enabled()
                     and self.fns.prepare_epoch is not None)

        def one_epoch():
            if step_prof:
                return devprof.profiled_epoch(self, x, y, mask)
            return self._train_jit(self.state, x, y, mask, epochs=1)

        if self.epochs == 1:
            self.state, metrics = one_epoch()
            epochs_run = 1
        else:
            # multi-epoch fits run one compiled epoch at a time so
            # interrupt_fit() takes effect at the next epoch boundary
            # (the reference stops its Trainer mid-epoch via
            # trainer.should_stop, lightninglearner.py:122-125; a
            # jitted epoch is one device program and cannot be cut,
            # but a 10-epoch fit must not be uninterruptible)
            metrics = None
            epochs_run = 0
            for _ in range(self.epochs):
                if self._interrupted:
                    self._interrupted = False
                    break
                self.state, metrics = one_epoch()
                epochs_run += 1
            if metrics is None:
                return
        if devprof.enabled():
            # drain before reading the clock: the fused epoch program
            # dispatches async, so an un-synced wall would time the
            # enqueue, not the step, and the MFU gauge would report
            # dispatch rate (a warm fit "measures" sub-millisecond)
            jax.block_until_ready(self.state)
        self._devprof_wall = time.monotonic() - t0
        self._devprof_epochs = epochs_run
        steps = max(len(self.data.x) // self.batch_size, 1) * epochs_run
        self.local_step = steps
        if self.logger is not None:
            self.logger.log_metrics(
                {"Train/loss": float(metrics["loss"]),
                 "Train/epoch_time_s": (time.monotonic() - t0) / epochs_run},
                step=self.global_step + steps, round=self.round,
            )

    def warm_up(self) -> None:
        """Populate the jit cache for fit's and evaluate's programs at
        THIS learner's data shapes — callers measuring steady-state
        rounds warm before starting the clock. AOT lower+compile: no
        device execution is queued (a real warm epoch would still be
        draining when the caller starts its timer), and the argument
        construction is the same `_fit_args`/`_eval_args` the live
        calls use (fit always dispatches epochs=1 programs —
        multi-epoch fits loop them)."""
        if self.fns is None:
            self.create_trainer()
        if self.state is None:
            self.init()

        def avals(args):
            # .lower() needs only shapes/dtypes — materializing every
            # node's whole shard on device just to read its aval would
            # double the federation's host->device traffic
            return tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args
            )

        self._train_jit.lower(self.state, *avals(self._fit_args()),
                              epochs=1).compile()
        self._eval_jit.lower(self.state.params,
                             *avals(self._eval_args())).compile()

    def interrupt_fit(self) -> None:
        """Best-effort stop (lightninglearner.py:122-125). A jitted
        epoch is a single device program, so interruption takes effect
        at the next epoch boundary of a multi-epoch fit (or the next
        fit call for single-epoch fits)."""
        self._interrupted = True

    def evaluate(self):
        with get_tracer().span("learner.evaluate",
                               args={"round": self.round}):
            x, y, mask = self._eval_args()
            metrics = self._eval_jit(self.state.params, x, y, mask)
            out = {k: float(v) for k, v in metrics.items()}
        if self.logger is not None:
            self.logger.log_metrics(
                {f"Val/{k}": v for k, v in out.items()},
                step=self.global_step + self.local_step, round=self.round,
            )
        return out

    def get_num_samples(self) -> tuple[int, int]:
        return (self.data.n_samples, len(self.data.x_val))

    # -- lifecycle -------------------------------------------------------
    def finalize_round(self) -> None:
        """Step bookkeeping parity (lightninglearner.py:159-165)."""
        self.global_step += self.local_step
        self.local_step = 0
        self.round += 1

    def close(self) -> None:
        self.state = None
