"""Loss / metric functions, all mask-aware.

Every function takes a boolean ``mask`` over the batch so padded rows
(from ragged federated shards, see datasets/data.py) contribute zero.
The reference's equivalents are the LightningModule ``training_step``s
(e.g. mnist/models/mlp.py:119-129 cross-entropy + MetricCollection);
the one-class SVM objective mirrors sklearn's SGDOneClassSVM used by
syscall/models/svm.py.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import optax


def _mean(values: jnp.ndarray, mask: jnp.ndarray | None) -> jnp.ndarray:
    if mask is None:
        return jnp.mean(values)
    m = mask.astype(values.dtype)
    return jnp.sum(values * m) / jnp.maximum(jnp.sum(m), 1.0)


def cross_entropy_loss(logits, y, mask=None):
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    return _mean(losses, mask)


def mse_loss(pred, x, mask=None):
    per_row = jnp.mean(
        jnp.square(pred - x.reshape(pred.shape)), axis=tuple(range(1, pred.ndim))
    )
    return _mean(per_row, mask)


def ocsvm_loss(scores, _y, mask=None, nu: float = 0.1):
    """Hinge part of the linear ν-one-class-SVM objective.

    With ``scores = w·x − ρ`` (models.syscall.OneClassSVM), the full
    SGDOneClassSVM objective is ``½‖w‖² − ρ + 1/ν · mean(max(0, −s))``;
    this returns the data term — the caller adds :func:`ocsvm_penalty`
    over the params (the learner does so when objective == "ocsvm").
    """
    hinge = jnp.maximum(0.0, -scores)
    return _mean(hinge, mask) / nu


def ocsvm_penalty(params) -> jnp.ndarray:
    """Parameter part of the ν-OCSVM objective: ``½‖w‖² − ρ``."""
    inner = params["params"] if "params" in params else params
    return 0.5 * jnp.sum(jnp.square(inner["w"])) - inner["rho"]


def masked_accuracy(logits, y, mask=None):
    correct = (jnp.argmax(logits, axis=-1) == y).astype(jnp.float32)
    return _mean(correct, mask)


NO_ACCURACY_OBJECTIVES = ("autoencoder", "ocsvm")  # scores aren't class logits

_OBJECTIVES: dict[str, Callable] = {
    "classification": cross_entropy_loss,
    "autoencoder": mse_loss,
    "ocsvm": ocsvm_loss,
}


def get_objective(name: str) -> Callable:
    if name not in _OBJECTIVES:
        raise ValueError(f"unknown objective {name!r}; have {sorted(_OBJECTIVES)}")
    return _OBJECTIVES[name]
