"""Learning layer: the NodeLearner contract and its JAX implementation.

Successor of the reference's L2 (fedstellar/learning/learner.py — the
16-method template every learner satisfies — and
lightninglearner.py, its PyTorch-Lightning instance). Here the learner
is JAX end-to-end: local training is one jit-compiled
``lax.scan`` over batches per epoch, metrics are computed on device,
and parameters are flax pytrees, so a *stack* of learners (one per
federated node) is the same program under ``vmap``/``shard_map``.
"""

from p2pfl_tpu.learning.objectives import (
    cross_entropy_loss,
    masked_accuracy,
    mse_loss,
    ocsvm_loss,
    get_objective,
)
from p2pfl_tpu.learning.learner import JaxLearner, NodeLearner, TrainState
from p2pfl_tpu.learning.lora import (
    LoraModel,
    lora_init,
    maybe_wrap_lora,
    merge_adapters,
    split_adapters,
)

__all__ = [
    "cross_entropy_loss",
    "masked_accuracy",
    "mse_loss",
    "ocsvm_loss",
    "get_objective",
    "JaxLearner",
    "NodeLearner",
    "TrainState",
    "LoraModel",
    "lora_init",
    "maybe_wrap_lora",
    "merge_adapters",
    "split_adapters",
]
