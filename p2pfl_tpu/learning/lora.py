"""Adapter-only federation (LoRA): the unit of federation becomes the
adapter delta instead of the full parameter tree.

The scenario users actually want from a TPU-native FL framework is
collaborative fine-tuning of a pretrained transformer without shipping
full weights (ROADMAP item 3, Gemma-on-TPU in PAPERS.md). The perf
lever is structural, not numeric: once the unit of federation is the
adapter tree, every downstream consumer shrinks by the same orders of
magnitude *without changing*, because each is generic over "params":

- the socket wire envelope (``core.serialize.encode_parameters``), the
  v2 bf16/int8 dtypes and the int8 error-feedback state;
- the SPMD FedAvg contraction (``[n,n] @ [n,d']`` instead of ``[n,n] @
  [n,d]``) and the staged-overlap double buffer;
- the Krum/trimmed-mean flatten — the ``[n,n]`` Gram matmul drops from
  full-model ``d`` to adapter ``d'``;
- reputation cosine scoring (``entry_scales`` over adapter vectors) and
  the attack transforms (a malicious node poisons the adapters it
  ships, exactly as it poisoned full weights);
- checkpoints and the live-join STATE_SYNC payload.

Mechanically this is ONE seam: :class:`LoraModel` duck-types the two
methods ``make_step_fns`` uses (``init(rng, x)`` / ``apply(params,
x)``), returning and consuming an **adapter-only pytree**. The frozen
base is a captured constant of the compiled programs — it never enters
``TrainState``, the optimizer state, the donated ``FederatedState``
buffers, or any wire payload. Per target kernel ``W`` the effective
weight is

    ``W_eff = W + (alpha / rank) * A @ B``

with ``A ~ N(0, 1/d_in)`` and ``B = 0``, so the merged model equals the
base **bit-exactly** at adapter init (``W + 0.0 == W`` for finite
``W``) — the property the cross-plane parity tests anchor on.

Shape handling: a target kernel is viewed as ``lead axes + [d_in axes]
+ [d_out axes]``. ``lead`` (e.g. the ``nn.scan`` depth axis) broadcasts
— each scanned layer gets its own ``A``/``B`` pair via one batched
matmul. The per-target ``(out_axes, base_ndim)`` split is model
metadata registered next to the model factory
(``models.base.register_lora_targets``); anything unregistered falls
back to the plain 2-D view ``(..., d_in, d_out)``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from p2pfl_tpu.models.base import default_lora_targets, lora_axis_specs

# the combined-tree keys ``split_adapters``/``merge_adapters`` pivot on
BASE_KEY = "base"
ADAPTERS_KEY = "adapters"

# joins a tree path into the flat adapter-tree key; "/" cannot appear
# in flax module/param names
_SEP = "/"


@dataclasses.dataclass(frozen=True)
class AdapterSite:
    """One target kernel: where it lives and its factorization view."""

    key: str  # _SEP-joined path, the adapter tree's dict key
    shape: tuple[int, ...]  # full kernel shape
    lead: tuple[int, ...]  # broadcast axes (scan depth, ...)
    d_in: int
    d_out: int


def _path_keys(path) -> tuple[str, ...]:
    return tuple(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def find_adapter_sites(
    params: Any, targets: tuple[str, ...],
    specs: dict[str, tuple[int, int]] | None = None,
) -> tuple[AdapterSite, ...]:
    """Resolve target patterns against a param tree.

    A leaf qualifies when its final path key is ``"kernel"`` and any
    path component contains a target pattern as a substring. Every
    pattern must match at least one kernel — a typo'd target silently
    adapting nothing would report a fine-tune that never ran, so this
    fails loud naming the tree's kernels (the ``check_parameters``
    leaf-naming convention).
    """
    if not targets:
        raise ValueError("lora targets must not be empty")
    specs = specs or {}
    sites: list[AdapterSite] = []
    matched: set[str] = set()
    kernels: list[str] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        keys = _path_keys(path)
        if not keys or keys[-1] != "kernel":
            continue
        key = _SEP.join(keys)
        kernels.append(key)
        hits = [t for t in targets if any(t in k for k in keys[:-1])]
        if not hits:
            continue
        matched.update(hits)
        out_axes, base_ndim = specs.get(hits[0], (1, 2))
        shape = tuple(leaf.shape)
        n_lead = leaf.ndim - base_ndim
        if n_lead < 0 or out_axes >= base_ndim:
            raise ValueError(
                f"lora target {hits[0]!r} spec (out_axes={out_axes}, "
                f"base_ndim={base_ndim}) does not fit kernel {key} "
                f"of shape {shape}"
            )
        lead = shape[:n_lead]
        d_in = math.prod(shape[n_lead:leaf.ndim - out_axes])
        d_out = math.prod(shape[leaf.ndim - out_axes:])
        sites.append(AdapterSite(key=key, shape=shape, lead=lead,
                                 d_in=d_in, d_out=d_out))
    missing = [t for t in targets if t not in matched]
    if missing:
        raise ValueError(
            f"lora targets {missing} match no kernel; tree has "
            f"{kernels}"
        )
    return tuple(sites)


def init_adapters(sites: tuple[AdapterSite, ...], rank: int,
                  rng: jax.Array) -> dict:
    """Fresh A/B leaves per site: ``A ~ N(0, 1/d_in)``, ``B = 0`` — the
    zero-init that makes ``merged == base`` bit-exact at start."""
    if rank < 1:
        raise ValueError(f"lora rank must be >= 1, got {rank}")
    adapters: dict[str, dict[str, jax.Array]] = {}
    for i, site in enumerate(sites):
        a_rng = jax.random.fold_in(rng, i)
        a = jax.random.normal(
            a_rng, site.lead + (site.d_in, rank), jnp.float32
        ) * (1.0 / math.sqrt(site.d_in))
        b = jnp.zeros(site.lead + (rank, site.d_out), jnp.float32)
        adapters[site.key] = {"A": a, "B": b}
    return adapters


def adapter_deltas(adapters: dict, sites: tuple[AdapterSite, ...],
                   rank: int, alpha: float | None) -> dict:
    """``(alpha/rank) * A @ B`` per site, reshaped to the kernel shape.
    The matmul broadcasts over the lead axes, so scanned layers keep
    per-depth adapters in one contraction."""
    scale = (alpha if alpha is not None else float(rank)) / float(rank)
    out = {}
    for site in sites:
        ab = adapters[site.key]
        delta = jnp.matmul(ab["A"], ab["B"]) * jnp.float32(scale)
        out[site.key] = delta.reshape(site.shape)
    return out


def split_adapters(tree: dict) -> tuple[Any, dict]:
    """``{"base": ..., "adapters": ...} -> (base, adapters)`` — the
    pure structural split of one lora tree. Inverse of
    :func:`merge_adapters`; round-trips bit-exactly by construction."""
    try:
        return tree[BASE_KEY], tree[ADAPTERS_KEY]
    except (KeyError, TypeError) as e:
        raise ValueError(
            f"not a lora tree: expected dict with {BASE_KEY!r}/"
            f"{ADAPTERS_KEY!r} keys, got {type(tree).__name__}"
        ) from e


def merge_adapters(base: Any, adapters: dict) -> dict:
    """``(base, adapters) -> {"base": ..., "adapters": ...}`` — the
    inverse of :func:`split_adapters` (no materialization; use
    :meth:`LoraModel.materialize` for the effective full weights)."""
    return {BASE_KEY: base, ADAPTERS_KEY: adapters}


def lora_init(params: Any, rank: int, targets: tuple[str, ...],
              *, alpha: float | None = None,
              rng: jax.Array | None = None,
              specs: dict[str, tuple[int, int]] | None = None) -> dict:
    """Build the frozen-base + adapter split for an existing param tree:
    one combined pytree ``{"base": params, "adapters": {site: {A, B}}}``
    (take it apart with :func:`split_adapters`)."""
    sites = find_adapter_sites(params, tuple(targets), specs)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return merge_adapters(params, init_adapters(sites, rank, rng))


class LoraModel:
    """Adapter-only view of a flax model.

    Duck-types the surface ``make_step_fns`` consumes: ``init`` returns
    the adapter-only pytree (so ``TrainState.params`` and the optimizer
    state are adapter-sized), ``apply`` merges the adapters into the
    closed-over frozen base and delegates. The base is a compile-time
    constant of every jitted program — never donated, vmapped, shipped
    or optimized, and shared by all nodes of a federation (one copy in
    HBM regardless of the node count).
    """

    def __init__(self, model, base: Any, rank: int,
                 targets: tuple[str, ...], alpha: float | None = None,
                 specs: dict[str, tuple[int, int]] | None = None):
        self.inner = model
        self.rank = int(rank)
        self.alpha = alpha
        self.targets = tuple(targets)
        self.base = jax.tree.map(jnp.asarray, base)
        self.sites = find_adapter_sites(self.base, self.targets, specs)
        if self.rank < 1:
            raise ValueError(f"lora rank must be >= 1, got {rank}")

    # -- the make_step_fns surface ------------------------------------
    def init(self, rng, sample_x) -> dict:
        del sample_x  # base already fixes every shape
        return init_adapters(self.sites, self.rank, rng)

    def apply(self, adapters: dict, x):
        return self.inner.apply(self.materialize(adapters), x)

    # -- merge math ----------------------------------------------------
    def materialize(self, adapters: dict) -> Any:
        """Effective full weights: ``base + (alpha/rank) * A @ B`` at
        every site, untouched leaves passed through by reference."""
        deltas = adapter_deltas(adapters, self.sites, self.rank,
                                self.alpha)

        def leaf(path, w):
            d = deltas.get(_SEP.join(_path_keys(path)))
            return w if d is None else (w + d.astype(w.dtype))

        return jax.tree_util.tree_map_with_path(leaf, self.base)

    def adapter_param_count(self) -> int:
        return sum(
            math.prod(s.lead) * self.rank * (s.d_in + s.d_out)
            for s in self.sites
        )


def base_params_for(model, seed: int, sample_x) -> Any:
    """The frozen base every plane derives identically from config:
    ``model.init(PRNGKey(seed), sample)`` — the SAME key the full-weight
    paths use (``init_federation`` with ``same_init`` and
    ``JaxLearner.init``), so a lora federation's merged round-0 model
    equals the full-weight federation's round-0 model bit-exactly.
    Depends only on the sample's shape/dtype, never its values, so
    every node of a socket federation converges on one base."""
    return model.init(jax.random.PRNGKey(seed), jnp.asarray(sample_x))


def wrap_model(model, model_name: str, rank: int, *,
               targets: tuple[str, ...] = (), alpha: float | None = None,
               base: Any = None, seed: int = 0,
               sample_x=None) -> LoraModel:
    """Build a :class:`LoraModel` from registry metadata: empty
    ``targets`` resolve to the model's registered defaults, axis specs
    come from the same registry, and a missing ``base`` is derived
    deterministically via :func:`base_params_for`."""
    targets = tuple(targets) or default_lora_targets(model_name)
    specs = lora_axis_specs(model_name)
    if base is None:
        if sample_x is None:
            raise ValueError("wrap_model needs base= or sample_x=")
        base = base_params_for(model, seed, sample_x)
    return LoraModel(model, base, rank=rank, targets=targets,
                     alpha=alpha, specs=specs)


def maybe_wrap_lora(model, cfg, sample_x):
    """Scenario/launch seam: the model unchanged when ``cfg.lora`` is
    off, else the :class:`LoraModel` every plane must train through.
    Deterministic in ``(cfg.model, cfg.lora, cfg.seed)`` so separate
    node processes derive one identical frozen base."""
    if not cfg.lora.active:
        return model
    return wrap_model(
        model, cfg.model.model, cfg.lora.rank,
        targets=tuple(cfg.lora.targets), alpha=cfg.lora.alpha,
        seed=cfg.seed, sample_x=sample_x,
    )
