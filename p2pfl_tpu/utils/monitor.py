"""Live federation monitoring — the L5 successor.

Reference: every node POSTs its status to the controller each heartbeat
cycle (node.py:916-937); the controller upserts a SQLite ``nodes``
table (webserver/database.py:253-274); the Flask monitoring page
renders a live node table/map with a 20 s liveness cutoff
(webserver/app.py:291-364, :307-311).

Here the transport is the filesystem (no service dependency, works for
in-process scenarios AND multi-process socket federations): each
participant atomically publishes ``node_<idx>.status.json`` into a
status directory; ``python -m p2pfl_tpu.monitor <dir>`` renders a live
terminal table (or ``--html`` writes a self-refreshing dashboard
page). Liveness is record age against the same 20 s default.
"""

from __future__ import annotations

import dataclasses
import html
import json
import pathlib
import sys
import threading
import time
from typing import Any

from p2pfl_tpu.obs.records import make_record
from p2pfl_tpu.utils.fsio import atomic_write_text

DEFAULT_LIVENESS_S = 20.0  # webserver/app.py:307-311 cutoff

# per-(directory, node) monotonic publish sequence: ``ts`` comes from
# each host's wall clock, and cross-host skew can make a stale node
# look fresher than a live one — ``seq`` only ever grows per publisher,
# so readers can order one node's records skew-free
_seq_lock = threading.Lock()
_seq: dict[tuple[str, int], int] = {}


def _next_seq(directory: pathlib.Path, node: int) -> int:
    key = (str(directory), int(node))
    with _seq_lock:
        _seq[key] = _seq.get(key, 0) + 1
        return _seq[key]


def publish_status(directory: str | pathlib.Path, node: int,
                   record: dict[str, Any]) -> pathlib.Path:
    """Atomically publish one node's current status record (the shared
    obs record shape: node + ts + fields, plus the monotonic seq)."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    rec = make_record(int(node), **record)
    rec.setdefault("seq", _next_seq(directory, node))
    path = directory / f"node_{node}.status.json"
    atomic_write_text(path, json.dumps(rec))
    return path


def read_statuses(directory: str | pathlib.Path) -> list[dict[str, Any]]:
    """All published node records, sorted by node index; unreadable
    files (mid-replace on exotic filesystems) are skipped."""
    directory = pathlib.Path(directory)
    out = []
    for path in sorted(directory.glob("node_*.status.json")):
        try:
            out.append(json.loads(path.read_text()))
        except (ValueError, OSError):
            continue
    return sorted(out, key=lambda r: r.get("node", 0))


# The status-record key registry: every key a publisher (p2p/launch.py,
# federation/scenario.py) may emit and a renderer (this module,
# webapp.py) or health rule may read. analysis/statuskeys.py checks the
# three ways against each other — a renamed gauge that would silently
# render "-" forever fails tier-1 instead. node/ts/seq come from
# publish_status itself, not the callers.
STATUS_KEYS = (
    # record envelope (make_record + publish_status)
    "node", "ts", "seq",
    # federation identity / progress
    "role", "round", "peers", "leader", "loss", "accuracy", "trust",
    # round timing + wire traffic
    "round_p95_s", "bytes_in", "bytes_out",
    "peer_bytes_in", "peer_bytes_out", "recompiles",
    # privacy plane
    "dp_epsilon", "dp_epsilon_budget",
    # round-18 critical-path components
    "critpath_round", "critpath_round_s", "critpath_fit_s",
    "critpath_wire_s", "critpath_wait_s", "critpath_agg_s",
    "critpath_other_s",
    # round-20 cross-device throughput
    "crossdev_clients_per_s", "crossdev_prefetch_mb",
    "crossdev_prefetch_stall_s",
    # aggregation sidecar
    "aggd_desc_q_depth", "aggd_slot_releases", "aggd_bytes_ingested",
    # round-22 device profiling (obs.devprof)
    "devprof_fit_s", "devprof_tflops", "devprof_mfu",
    "devprof_hbm_peak_mb", "devprof_hbm_limit_mb", "devprof_rss_peak_mb",
)

_COLUMNS = ("node", "role", "round", "loss", "accuracy", "trust",
            "peers", "p95s", "wait%", "cl/s", "pf", "mfu", "hbm",
            "io_mb", "eps", "age", "health")


def _health_cell(node: int | None, alerts) -> str:
    """Worst active alert for one node as a short cell: ``ok``,
    ``warn(rule)`` or ``crit(rule[+k])``. ``alerts`` is the active
    list from obs.health (duck-typed: .node/.severity/.rule)."""
    mine = [a for a in (alerts or ()) if a.node == node]
    if not mine:
        return "ok"
    crit = [a for a in mine if a.severity == "crit"]
    top = (crit or mine)[0]
    extra = f"+{len(mine) - 1}" if len(mine) > 1 else ""
    return f"{top.severity}({top.rule}{extra})"


def _wait_cell(rec: dict[str, Any]) -> str:
    """WAIT% cell from the critpath_* gauges launch.py publishes: the
    fraction of the last closed round spent blocked on quorum/barrier.
    Falls back to "-" for records predating a closed round (or from
    builds without critical-path accounting)."""
    wait, wall = rec.get("critpath_wait_s"), rec.get("critpath_round_s")
    if wait is None or not wall:
        return "-"
    return f"{100.0 * float(wait) / float(wall):.0f}%"


def _clients_cell(rec: dict[str, Any]) -> str:
    """CL/S cell: the cross-device driver's simulated clients per
    second (``crossdev_clients_per_s``, the HEADLINE throughput) — "-"
    for per-node planes, which have no cohort scan."""
    v = rec.get("crossdev_clients_per_s")
    return "-" if v is None else f"{float(v):.0f}"


def _prefetch_cell(rec: dict[str, Any]) -> str:
    """PF cell: streamed-round host→device prefetch traffic and stall,
    ``<MB>/<stall s>`` — "-" off the streamed path (including plain
    cross-device runs, which materialize cohorts up front)."""
    mb = rec.get("crossdev_prefetch_mb")
    st = rec.get("crossdev_prefetch_stall_s")
    if mb is None and st is None:
        return "-"
    return f"{float(mb or 0):.0f}M/{float(st or 0):.2f}s"


def _mfu_cell(rec: dict[str, Any]) -> str:
    """MFU cell from the devprof gauges: model-FLOP utilization as a
    percentage when the device has a known peak, achieved TFLOP/s when
    it does not (CPU dev boxes), "-" with devprof off."""
    v = rec.get("devprof_mfu")
    if v is not None:
        return f"{float(v) * 100:.1f}%"
    t = rec.get("devprof_tflops")
    return "-" if t is None else f"{float(t):.2f}T"


def _hbm_cell(rec: dict[str, Any]) -> str:
    """HBM cell: device peak-memory high-water, with percent-of-limit
    when the backend publishes one; host RSS peak (``r``-prefixed) as
    the fallback on backends without memory_stats; "-" with devprof
    off."""
    peak = rec.get("devprof_hbm_peak_mb")
    if peak is not None:
        limit = rec.get("devprof_hbm_limit_mb")
        cell = f"{float(peak):.0f}M"
        if limit:
            cell += f"/{100.0 * float(peak) / float(limit):.0f}%"
        return cell
    rss = rec.get("devprof_rss_peak_mb")
    return "-" if rss is None else f"r{float(rss):.0f}M"


def _eps_cell(rec: dict[str, Any]) -> str:
    """EPS cell: running DP spend from the privacy accountant,
    ``<eps>/<budget>`` when a budget is configured, bare ``<eps>``
    otherwise — "-" on non-DP runs."""
    eps = rec.get("dp_epsilon")
    if eps is None:
        return "-"
    budget = rec.get("dp_epsilon_budget")
    if budget:
        return f"{float(eps):.2f}/{float(budget):.2f}"
    return f"{float(eps):.2f}"


def _row(rec: dict[str, Any], now: float, liveness_s: float,
         alerts=None) -> dict[str, str]:
    # clamp: cross-host clock skew can put a record's ts slightly in
    # this reader's future, and a rendered "-0.3s" age reads as a bug.
    # Liveness is unaffected (a negative age was always alive).
    age = max(now - float(rec.get("ts", 0.0)), 0.0)
    alive = age <= liveness_s

    def num(key):
        v = rec.get(key)
        return "-" if v is None else (f"{v:.4f}" if isinstance(v, float) else str(v))

    bi, bo = rec.get("bytes_in"), rec.get("bytes_out")
    p95 = rec.get("round_p95_s")
    return {
        "node": str(rec.get("node", "?")),
        "role": str(rec.get("role", "-")),
        "round": num("round"),
        "loss": num("loss"),
        "accuracy": num("accuracy"),
        # reputation-weighted runs publish per-node trust (scenario.py /
        # adversary.reputation); "-" on clean runs
        "trust": num("trust"),
        "peers": num("peers"),
        # obs summaries (round-9): p95 round wall time + wire traffic
        # in/out MB — published by launch.py/scenario.py status loops
        "p95s": "-" if p95 is None else f"{float(p95):.2f}",
        # round-18 critical path: share of the last round spent blocked
        # on quorum/barrier (critpath_wait_s / critpath_round_s). "-"
        # until the node closes a round with tracing-era gauges.
        "wait%": _wait_cell(rec),
        # round-20 cross-device throughput plane: clients/s from the
        # cohort-scan driver, prefetch MB/stall from streamed rounds.
        "cl/s": _clients_cell(rec),
        "pf": _prefetch_cell(rec),
        # round-22 device profiling: live utilization + memory
        # watermarks from the devprof_* gauges (P2PFL_DEVPROF)
        "mfu": _mfu_cell(rec),
        "hbm": _hbm_cell(rec),
        "io_mb": (
            "-" if bi is None and bo is None
            else f"{(bi or 0) / 1e6:.1f}/{(bo or 0) / 1e6:.1f}"
        ),
        # privacy plane: running (ε, budget) spend from the DP
        # accountant — feeds the epsilon-budget health rule
        "eps": _eps_cell(rec),
        "age": f"{age:.1f}s" + ("" if alive else " DEAD"),
        # round-12 health plane: worst active alert for this node
        "health": _health_cell(rec.get("node"), alerts),
    }


def render_alerts(alerts) -> str:
    """Plain-text alerts pane: one line per active alert, most severe
    first (the order obs.health.HealthEngine.alerts() returns)."""
    if not alerts:
        return "alerts: none"
    lines = ["alerts:"]
    for a in alerts:
        who = "federation" if a.node is None else f"node {a.node}"
        lines.append(f"  [{a.severity.upper():4s}] {a.rule} {who}: "
                     f"{a.message}")
    return "\n".join(lines)


def render_alerts_html(alerts) -> str:
    if not alerts:
        return "<div class='alerts ok'>alerts: none</div>"
    items = "".join(
        "<li class='{cls}'>[{sev}] {rule} {who}: {msg}</li>".format(
            cls=html.escape(a.severity),
            sev=html.escape(a.severity.upper()),
            rule=html.escape(a.rule),
            who="federation" if a.node is None else f"node {a.node}",
            msg=html.escape(a.message),
        )
        for a in alerts
    )
    return f"<div class='alerts'><ul>{items}</ul></div>"


def render_table(statuses: list[dict[str, Any]], now: float | None = None,
                 liveness_s: float = DEFAULT_LIVENESS_S,
                 alerts=None) -> str:
    """Plain-text node table (the monitoring page's table, app.py:291+)."""
    now = time.time() if now is None else now
    rows = [_row(r, now, liveness_s, alerts=alerts) for r in statuses]
    widths = {
        c: max(len(c), *(len(r[c]) for r in rows)) if rows else len(c)
        for c in _COLUMNS
    }
    header = "  ".join(c.upper().ljust(widths[c]) for c in _COLUMNS)
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append("  ".join(r[c].ljust(widths[c]) for c in _COLUMNS))
    return "\n".join(lines)


def render_table_html(statuses: list[dict[str, Any]],
                      now: float | None = None,
                      liveness_s: float = DEFAULT_LIVENESS_S,
                      alerts=None) -> str:
    """Just the node ``<table>`` — shared by the standalone dashboard
    page below and the webapp's scenario page."""
    now = time.time() if now is None else now
    rows = [_row(r, now, liveness_s, alerts=alerts) for r in statuses]
    body = "".join(
        "<tr class='{cls}'>{cells}</tr>".format(
            cls="dead" if "DEAD" in r["age"] else "alive",
            cells="".join(f"<td>{html.escape(r[c])}</td>" for c in _COLUMNS),
        )
        for r in rows
    )
    head = "".join(f"<th>{c.upper()}</th>" for c in _COLUMNS)
    return f"<table><tr>{head}</tr>{body}</table>"


def render_html(statuses: list[dict[str, Any]], now: float | None = None,
                liveness_s: float = DEFAULT_LIVENESS_S,
                refresh_s: int = 2, alerts=None) -> str:
    """Self-contained dashboard page (auto-refreshes via meta tag —
    re-render it in a loop with --watch for a live view)."""
    now = time.time() if now is None else now
    table = render_table_html(statuses, now, liveness_s, alerts=alerts)
    pane = render_alerts_html(alerts)
    return f"""<!doctype html><html><head>
<meta http-equiv="refresh" content="{refresh_s}">
<title>p2pfl_tpu federation</title>
<style>
body{{font-family:monospace;background:#111;color:#ddd;padding:1em}}
table{{border-collapse:collapse}} td,th{{padding:.3em .8em;border:1px solid #333}}
tr.dead td{{color:#f55}} th{{background:#222}}
.alerts{{margin:.6em 0}} .alerts li.crit{{color:#f55}}
.alerts li.warn{{color:#fb0}} .alerts.ok{{color:#5a5}}
</style></head><body>
<h2>federation status — {time.strftime('%H:%M:%S', time.localtime(now))}</h2>
{pane}
{table}
</body></html>"""


@dataclasses.dataclass
class StatusPublisher:
    """A participant's handle for publishing its status each round /
    heartbeat (the node→controller POST analog, node.py:916-937)."""

    directory: pathlib.Path
    node: int

    def publish(self, **record: Any) -> None:
        publish_status(self.directory, self.node, record)


def watch(directory: str | pathlib.Path, interval_s: float = 1.0,
          html_out: str | None = None, once: bool = False,
          liveness_s: float = DEFAULT_LIVENESS_S) -> None:
    """Render the live table + alerts pane to the terminal (and
    optionally an HTML dashboard file) until interrupted. The health
    engine is persistent across ticks, so the pane reflects firing/
    clear transitions, not per-tick re-detections."""
    # import here: obs.health imports read_statuses from this module
    from p2pfl_tpu.obs.health import HealthConfig, HealthEngine, evaluate_dir

    directory = pathlib.Path(directory)
    engine = HealthEngine(config=HealthConfig(liveness_s=liveness_s))
    while True:
        statuses = read_statuses(directory)
        alerts, _ = evaluate_dir(directory, engine=engine)
        table = render_table(statuses, liveness_s=liveness_s,
                             alerts=alerts)
        pane = render_alerts(alerts)
        if html_out:
            atomic_write_text(
                pathlib.Path(html_out),
                render_html(statuses, liveness_s=liveness_s,
                            alerts=alerts),
            )
        if once:
            print(table + "\n" + pane)
            return
        sys.stdout.write("\x1b[2J\x1b[H" + table + "\n" + pane + "\n")
        sys.stdout.flush()
        time.sleep(interval_s)
