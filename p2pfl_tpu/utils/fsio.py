"""Atomic file publication helpers.

Any artifact another process tails live (status JSON, dashboards,
topology maps, health probes) must never be observable empty or
half-written. The contract — shared with
``federation.checkpoint._atomic_write_bytes`` — is: write a ``tmp``
sibling in the same directory, fsync it, then ``os.replace`` onto the
published name, which POSIX guarantees is atomic within a filesystem.
"""

from __future__ import annotations

import os
import pathlib


def atomic_write_bytes(path: pathlib.Path, data: bytes) -> None:
    path = pathlib.Path(path)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_text(path: pathlib.Path, text: str,
                      encoding: str = "utf-8") -> None:
    atomic_write_bytes(path, text.encode(encoding))
