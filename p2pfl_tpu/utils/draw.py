"""Topology rendering (controller parity: topology.png per scenario,
fedstellar/utils/topologymanager.py:48-109 draw_graph with role colors).
"""

from __future__ import annotations

import pathlib

import numpy as np

from p2pfl_tpu.topology.topology import Topology

_ROLE_COLORS = {
    "trainer": "#6baed6",
    "aggregator": "#74c476",
    "server": "#fd8d3c",
    "proxy": "#9e9ac8",
    "idle": "#bdbdbd",
}


def draw_topology(topology: Topology, path: str | pathlib.Path,
                  roles: list[str] | None = None) -> pathlib.Path | None:
    """Render the federation graph to PNG. Returns None (and is a
    no-op) if matplotlib/networkx are unavailable."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        import networkx as nx
    except Exception:
        return None
    g = nx.from_numpy_array(topology.adjacency.astype(int))
    colors = (
        [_ROLE_COLORS.get(r, "#bdbdbd") for r in roles]
        if roles
        else "#6baed6"
    )
    fig, ax = plt.subplots(figsize=(6, 6))
    pos = nx.circular_layout(g)
    nx.draw_networkx(g, pos=pos, ax=ax, node_color=colors, node_size=600,
                     font_size=8, edge_color="#999999")
    ax.set_title(f"{topology.kind} (n={topology.n})")
    ax.axis("off")
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return path
