"""Host + device resource telemetry.

Parity with the reference's psutil/pynvml loop (node.py:939-997,
logged as ``Resources/*`` each report cycle), with TPU HBM stats from
``jax.local_devices()[...].memory_stats()`` replacing the NVML GPU
query.
"""

from __future__ import annotations

from typing import Any

import jax


def resource_snapshot() -> dict[str, float]:
    """One sample of CPU/RAM/disk/net + per-device HBM usage."""
    out: dict[str, float] = {}
    try:
        import psutil

        out["Resources/cpu_percent"] = psutil.cpu_percent(interval=None)
        vm = psutil.virtual_memory()
        out["Resources/ram_percent"] = vm.percent
        out["Resources/ram_used_gb"] = vm.used / 2**30
        du = psutil.disk_usage("/")
        out["Resources/disk_percent"] = du.percent
        net = psutil.net_io_counters()
        out["Resources/net_sent_mb"] = net.bytes_sent / 2**20
        out["Resources/net_recv_mb"] = net.bytes_recv / 2**20
    except Exception:  # psutil optional — never break a round over telemetry
        pass
    for i, dev in enumerate(jax.local_devices()):
        try:
            stats: dict[str, Any] = dev.memory_stats() or {}
            if "bytes_in_use" in stats:
                out[f"Resources/device{i}_hbm_used_mb"] = (
                    stats["bytes_in_use"] / 2**20
                )
            if "bytes_limit" in stats:
                out[f"Resources/device{i}_hbm_limit_mb"] = (
                    stats["bytes_limit"] / 2**20
                )
        except Exception:
            continue
    return out
