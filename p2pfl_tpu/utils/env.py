"""Environment probe.

Parity with the reference's utils/env.py (logs OS / python / torch /
psutil / GPU info at node start), re-pointed at the TPU stack: OS,
python, jax/jaxlib versions, backend platform, device inventory, and
host memory/CPU.
"""

from __future__ import annotations

import logging
import platform
import sys
from typing import Any

log = logging.getLogger("p2pfl_tpu.env")


def environment_report(include_devices: bool = True) -> dict[str, Any]:
    """Collect the environment facts as one dict (JSON-safe)."""
    report: dict[str, Any] = {
        "os": platform.platform(),
        "machine": platform.machine(),
        "python": sys.version.split()[0],
    }
    try:
        import numpy as np

        report["numpy"] = np.__version__
    except Exception:  # pragma: no cover
        pass
    try:
        import jax

        report["jax"] = jax.__version__
        if include_devices:
            devices = jax.devices()
            report["backend"] = devices[0].platform
            report["device_kind"] = devices[0].device_kind
            report["n_devices"] = len(devices)
            report["process_index"] = jax.process_index()
            report["process_count"] = jax.process_count()
    except Exception as e:  # pragma: no cover - backend init failures
        report["jax_error"] = str(e)
    try:
        import psutil

        vm = psutil.virtual_memory()
        report["cpu_count"] = psutil.cpu_count()
        report["ram_gb"] = round(vm.total / 2**30, 2)
    except Exception:  # pragma: no cover
        pass
    return report


def log_environment() -> dict[str, Any]:
    """Log the report at INFO (the reference's node-start banner)."""
    report = environment_report()
    for key, value in report.items():
        log.info("env %s = %s", key, value)
    return report
