"""``P2PFL_SANITIZE=1`` — opt-in runtime sanitizer.

One environment variable turns on every cheap bug-surfacing mode at
once, for local debugging and the tier-1 sanitized smoke test:

- ``jax_debug_nans``: a NaN produced inside a jitted computation
  raises at the op that made it instead of poisoning the aggregate
  rounds later;
- asyncio debug mode (pass ``sanitize.asyncio_debug()`` to
  ``asyncio.run``): slow-callback warnings (the round-11 event-loop
  blocking class) and never-retrieved task exceptions get tracebacks;
- ``ResourceWarning`` and "coroutine ... was never awaited"
  ``RuntimeWarning`` become errors, so leaked transports/files and
  dropped coroutines fail the run instead of scrolling past.

Usage::

    P2PFL_SANITIZE=1 python -m p2pfl_tpu.p2p.launch config.yaml

    with sanitize.scope():          # no-op unless enabled
        run_simulation(cfg)

The ``scope`` context manager saves and restores both the jax config
flag and the warnings filters, so tests can nest it without leaking
state into the rest of the suite.
"""

from __future__ import annotations

import contextlib
import os
import warnings

ENV_VAR = "P2PFL_SANITIZE"


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "").strip() not in ("", "0", "false")


def asyncio_debug() -> bool | None:
    """Value for ``asyncio.run(..., debug=...)``: ``True`` under the
    sanitizer, ``None`` (leave the interpreter default) otherwise."""
    return True if enabled() else None


@contextlib.contextmanager
def scope():
    """Activate the sanitizer for a block (no-op when disabled)."""
    if not enabled():
        yield
        return
    import jax

    prev_nans = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("error", ResourceWarning)
            warnings.filterwarnings(
                "error", message=r"coroutine .* was never awaited",
                category=RuntimeWarning)
            yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
