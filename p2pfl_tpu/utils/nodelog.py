"""Per-participant file logging.

Parity with the reference's 4-handler setup (base_node.py:133-158):
each node process writes ``node_<idx>.log`` (INFO+),
``node_<idx>_debug.log`` (DEBUG records only), and
``node_<idx>_error.log`` (ERROR+), alongside the console handler —
so a multi-process scenario leaves one inspectable log trail per
participant under ``<log_dir>/<scenario>/logs/``.
"""

from __future__ import annotations

import logging
import pathlib

_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


class _DebugOnly(logging.Filter):
    """The reference's debug file holds ONLY debug records
    (base_node.py:151)."""

    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno == logging.DEBUG


def setup_node_logging(log_dir: str | pathlib.Path, name: str,
                       idx: int, console: bool = True) -> pathlib.Path:
    """Install the per-node handlers on the root logger; returns the
    log directory. Idempotent per (dir, idx): repeated calls don't
    stack duplicate handlers."""
    directory = pathlib.Path(log_dir) / name / "logs"
    directory.mkdir(parents=True, exist_ok=True)
    root = logging.getLogger()
    # DEBUG is scoped to the framework's own logger tree — raising the
    # ROOT level would flood the debug file with jax/asyncio internals
    # (megabytes per XLA compile). Third-party records still reach the
    # files at their default WARNING+ effective level.
    logging.getLogger("p2pfl_tpu").setLevel(logging.DEBUG)
    marker = f"p2pfl-node-{directory}-{idx}"
    if any(getattr(h, "_p2pfl_marker", None) == marker for h in root.handlers):
        return directory
    fmt = logging.Formatter(_FMT)
    specs = [
        (directory / f"node_{idx}.log", logging.INFO, None),
        (directory / f"node_{idx}_debug.log", logging.DEBUG, _DebugOnly()),
        (directory / f"node_{idx}_error.log", logging.ERROR, None),
    ]
    for path, level, filt in specs:
        h = logging.FileHandler(path)
        h.setLevel(level)
        h.setFormatter(fmt)
        if filt is not None:
            h.addFilter(filt)
        h._p2pfl_marker = marker
        root.addHandler(h)
    if console and not any(
        isinstance(h, logging.StreamHandler)
        and not isinstance(h, logging.FileHandler)
        for h in root.handlers
    ):
        sh = logging.StreamHandler()
        sh.setLevel(logging.INFO)
        sh.setFormatter(fmt)
        sh._p2pfl_marker = marker
        root.addHandler(sh)
    return directory
