"""FL-aware metrics logging.

Parity with the reference's richest subsystem (SURVEY.md §5.5): the
forked TensorBoard/W&B loggers whose x-axis concatenates per-round
trainer steps via an accumulated global step
(statisticslogger.py:131-153, lightninglearner.py:162-165), the CSV
option (node.py:122-125), and round markers (node.py:642).

Backends here: JSONL (machine-readable event stream) + per-node CSV,
plus an optional TensorBoard backend (``tensorboard=True``) writing one
event-file run per node and one for the federation — drop-in for the
reference users' `tensorboard --logdir` workflow, with the same
FL-aware global-step x-axis.
"""

from __future__ import annotations

import csv
import json
import pathlib
from typing import Any

from p2pfl_tpu.obs.records import make_record


class MetricsLogger:
    """Writes scenario-level JSONL and per-node CSV metric streams.

    Every record carries ``step`` (FL-aware global step: local steps
    accumulated across rounds) and ``round``. ``node=None`` means a
    federation-level metric (e.g. mean accuracy).
    """

    def __init__(self, log_dir: str | pathlib.Path | None, name: str = "scenario",
                 tensorboard: bool = False, wandb: bool = False):
        self.enabled = log_dir is not None
        self.name = name
        self._csv_files: dict[int, Any] = {}
        self._csv_writers: dict[int, Any] = {}
        self._tb_writers: dict[Any, Any] = {}
        self._tensorboard = tensorboard and self.enabled
        self._wandb_run = None
        if self._tensorboard:
            # fail FAST at construction, not mid-run after training
            # compute was spent
            from torch.utils.tensorboard import SummaryWriter  # noqa: F401
        if wandb:
            # remote tracking (the reference's remotelogger.py W&B
            # fork, selected by tracking_args.enable_remote_tracking);
            # fail fast if the client isn't installed
            import wandb as _wandb

            self._wandb_run = _wandb.init(project="p2pfl_tpu", name=name)
        self.history: list[dict] = []  # in-memory view for tests/benchmarks
        if self.enabled:
            self.dir = pathlib.Path(log_dir) / name
            self.dir.mkdir(parents=True, exist_ok=True)
            # line-buffered AND written one complete line per write()
            # call (log_metrics): in multi-process runs several
            # appenders share this file, and POSIX O_APPEND only
            # guarantees atomicity per write syscall — a row built from
            # multiple write() calls could interleave with another
            # process's row and tear both
            self._jsonl = open(self.dir / "metrics.jsonl", "a", buffering=1)
        else:
            self.dir = None
            self._jsonl = None

    def log_metrics(self, metrics: dict[str, float], step: int = 0,
                    round: int = 0, node: int | None = None) -> None:
        # the shared obs record shape (obs.records.make_record): one ts
        # convention across metrics rows, status files, and trace
        # summaries
        rec = make_record(
            node, step=int(step), round=int(round),
            **{k: float(v) for k, v in metrics.items()},
        )
        self.history.append(rec)
        if self._wandb_run is not None:
            # remote tracking is independent of the local log_dir —
            # one W&B run per scenario; node metrics namespaced the way
            # the reference's logger prefixes participant names
            prefix = "" if node is None else f"node_{node}/"
            self._wandb_run.log(
                {f"{prefix}{k}": float(v) for k, v in metrics.items()},
                step=int(step),
            )
        if not self.enabled:
            return
        # single write() of one complete line + flush: live tailers
        # (webapp tail_metrics, obs.health) may read mid-append, and a
        # torn row must be at worst a *trailing* partial line they can
        # skip — never an interleaved one
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()
        if node is not None:
            self._node_csv(node, rec)
        if self._tensorboard:
            self._tb(node, metrics, step)

    def _tb(self, node: int | None, metrics: dict, step: int) -> None:
        """TensorBoard backend (statisticslogger.py:131-153 parity: the
        x-axis is the FL-aware accumulated global step, so per-round
        trainer curves concatenate into one line per node)."""
        key = "federation" if node is None else f"node_{node}"
        if key not in self._tb_writers:
            from torch.utils.tensorboard import SummaryWriter

            self._tb_writers[key] = SummaryWriter(
                str(self.dir / "tb" / key)
            )
        w = self._tb_writers[key]
        for name, value in metrics.items():
            w.add_scalar(name, float(value), int(step))

    def _node_csv(self, node: int, rec: dict) -> None:
        # long format (ts, step, round, metric, value): metric sets vary
        # between train and eval records, and a wide CSV would freeze its
        # columns at the first row
        if node not in self._csv_writers:
            f = open(self.dir / f"node_{node}.csv", "a", newline="",
                     buffering=1)
            w = csv.writer(f)
            if f.tell() == 0:
                w.writerow(["ts", "step", "round", "metric", "value"])
            self._csv_files[node] = f
            self._csv_writers[node] = w
        w = self._csv_writers[node]
        for key, val in rec.items():
            if key in ("ts", "step", "round", "node"):
                continue
            w.writerow([rec["ts"], rec["step"], rec["round"], key, val])

    def round_marker(self, round: int, step: int) -> None:
        """Round-boundary marker (node.py:642 analog)."""
        self.log_metrics({"round_boundary": 1.0}, step=step, round=round)

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
        for f in self._csv_files.values():
            f.close()
        for w in self._tb_writers.values():
            w.close()
        if self._wandb_run is not None:
            self._wandb_run.finish()
