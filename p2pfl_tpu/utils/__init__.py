"""Cross-cutting utilities: metrics logging, telemetry, rendering."""

from p2pfl_tpu.utils.metrics import MetricsLogger
from p2pfl_tpu.utils.telemetry import resource_snapshot

__all__ = ["MetricsLogger", "resource_snapshot"]
