"""Core dataplane: pytree parameter math, safe serialization, aggregation.

Pure-JAX, no I/O. This layer is the TPU-native replacement for the
reference's torch ``state_dict`` arithmetic
(fedstellar/learning/aggregators/fedavg.py:26-60) and its
pickle-over-TCP serialization
(fedstellar/learning/pytorch/lightninglearner.py:73-89).
"""

from p2pfl_tpu.core.pytree import (
    tree_add,
    tree_cast,
    tree_global_norm,
    tree_param_count,
    tree_scale,
    tree_stack,
    tree_sub,
    tree_unstack,
    tree_weighted_mean,
    tree_zeros_like,
)
from p2pfl_tpu.core.serialize import (
    DecodingParamsError,
    ModelNotMatchingError,
    ParamsPayload,
    check_parameters,
    decode_parameters,
    encode_parameters,
)
from p2pfl_tpu.core.aggregators import (
    Aggregator,
    FedAvg,
    FedMedian,
    Krum,
    TrimmedMean,
    get_aggregator,
)

__all__ = [
    "tree_add",
    "tree_cast",
    "tree_global_norm",
    "tree_param_count",
    "tree_scale",
    "tree_stack",
    "tree_sub",
    "tree_unstack",
    "tree_weighted_mean",
    "tree_zeros_like",
    "DecodingParamsError",
    "ModelNotMatchingError",
    "ParamsPayload",
    "check_parameters",
    "decode_parameters",
    "encode_parameters",
    "Aggregator",
    "FedAvg",
    "FedMedian",
    "Krum",
    "TrimmedMean",
    "get_aggregator",
]
