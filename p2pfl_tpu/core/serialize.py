"""Safe wire serialization for model parameters.

Replaces the reference's ``pickle.dumps(([ndarray, ...], contributors,
weight))`` payloads (fedstellar/learning/pytorch/lightninglearner.py:
73-89) — pickle is code-execution-unsafe between federated peers — with
a versioned msgpack envelope built on ``flax.serialization``. Decode
never executes code; shape/dtype validation against a template pytree
mirrors the reference's ``check_parameters``
(lightninglearner.py:91-99) and its ``ModelNotMatchingError``
(fedstellar/learning/exceptions.py).
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization as flax_ser

_MAGIC = b"P2TP"  # p2pfl_tpu params
_VERSION = 1
_HEADER = struct.Struct(">4sHII")  # magic, version, contributor-count, crc32


class DecodingParamsError(Exception):
    """Raised when a payload cannot be decoded (reference: learning/exceptions.py)."""


class ModelNotMatchingError(Exception):
    """Raised when decoded params don't match the local model template."""


@dataclasses.dataclass
class ParamsPayload:
    """What moves between federated nodes.

    ``contributors`` is the set of node indices whose local models are
    folded into ``params`` (the reference tracks these as string sets,
    fedstellar/learning/aggregators/aggregator.py:151; here they are
    int indices so they can become fixed-shape boolean masks on device).
    ``weight`` is the total sample count backing the payload.
    """

    params: Any
    contributors: tuple[int, ...] = ()
    weight: int = 1


def encode_parameters(params: Any, contributors: tuple[int, ...] = (), weight: int = 1) -> bytes:
    """Encode a params pytree + metadata into a self-describing payload."""
    host_params = jax.tree.map(np.asarray, params)
    body = flax_ser.msgpack_serialize({"p": host_params, "w": np.int64(weight)})
    contrib = struct.pack(f">{len(contributors)}I", *contributors)
    crc = zlib.crc32(contrib + body)
    header = _HEADER.pack(_MAGIC, _VERSION, len(contributors), crc)
    return header + contrib + body


def decode_parameters(blob: bytes) -> ParamsPayload:
    """Decode a payload. Raises DecodingParamsError on any malformation.

    Accepts any bytes-like object and never copies the blob: the CRC,
    the contributor table, and the msgpack body are all read through
    ``memoryview`` slices of the buffer the socket read produced
    (``blob[off:]`` on a tens-of-MB bytes object was a second full
    host-side copy per receive before round 7).
    """
    try:
        mv = memoryview(blob)
        magic, version, n_contrib, crc = _HEADER.unpack_from(mv, 0)
        if magic != _MAGIC or version != _VERSION:
            raise ValueError(f"bad magic/version {magic!r}/{version}")
        if zlib.crc32(mv[_HEADER.size :]) != crc:
            raise ValueError("payload CRC mismatch (corrupt or tampered)")
        off = _HEADER.size
        contributors = struct.unpack_from(f">{n_contrib}I", mv, off)
        off += 4 * n_contrib
        obj = flax_ser.msgpack_restore(mv[off:])
        return ParamsPayload(
            params=obj["p"], contributors=tuple(contributors), weight=int(obj["w"])
        )
    except DecodingParamsError:
        raise
    except Exception as e:  # malformed struct/msgpack — never execute code
        raise DecodingParamsError(f"could not decode params payload: {e}") from e


def check_parameters(params: Any, template: Any) -> None:
    """Validate structure + leaf shapes/dtypes against a template pytree.

    Mirrors lightninglearner.py:91-99 (zip state_dict keys, compare
    shapes) but also catches structure mismatches.
    """
    t_struct = jax.tree.structure(template)
    p_struct = jax.tree.structure(params)
    if t_struct != p_struct:
        raise ModelNotMatchingError(
            f"pytree structure mismatch: got {p_struct}, want {t_struct}"
        )
    for got, want in zip(jax.tree.leaves(params), jax.tree.leaves(template)):
        got_shape = jnp.shape(got)
        want_shape = jnp.shape(want)
        if got_shape != want_shape:
            raise ModelNotMatchingError(
                f"leaf shape mismatch: got {got_shape}, want {want_shape}"
            )
        got_dtype = jnp.result_type(got)
        want_dtype = jnp.result_type(want)
        if got_dtype != want_dtype:
            raise ModelNotMatchingError(
                f"leaf dtype mismatch: got {got_dtype}, want {want_dtype}"
            )
