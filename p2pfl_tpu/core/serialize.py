"""Safe wire serialization for model parameters.

Replaces the reference's ``pickle.dumps(([ndarray, ...], contributors,
weight))`` payloads (fedstellar/learning/pytorch/lightninglearner.py:
73-89) — pickle is code-execution-unsafe between federated peers — with
a versioned msgpack envelope built on ``flax.serialization``. Decode
never executes code; shape/dtype validation against a template pytree
mirrors the reference's ``check_parameters``
(lightninglearner.py:91-99) and its ``ModelNotMatchingError``
(fedstellar/learning/exceptions.py).
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization as flax_ser

_MAGIC = b"P2TP"  # p2pfl_tpu params
_VERSION = 1  # full-precision envelope (the only one v1 decoders accept)
# envelope v2 adds a wire-dtype segment in the body: "d" names the
# reduced precision ("bf16" | "int8"), "dt" records each leaf's
# original dtype (flatten order) so decode restores it exactly, and
# int8 additionally carries per-leaf scales under "s". A v1-only
# decoder rejects v2 loudly via its version check — reduced-precision
# payloads are only sent to peers that advertised support (p2p.node's
# CONNECT-hello negotiation), so mixed fleets interoperate at f32.
_VERSION_QUANT = 2
_HEADER = struct.Struct(">4sHII")  # magic, version, contributor-count, crc32

#: wire precisions ``encode_parameters`` can ship (config.wire_dtype)
WIRE_DTYPES = ("f32", "bf16", "int8")


def _is_float(x) -> bool:
    return jnp.issubdtype(np.asarray(x).dtype, jnp.floating)


def quantize_int8(params: Any) -> tuple[Any, list[float]]:
    """Symmetric per-leaf int8 quantization of the floating leaves.

    Returns the quantized tree plus one scale per leaf in flatten
    order; non-float leaves pass through with scale 0.0 as the
    "untouched" marker. f32 accumulation + ``dequantize_int8`` keep
    aggregation parity — the only error is the rounding at encode.
    """
    leaves, treedef = jax.tree.flatten(params)
    q, scales = [], []
    for leaf in leaves:
        a = np.asarray(leaf)
        if _is_float(a):
            f = a.astype(np.float32)
            scale = float(np.max(np.abs(f)) / 127.0) if f.size else 0.0
            if scale == 0.0:
                scale = 1.0
            q.append(np.clip(np.rint(f / scale), -127, 127).astype(np.int8))
            scales.append(scale)
        else:
            q.append(a)
            scales.append(0.0)
    return jax.tree.unflatten(treedef, q), scales


def dequantize_int8(params: Any, scales: list[float]) -> Any:
    """Inverse of ``quantize_int8``: int8 leaves back to float32."""
    leaves, treedef = jax.tree.flatten(params)
    out = [
        np.asarray(leaf).astype(np.float32) * np.float32(s) if s else leaf
        for leaf, s in zip(leaves, scales)
    ]
    return jax.tree.unflatten(treedef, out)


class DecodingParamsError(Exception):
    """Raised when a payload cannot be decoded (reference: learning/exceptions.py)."""


class ModelNotMatchingError(Exception):
    """Raised when decoded params don't match the local model template."""


@dataclasses.dataclass
class ParamsPayload:
    """What moves between federated nodes.

    ``contributors`` is the set of node indices whose local models are
    folded into ``params`` (the reference tracks these as string sets,
    fedstellar/learning/aggregators/aggregator.py:151; here they are
    int indices so they can become fixed-shape boolean masks on device).
    ``weight`` is the total sample count backing the payload.
    """

    params: Any
    contributors: tuple[int, ...] = ()
    weight: int = 1
    #: the wire blob the leaves view into — ``decode_parameters`` never
    #: copies, so the whole received buffer (or shared-memory slot)
    #: stays alive for as long as ``params`` does. ``release()`` severs
    #: it once the payload's useful life ends.
    _source: Any = dataclasses.field(default=None, repr=False, compare=False)

    def release(self) -> "ParamsPayload":
        """Owning-copy boundary: replace every leaf that still views
        the wire blob with an owning copy and drop the blob reference,
        making the blob (or the shm slot backing it) collectable /
        reusable. Idempotent; returns self for chaining."""
        self.params = own_params(self.params)
        self._source = None
        return self


def own_params(params: Any) -> Any:
    """Return ``params`` with every non-owning leaf (msgpack_restore
    views into a wire blob, shared-memory slot views) replaced by an
    owning ``np.array`` copy. Leaves that already own their buffer pass
    through untouched, so calling this on an aggregation result (fresh
    accumulator arrays) costs only the flag checks."""

    def leaf(a):
        arr = np.asarray(a)
        if arr.flags.owndata and arr.base is None:
            return a
        return np.array(arr)

    return jax.tree.map(leaf, params)


def encode_parameters(params: Any, contributors: tuple[int, ...] = (),
                      weight: int = 1,
                      wire_dtype: str | None = None) -> bytes:
    """Encode a params pytree + metadata into a self-describing payload.

    ``wire_dtype`` None/"f32" emits the byte-identical v1 envelope;
    "bf16" casts floating leaves to bfloat16 on the wire (half the
    payload bytes), "int8" quantizes them with per-leaf scales
    (quarter). Both reduced forms stamp envelope version 2, so a
    decoder that predates them refuses loudly instead of misreading.
    """
    host_params = jax.tree.map(np.asarray, params)
    if wire_dtype in (None, "f32"):
        version = _VERSION
        body = flax_ser.msgpack_serialize(
            {"p": host_params, "w": np.int64(weight)})
    elif wire_dtype == "bf16":
        version = _VERSION_QUANT
        dts = [str(np.asarray(a).dtype)
               for a in jax.tree.leaves(host_params)]
        wire = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16) if _is_float(a) else a,
            host_params)
        body = flax_ser.msgpack_serialize(
            {"p": wire, "w": np.int64(weight), "d": "bf16", "dt": dts})
    elif wire_dtype == "int8":
        version = _VERSION_QUANT
        dts = [str(np.asarray(a).dtype)
               for a in jax.tree.leaves(host_params)]
        wire, scales = quantize_int8(host_params)
        body = flax_ser.msgpack_serialize(
            {"p": wire, "w": np.int64(weight), "d": "int8", "dt": dts,
             "s": np.asarray(scales, np.float32)})
    else:
        raise ValueError(
            f"unknown wire_dtype {wire_dtype!r}; have {WIRE_DTYPES}")
    contrib = struct.pack(f">{len(contributors)}I", *contributors)
    crc = zlib.crc32(contrib + body)
    header = _HEADER.pack(_MAGIC, version, len(contributors), crc)
    return header + contrib + body


def decode_parameters(blob: bytes) -> ParamsPayload:
    """Decode a payload. Raises DecodingParamsError on any malformation.

    Accepts any bytes-like object and never copies the blob: the CRC,
    the contributor table, and the msgpack body are all read through
    ``memoryview`` slices of the buffer the socket read produced
    (``blob[off:]`` on a tens-of-MB bytes object was a second full
    host-side copy per receive before round 7).
    """
    try:
        mv = memoryview(blob)
        magic, version, n_contrib, crc = _HEADER.unpack_from(mv, 0)
        if magic != _MAGIC or version not in (_VERSION, _VERSION_QUANT):
            raise ValueError(f"bad magic/version {magic!r}/{version}")
        if zlib.crc32(mv[_HEADER.size :]) != crc:
            raise ValueError("payload CRC mismatch (corrupt or tampered)")
        off = _HEADER.size
        contributors = struct.unpack_from(f">{n_contrib}I", mv, off)
        off += 4 * n_contrib
        obj = flax_ser.msgpack_restore(mv[off:])
        p = obj["p"]
        if version == _VERSION_QUANT:
            wd = obj.get("d")
            if wd == "int8":
                p = dequantize_int8(
                    p, [float(s) for s in np.asarray(obj["s"])])
            elif wd != "bf16":
                raise ValueError(f"unknown wire dtype {wd!r} in v2 envelope")
            # restore each leaf's recorded origin dtype so aggregation
            # (f32-accumulating numpy FedAvg) and check_parameters see
            # exactly the shapes/dtypes the sender's model holds
            dts = obj["dt"]
            leaves, treedef = jax.tree.flatten(p)
            if len(dts) != len(leaves):
                raise ValueError("wire-dtype leaf table length mismatch")
            p = jax.tree.unflatten(
                treedef,
                [np.asarray(leaf).astype(np.dtype(dt))
                 for leaf, dt in zip(leaves, dts)])
        return ParamsPayload(
            params=p, contributors=tuple(contributors),
            weight=int(obj["w"]), _source=blob,
        )
    except DecodingParamsError:
        raise
    except Exception as e:  # malformed struct/msgpack — never execute code
        raise DecodingParamsError(f"could not decode params payload: {e}") from e


def check_parameters(params: Any, template: Any) -> None:
    """Validate structure + leaf shapes/dtypes against a template pytree.

    Mirrors lightninglearner.py:91-99 (zip state_dict keys, compare
    shapes) but also catches structure mismatches.
    """
    t_struct = jax.tree.structure(template)
    p_struct = jax.tree.structure(params)
    if t_struct != p_struct:
        raise ModelNotMatchingError(
            f"pytree structure mismatch: got {p_struct}, want {t_struct}"
        )
    got_leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    want_leaves = jax.tree.leaves(template)
    for (path, got), want in zip(got_leaves, want_leaves):
        where = jax.tree_util.keystr(path)
        got_shape = jnp.shape(got)
        want_shape = jnp.shape(want)
        if got_shape != want_shape:
            raise ModelNotMatchingError(
                f"leaf {where} shape mismatch: "
                f"got {got_shape}, want {want_shape}"
            )
        got_dtype = jnp.result_type(got)
        want_dtype = jnp.result_type(want)
        if got_dtype != want_dtype:
            raise ModelNotMatchingError(
                f"leaf {where} dtype mismatch: "
                f"got {got_dtype}, want {want_dtype}"
            )
