"""Aggregation algorithms over stacked parameter pytrees.

TPU-native re-design of the reference's aggregator family
(fedstellar/learning/aggregators/aggregator.py + fedavg.py): instead of
a daemon thread collecting ``{contributor-key: (state_dict, weight)}``
dicts and looping over layers, every aggregator here is a **pure
function** ``aggregate(stacked, weights, mask) -> params``:

- ``stacked``: pytree whose leaves carry a leading ``[n]`` node axis;
- ``weights``: float ``[n]`` sample counts (FedAvg weighting,
  fedavg.py:52-58);
- ``mask``: bool ``[n]`` — which rows actually arrived. Timeout-bounded
  aggregation (aggregator.py:46-76 "aggregate with whatever arrived")
  becomes "call with a partial mask"; a dead node is a False entry, not
  a special case.

Everything is fixed-shape and jit-able, so aggregation fuses into the
same XLA program as training and the gossip collectives. The robust
aggregators (Krum, trimmed mean, median) cover the reference's stretch
config "ViT-Tiny … Krum/trimmed-mean aggregator" (BASELINE.json).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from p2pfl_tpu.core.pytree import tree_weighted_mean

Params = Any


def _masked_weights(weights: jnp.ndarray, mask: jnp.ndarray | None) -> jnp.ndarray:
    w = jnp.asarray(weights, jnp.float32)
    if mask is not None:
        w = jnp.where(mask, w, 0.0)
    return w


class Aggregator:
    """Base aggregator. Subclasses implement :meth:`aggregate`.

    The reference's session bookkeeping (waiting for the train set,
    partial-aggregation gossip, contributor dedup —
    aggregator.py:106-229) lives in
    :mod:`p2pfl_tpu.p2p.session`, not here: this class is only
    the math, so it can run on-device.
    """

    name = "base"

    def aggregate(
        self,
        stacked: Params,
        weights: jnp.ndarray,
        mask: jnp.ndarray | None = None,
    ) -> Params:
        raise NotImplementedError

    def __call__(self, stacked, weights, mask=None):
        return self.aggregate(stacked, weights, mask)


class FedAvg(Aggregator):
    """Sample-count-weighted mean (fedavg.py:26-60 semantics)."""

    name = "FedAvg"

    def aggregate(self, stacked, weights, mask=None):
        return tree_weighted_mean(stacked, _masked_weights(weights, mask))


class FedMedian(Aggregator):
    """Coordinate-wise median over present rows.

    Masked rows are replaced by the masked mean so they never win the
    median; with an odd number of present rows this is the exact
    coordinate-wise median.
    """

    name = "FedMedian"

    def aggregate(self, stacked, weights, mask=None):
        w = _masked_weights(weights, mask)
        fill = tree_weighted_mean(stacked, w)
        present = w > 0

        def leaf(x, f):
            bshape = (x.shape[0],) + (1,) * (x.ndim - 1)
            xf = jnp.where(present.reshape(bshape), x.astype(jnp.float32), f)
            return jnp.median(xf, axis=0).astype(x.dtype)

        return jax.tree.map(leaf, stacked, fill)


class TrimmedMean(Aggregator):
    """Coordinate-wise trimmed mean: drop the ``beta`` largest and
    smallest values per coordinate, average the rest.

    ``beta`` is the trim count per side (Byzantine tolerance). Masked
    rows are filled with the masked mean, so they land mid-sort and are
    averaged as if they were the consensus value.
    """

    name = "TrimmedMean"

    def __init__(self, beta: int = 1):
        if beta < 0:
            raise ValueError(f"trim count beta must be >= 0, got {beta}")
        self.beta = beta

    def aggregate(self, stacked, weights, mask=None):
        w = _masked_weights(weights, mask)
        fill = tree_weighted_mean(stacked, w)
        present = w > 0
        n = w.shape[0]
        beta = min(self.beta, max((n - 1) // 2, 0))
        lo, hi = beta, n - beta

        def leaf(x, f):
            bshape = (x.shape[0],) + (1,) * (x.ndim - 1)
            xf = jnp.where(present.reshape(bshape), x.astype(jnp.float32), f)
            xs = jnp.sort(xf, axis=0)
            return jnp.mean(xs[lo:hi], axis=0).astype(x.dtype)

        return jax.tree.map(leaf, stacked, fill)


class Krum(Aggregator):
    """(Multi-)Krum: score each model by the sum of its ``n - f - 2``
    smallest squared distances to other models; return the best one
    (``m=1``) or the mean of the ``m`` best.

    Distances are computed on flattened float32 vectors — one big
    ``[n, d] @ [d, n]`` Gram matmul, which XLA tiles onto the MXU.
    Masked rows get +inf score and can never be selected.
    """

    name = "Krum"

    def __init__(self, f: int = 1, m: int = 1):
        self.f = f
        self.m = m
        self._small_cohort_warned = False

    def aggregate(self, stacked, weights, mask=None):
        w = _masked_weights(weights, mask)
        present = w > 0
        n = w.shape[0]
        # Krum's score needs n_present - f - 2 >= 1 closest neighbors;
        # below that the clip to 1 silently degrades selection to
        # nearest-single-neighbor, which tolerates NOTHING — fail loud
        # instead of returning a number that looks Byzantine-robust.
        # The static row count is checkable even under jit (and a
        # too-small n can never recover at runtime)...
        if n < self.f + 3:
            raise ValueError(
                f"Krum(f={self.f}) needs at least f+3={self.f + 3} rows "
                f"to score n_present-f-2 neighbors, got n={n}; lower f "
                "or use TrimmedMean/FedMedian for small cohorts"
            )
        # ...while a dynamic partial mask can only be checked when it
        # is concrete (eager host-path aggregation); inside a jitted
        # program the clip below still applies, documented here.
        if not isinstance(present, jax.core.Tracer):
            n_present = int(jnp.sum(present))
            if n_present < self.f + 3 and not self._small_cohort_warned:
                import warnings

                warnings.warn(
                    f"Krum(f={self.f}) aggregating only {n_present} "
                    f"present rows (< f+3={self.f + 3}): neighbor count "
                    "clipped to 1 — selection is NOT Byzantine-robust "
                    "this round",
                    RuntimeWarning,
                    stacklevel=2,
                )
                self._small_cohort_warned = True  # once per instance

        flat = jnp.concatenate(
            [x.reshape(n, -1).astype(jnp.float32) for x in jax.tree.leaves(stacked)],
            axis=1,
        )
        sq = jnp.sum(flat * flat, axis=1)
        gram = flat @ flat.T
        d2 = sq[:, None] + sq[None, :] - 2.0 * gram  # [n, n]
        big = jnp.float32(jnp.finfo(jnp.float32).max / 4)
        # distances to self / to absent rows never count as "closest"
        d2 = jnp.where(jnp.eye(n, dtype=bool), big, d2)
        d2 = jnp.where(present[None, :], d2, big)

        n_present = jnp.sum(present.astype(jnp.int32))
        k = jnp.clip(n_present - self.f - 2, 1, n - 1)  # closest-count per Krum
        d2_sorted = jnp.sort(d2, axis=1)
        col_mask = jnp.arange(n - 1)[None, :] < k  # static shape, dynamic k
        scores = jnp.sum(jnp.where(col_mask, d2_sorted[:, : n - 1], 0.0), axis=1)
        scores = jnp.where(present, scores, jnp.inf)

        m = min(self.m, n)
        _, best = jax.lax.top_k(-scores, m)  # indices of m lowest scores
        sel = jnp.zeros((n,), jnp.float32).at[best].set(1.0)
        sel = jnp.where(present, sel, 0.0)
        return tree_weighted_mean(stacked, sel)


_REGISTRY: dict[str, Callable[..., Aggregator]] = {
    "fedavg": FedAvg,
    "fedmedian": FedMedian,
    "median": FedMedian,
    "trimmedmean": TrimmedMean,
    "krum": Krum,
}


def get_aggregator(name: str, **kwargs) -> Aggregator:
    """Factory by name (reference selects by ``aggregator_args.algorithm``,
    participant.json.example + node.py:134-137)."""
    key = name.lower().replace("_", "").replace("-", "")
    if key not in _REGISTRY:
        raise ValueError(f"unknown aggregator {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)
