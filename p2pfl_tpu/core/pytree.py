"""Parameter-pytree arithmetic.

Models are pytrees of ``jnp.ndarray`` (flax param dicts). A *federation*
of N nodes is the same pytree with a leading ``nodes`` axis on every
leaf ("stacked" form) — that leading axis is what gets sharded over the
TPU mesh or vmapped on a single chip.

Replaces the reference's per-layer ``state_dict`` loops
(fedstellar/learning/aggregators/fedavg.py:46-58) with
``jax.tree.map`` so XLA sees one fused program.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = Any  # a pytree of jnp.ndarray


def tree_zeros_like(tree: Params) -> Params:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Params, b: Params) -> Params:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Params, b: Params) -> Params:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Params, s) -> Params:
    return jax.tree.map(lambda x: x * s, tree)


def tree_cast(tree: Params, dtype) -> Params:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_param_count(tree: Params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(tree))


def tree_global_norm(tree: Params) -> jnp.ndarray:
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree.leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def tree_stack(trees: list[Params]) -> Params:
    """Stack N same-structure pytrees into one with a leading node axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(stacked: Params, n: int | None = None) -> list[Params]:
    """Inverse of :func:`tree_stack`."""
    if n is None:
        n = jax.tree.leaves(stacked)[0].shape[0]
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


def tree_weighted_mean(stacked: Params, weights: jnp.ndarray) -> Params:
    """Weighted mean over the leading node axis.

    ``weights`` has shape ``[n]``; zero-weight entries drop out, so an
    alive/contributor mask can be folded into the weights. Semantics of
    the reference's FedAvg (fedstellar/learning/aggregators/fedavg.py:
    46-58: accumulate ``m[layer]*w`` then divide by total samples), with
    the accumulation done in float32 regardless of storage dtype.

    Degenerate case: if the total weight is zero (nothing arrived before
    the aggregation timeout and the caller masked everything out), the
    result falls back to the **uniform mean over all rows** rather than
    silently zeroing the model. Federation callers always include self
    in the mask, so this fallback only fires on direct misuse.
    """
    total = jnp.sum(weights)
    n = jnp.shape(weights)[0]
    weights = jnp.where(total > 0, weights, jnp.ones_like(weights))
    total = jnp.where(total > 0, total, jnp.asarray(n, total.dtype))
    w = (weights / total).astype(jnp.float32)

    def leaf_mean(x):
        wshape = (x.shape[0],) + (1,) * (x.ndim - 1)
        acc = jnp.sum(x.astype(jnp.float32) * w.reshape(wshape), axis=0)
        return acc.astype(x.dtype)

    return jax.tree.map(leaf_mean, stacked)
