"""p2pfl_tpu — a TPU-native decentralized federated learning framework.

A brand-new framework with the capabilities of Fedstellar/p2pfl
(reference: /root/reference — DFL/CFL/SDFL federations, gossip weight
exchange over arbitrary topologies, node roles, FedAvg and robust
aggregation, scenario orchestration and observability), re-designed
TPU-first on JAX/XLA:

- A federation is a **sharded SPMD program on a device mesh**: federated
  node *i* lives on mesh position *i* along a ``nodes`` axis; local
  training is a jit-compiled ``lax.scan``; weight exchange is a masked
  XLA collective (``all_gather``/``ppermute``/``psum_scatter``) over ICI
  instead of pickled tensors over TCP sockets
  (reference: fedstellar/communication_protocol.py, gossiper.py).
- The asynchronous control plane of the reference (membership,
  heartbeats, role transfer, timeouts — fedstellar/heartbeater.py,
  node.py) becomes an explicit, deterministic round state machine on the
  host, with failure injection as first-class simulation state.
- Aggregation (reference: fedstellar/learning/aggregators/) is a pure
  function over a stacked parameter pytree with boolean
  contributor/alive masks — fixed shapes, jit-able, MXU-friendly.
"""

from p2pfl_tpu.version import __version__

__all__ = ["__version__"]
