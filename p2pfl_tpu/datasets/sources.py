"""Dataset sources: real files when available, synthetic surrogates otherwise.

Dataset families mirror the reference (SURVEY.md §2.4):

| name     | shape          | classes | reference                          |
|----------|----------------|---------|------------------------------------|
| mnist    | 28×28×1        | 10      | mnist/mnist.py                     |
| femnist  | 28×28×1        | 62      | femnist/femnist.py (LEAF)          |
| cifar10  | 32×32×3        | 10      | cifar10/cifar10.py                 |
| syscall  | 17 features    | 9       | syscall/syscall.py                 |
| wadi     | 123 features   | 2       | wadi/wadi.py                       |

Real data: ``$P2PFL_TPU_DATA_DIR/<name>.npz`` with arrays
``x_train, y_train, x_test, y_test`` (images HWC float or uint8), or
for MNIST the standard idx-ubyte files. The reference downloads at
first use (femnist.py:24-77, syscall.py:60-113); this environment has
no egress, so absent files fall back to a **deterministic learnable
surrogate**: each class is a smooth random prototype field plus
per-sample elastic noise — linearly separable enough that real models
show real learning curves, hard enough that accuracy is not trivially
100%.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import pathlib
import struct
import zlib

import numpy as np

_SPECS: dict[str, tuple[tuple[int, ...], int]] = {
    "mnist": ((28, 28, 1), 10),
    "femnist": ((28, 28, 1), 62),
    "cifar10": ((32, 32, 3), 10),
    "syscall": ((17,), 9),
    "wadi": ((123,), 2),
}

DATASETS = tuple(sorted(_SPECS))


@dataclasses.dataclass
class DatasetSplits:
    """Host-side numpy train/test splits, normalized, channels-last.

    ``writer_train`` (optional): per-sample writer/source id — LEAF
    FEMNIST's natural grouping (femnist.py partitions by writer). The
    hard surrogate emits it; real npz files may include a
    ``writer_train`` array. Enables ``partition="writer"``.
    """

    name: str
    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray
    num_classes: int
    synthetic: bool = False
    writer_train: np.ndarray | None = None

    @property
    def input_shape(self) -> tuple[int, ...]:
        return tuple(self.x_train.shape[1:])


def _data_dir() -> pathlib.Path | None:
    d = os.environ.get("P2PFL_TPU_DATA_DIR")
    return pathlib.Path(d) if d else None


def _read_idx(path: pathlib.Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _try_load_real(name: str) -> DatasetSplits | None:
    d = _data_dir()
    if d is None:
        return None
    npz = d / f"{name}.npz"
    if npz.exists():
        z = np.load(npz)
        out = _normalize(
            name, z["x_train"], z["y_train"], z["x_test"], z["y_test"]
        )
        if "writer_train" in z:  # enables partition="writer" (LEAF)
            out.writer_train = (
                np.asarray(z["writer_train"]).astype(np.int32).reshape(-1)
            )
        return out
    if name == "mnist":  # standard idx-ubyte layout
        files = {}
        for key, stems in {
            "x_train": ["train-images-idx3-ubyte"],
            "y_train": ["train-labels-idx1-ubyte"],
            "x_test": ["t10k-images-idx3-ubyte"],
            "y_test": ["t10k-labels-idx1-ubyte"],
        }.items():
            found = None
            for stem in stems:
                for cand in (d / "mnist" / stem, d / "mnist" / f"{stem}.gz",
                             d / stem, d / f"{stem}.gz"):
                    if cand.exists():
                        found = cand
                        break
                if found:
                    break
            if not found:
                return None
            files[key] = _read_idx(found)
        return _normalize(name, files["x_train"], files["y_train"],
                          files["x_test"], files["y_test"])
    return None


def _normalize(name, x_train, y_train, x_test, y_test) -> DatasetSplits:
    shape, num_classes = _SPECS[name]

    def prep(x):
        x = np.asarray(x)
        if x.dtype == np.uint8:
            x = x.astype(np.float32) / 255.0
        x = x.astype(np.float32)
        if len(shape) == 3 and x.ndim == 3:  # HW → HWC
            x = x[..., None]
        return x.reshape((x.shape[0],) + shape)

    return DatasetSplits(
        name=name,
        x_train=prep(x_train),
        y_train=np.asarray(y_train).astype(np.int32).reshape(-1),
        x_test=prep(x_test),
        y_test=np.asarray(y_test).astype(np.int32).reshape(-1),
        num_classes=num_classes,
    )


def _smooth_protos(rng, num_classes: int, shape, dim: int) -> np.ndarray:
    protos = rng.normal(0.0, 1.0, size=(num_classes, dim)).astype(np.float32)
    if len(shape) == 3:  # smooth image prototypes: blur flat noise a little
        img = protos.reshape((num_classes,) + shape)
        for ax in (1, 2):
            img = (
                0.5 * img
                + 0.25 * np.roll(img, 1, axis=ax)
                + 0.25 * np.roll(img, -1, axis=ax)
            )
        protos = img.reshape(num_classes, dim)
    return protos


def _synthetic_easy(name: str, n_train: int, n_test: int,
                    seed: int) -> DatasetSplits:
    """Rounds 1-4 surrogate: y → smooth prototype P_y; x = P_y rolled
    by a per-sample shift + gaussian noise. Learnable by linear models
    yet non-trivial (shift invariance must be learned). Kept verbatim
    for metric continuity — it saturates ~0.99, so round 5 made the
    HARD profile the default (VERDICT r4 #5)."""
    shape, num_classes = _SPECS[name]
    rng = np.random.default_rng(seed + zlib.crc32(name.encode()) % (2**16))
    dim = int(np.prod(shape))
    protos = _smooth_protos(rng, num_classes, shape, dim)

    def draw(n, rng):
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        shift = rng.integers(0, 4, size=n)
        base = protos[y]
        rows = np.arange(dim)
        x = np.empty((n, dim), np.float32)
        for s in range(4):
            m = shift == s
            if m.any():
                x[m] = base[m][:, (rows - s) % dim]
        x += rng.normal(0.0, 0.8, size=x.shape).astype(np.float32)
        return x.reshape((n,) + shape), y

    x_train, y_train = draw(n_train, rng)
    x_test, y_test = draw(n_test, rng)
    return DatasetSplits(
        name=name, x_train=x_train, y_train=y_train, x_test=x_test,
        y_test=y_test, num_classes=num_classes, synthetic=True,
    )


#: hard-surrogate difficulty knobs (calibrated on the bench chip so the
#: 64-node north-star federation plateaus ~0.85-0.92 — VERDICT r4 #5;
#: calibration sweep: scripts/exp_surrogate_calibration.py)
_HARD = {
    "n_writers": 240,       # 80% train / 20% held out for the test set
    "style_gamma": 0.7,     # writer-specific class-rendering strength
    "skew_alpha": 0.3,      # per-writer Dirichlet class skew (LEAF-like)
    "label_noise": 0.04,    # train-label flip rate (test labels clean)
    "sample_noise": 0.8,    # per-sample gaussian sigma
}
# calibration (bench chip, 64-node north star, 30-round trajectory —
# scripts/exp_surrogate_calibration.py): gamma 0.4 -> plateau 0.948,
# 0.55 -> 0.937, 0.7 -> 0.917 with rounds-to-80 = 13. gamma 0.7 puts
# the plateau in the 0.85-0.92 target band: 80% is now a threshold the
# federation fights for, not a point on a saturating curve.


def _synthetic_hard(name: str, n_train: int, n_test: int,
                    seed: int) -> DatasetSplits:
    """LEAF-calibrated surrogate (VERDICT r4 #5): the easy profile's
    prototypes, plus the structure that makes real federated FEMNIST
    hard —

    - **writers**: each sample belongs to a writer; a writer renders
      class y as ``P_y + γ·D_{w,y}`` (a writer-specific smooth
      deformation of the class prototype) with a writer intensity
      scale/bias. The TEST set is drawn from held-out writers, so the
      ~0.85-0.92 plateau is a real style-generalization gap, not an
      additive-noise floor.
    - **per-writer class skew**: writer class distributions are
      Dirichlet(α) draws (LEAF femnist: writers favor characters);
      with ``partition="writer"`` nodes inherit that skew.
    - **label noise**: a small fraction of TRAIN labels flipped
      (test labels stay clean — the metric measures generalization).

    Emits ``writer_train`` ids for writer-partitioning.
    """
    shape, num_classes = _SPECS[name]
    cfg = _HARD
    rng = np.random.default_rng(
        seed + zlib.crc32((name + "/hard").encode()) % (2**16))
    dim = int(np.prod(shape))
    protos = _smooth_protos(rng, num_classes, shape, dim)

    n_writers = cfg["n_writers"]
    n_w_test = max(n_writers // 5, 1)
    # writer-specific class renderings: smooth like the prototypes so
    # the style lives in the same frequency band the classifier uses
    deltas = rng.normal(0.0, 1.0, size=(n_writers, num_classes, dim)
                        ).astype(np.float32)
    if len(shape) == 3:
        img = deltas.reshape((n_writers * num_classes,) + shape)
        for ax in (1, 2):
            img = (0.5 * img + 0.25 * np.roll(img, 1, axis=ax)
                   + 0.25 * np.roll(img, -1, axis=ax))
        deltas = img.reshape(n_writers, num_classes, dim)
    w_scale = rng.normal(1.0, 0.15, size=n_writers).astype(np.float32)
    w_bias = rng.normal(0.0, 0.2, size=n_writers).astype(np.float32)
    w_probs = rng.dirichlet([cfg["skew_alpha"]] * num_classes,
                            size=n_writers).astype(np.float32)

    def draw(n, writer_pool, rng, label_noise):
        w = writer_pool[rng.integers(0, len(writer_pool), size=n)]
        # per-writer skewed class draw (vectorized inverse-CDF)
        cdf = np.cumsum(w_probs[w], axis=1)
        y = (rng.random((n, 1)) < cdf).argmax(axis=1).astype(np.int32)
        base = protos[y] + cfg["style_gamma"] * deltas[w, y]
        x = w_scale[w, None] * base + w_bias[w, None]
        shift = rng.integers(0, 4, size=n)
        rows = np.arange(dim)
        out = np.empty((n, dim), np.float32)
        for s in range(4):
            m = shift == s
            if m.any():
                out[m] = x[m][:, (rows - s) % dim]
        out += rng.normal(0.0, cfg["sample_noise"],
                          size=out.shape).astype(np.float32)
        if label_noise:
            flip = rng.random(n) < label_noise
            y = np.where(
                flip, rng.integers(0, num_classes, size=n), y
            ).astype(np.int32)
        return out.reshape((n,) + shape), y, w.astype(np.int32)

    train_pool = np.arange(n_writers - n_w_test)
    test_pool = np.arange(n_writers - n_w_test, n_writers)
    x_train, y_train, w_train = draw(n_train, train_pool, rng,
                                     cfg["label_noise"])
    x_test, y_test, _ = draw(n_test, test_pool, rng, 0.0)
    return DatasetSplits(
        name=name, x_train=x_train, y_train=y_train, x_test=x_test,
        y_test=y_test, num_classes=num_classes, synthetic=True,
        writer_train=w_train,
    )


def _synthetic(name: str, n_train: int, n_test: int, seed: int,
               profile: str = "hard") -> DatasetSplits:
    if profile == "easy":
        return _synthetic_easy(name, n_train, n_test, seed)
    if profile == "hard":
        return _synthetic_hard(name, n_train, n_test, seed)
    raise ValueError(f"unknown surrogate profile {profile!r}")


_SYNTH_SIZES = {  # match real dataset scale where it matters, smaller for speed
    "mnist": (20000, 4000),
    "femnist": (24000, 4000),
    "cifar10": (20000, 4000),
    "syscall": (10000, 2000),
    "wadi": (10000, 2000),
}


def get_dataset(name: str, seed: int = 0,
                synthetic_sizes: tuple[int, int] | None = None,
                profile: str = "hard") -> DatasetSplits:
    """Load a dataset by name — real if files exist, surrogate otherwise."""
    key = name.lower()
    if key not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; have {DATASETS}")
    real = _try_load_real(key)
    if real is not None:
        return real
    n_train, n_test = synthetic_sizes or _SYNTH_SIZES[key]
    return _synthetic(key, n_train, n_test, seed, profile=profile)
