"""Dataset sources: real files when available, synthetic surrogates otherwise.

Dataset families mirror the reference (SURVEY.md §2.4):

| name     | shape          | classes | reference                          |
|----------|----------------|---------|------------------------------------|
| mnist    | 28×28×1        | 10      | mnist/mnist.py                     |
| femnist  | 28×28×1        | 62      | femnist/femnist.py (LEAF)          |
| cifar10  | 32×32×3        | 10      | cifar10/cifar10.py                 |
| syscall  | 17 features    | 9       | syscall/syscall.py                 |
| wadi     | 123 features   | 2       | wadi/wadi.py                       |

Real data: ``$P2PFL_TPU_DATA_DIR/<name>.npz`` with arrays
``x_train, y_train, x_test, y_test`` (images HWC float or uint8), or
for MNIST the standard idx-ubyte files. The reference downloads at
first use (femnist.py:24-77, syscall.py:60-113); this environment has
no egress, so absent files fall back to a **deterministic learnable
surrogate**: each class is a smooth random prototype field plus
per-sample elastic noise — linearly separable enough that real models
show real learning curves, hard enough that accuracy is not trivially
100%.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import pathlib
import struct
import zlib

import numpy as np

_SPECS: dict[str, tuple[tuple[int, ...], int]] = {
    "mnist": ((28, 28, 1), 10),
    "femnist": ((28, 28, 1), 62),
    "cifar10": ((32, 32, 3), 10),
    "syscall": ((17,), 9),
    "wadi": ((123,), 2),
}

DATASETS = tuple(sorted(_SPECS))


@dataclasses.dataclass
class DatasetSplits:
    """Host-side numpy train/test splits, normalized, channels-last."""

    name: str
    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray
    num_classes: int
    synthetic: bool = False

    @property
    def input_shape(self) -> tuple[int, ...]:
        return tuple(self.x_train.shape[1:])


def _data_dir() -> pathlib.Path | None:
    d = os.environ.get("P2PFL_TPU_DATA_DIR")
    return pathlib.Path(d) if d else None


def _read_idx(path: pathlib.Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _try_load_real(name: str) -> DatasetSplits | None:
    d = _data_dir()
    if d is None:
        return None
    npz = d / f"{name}.npz"
    if npz.exists():
        z = np.load(npz)
        return _normalize(
            name, z["x_train"], z["y_train"], z["x_test"], z["y_test"]
        )
    if name == "mnist":  # standard idx-ubyte layout
        files = {}
        for key, stems in {
            "x_train": ["train-images-idx3-ubyte"],
            "y_train": ["train-labels-idx1-ubyte"],
            "x_test": ["t10k-images-idx3-ubyte"],
            "y_test": ["t10k-labels-idx1-ubyte"],
        }.items():
            found = None
            for stem in stems:
                for cand in (d / "mnist" / stem, d / "mnist" / f"{stem}.gz",
                             d / stem, d / f"{stem}.gz"):
                    if cand.exists():
                        found = cand
                        break
                if found:
                    break
            if not found:
                return None
            files[key] = _read_idx(found)
        return _normalize(name, files["x_train"], files["y_train"],
                          files["x_test"], files["y_test"])
    return None


def _normalize(name, x_train, y_train, x_test, y_test) -> DatasetSplits:
    shape, num_classes = _SPECS[name]

    def prep(x):
        x = np.asarray(x)
        if x.dtype == np.uint8:
            x = x.astype(np.float32) / 255.0
        x = x.astype(np.float32)
        if len(shape) == 3 and x.ndim == 3:  # HW → HWC
            x = x[..., None]
        return x.reshape((x.shape[0],) + shape)

    return DatasetSplits(
        name=name,
        x_train=prep(x_train),
        y_train=np.asarray(y_train).astype(np.int32).reshape(-1),
        x_test=prep(x_test),
        y_test=np.asarray(y_test).astype(np.int32).reshape(-1),
        num_classes=num_classes,
    )


def _synthetic(name: str, n_train: int, n_test: int, seed: int) -> DatasetSplits:
    """Class-prototype surrogate: y → smooth prototype P_y; x = P_y
    rolled by a per-sample shift + gaussian noise. Learnable by linear
    models yet non-trivial (shift invariance must be learned)."""
    shape, num_classes = _SPECS[name]
    rng = np.random.default_rng(seed + zlib.crc32(name.encode()) % (2**16))
    dim = int(np.prod(shape))
    protos = rng.normal(0.0, 1.0, size=(num_classes, dim)).astype(np.float32)
    if len(shape) == 3:  # smooth image prototypes: blur flat noise a little
        img = protos.reshape((num_classes,) + shape)
        for ax in (1, 2):
            img = (
                0.5 * img
                + 0.25 * np.roll(img, 1, axis=ax)
                + 0.25 * np.roll(img, -1, axis=ax)
            )
        protos = img.reshape(num_classes, dim)

    def draw(n, rng):
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        shift = rng.integers(0, 4, size=n)
        base = protos[y]
        rows = np.arange(dim)
        x = np.empty((n, dim), np.float32)
        for s in range(4):
            m = shift == s
            if m.any():
                x[m] = base[m][:, (rows - s) % dim]
        x += rng.normal(0.0, 0.8, size=x.shape).astype(np.float32)
        return x.reshape((n,) + shape), y

    x_train, y_train = draw(n_train, rng)
    x_test, y_test = draw(n_test, rng)
    return DatasetSplits(
        name=name, x_train=x_train, y_train=y_train, x_test=x_test,
        y_test=y_test, num_classes=num_classes, synthetic=True,
    )


_SYNTH_SIZES = {  # match real dataset scale where it matters, smaller for speed
    "mnist": (20000, 4000),
    "femnist": (24000, 4000),
    "cifar10": (20000, 4000),
    "syscall": (10000, 2000),
    "wadi": (10000, 2000),
}


def get_dataset(name: str, seed: int = 0,
                synthetic_sizes: tuple[int, int] | None = None) -> DatasetSplits:
    """Load a dataset by name — real if files exist, surrogate otherwise."""
    key = name.lower()
    if key not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; have {DATASETS}")
    real = _try_load_real(key)
    if real is not None:
        return real
    n_train, n_test = synthetic_sizes or _SYNTH_SIZES[key]
    return _synthetic(key, n_train, n_test, seed)
