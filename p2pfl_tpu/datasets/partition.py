"""Federated partitioning of a dataset across N nodes.

Reference semantics reproduced:
- ``iid``: contiguous equal ranges after a seeded shuffle
  (mnist.py:100-118 — ``rows_by_sub = floor(len/number_sub)``,
  node i takes rows [i*k, (i+1)*k)).
- ``sorted``: label-sort the dataset *then* contiguous ranges, giving
  each node a few labels only (mnist.py:76-83 non-IID flag).
- ``dirichlet``: per-class Dirichlet(α) allocation across nodes — the
  standard non-IID benchmark knob (BASELINE.json: "non-IID Dirichlet
  shards"), absent in the reference.

All return ``list[np.ndarray]`` of row indices, length N.
"""

from __future__ import annotations

import numpy as np


def iid_partition(labels: np.ndarray, n_nodes: int, seed: int = 0) -> list[np.ndarray]:
    n = len(labels)
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    per = n // n_nodes
    return [order[i * per : (i + 1) * per] for i in range(n_nodes)]


def sorted_partition(labels: np.ndarray, n_nodes: int, seed: int = 0) -> list[np.ndarray]:
    order = np.argsort(labels, kind="stable")
    per = len(labels) // n_nodes
    return [order[i * per : (i + 1) * per] for i in range(n_nodes)]


def dirichlet_partition(
    labels: np.ndarray, n_nodes: int, alpha: float = 0.5, seed: int = 0,
    min_per_node: int = 2,
) -> list[np.ndarray]:
    """Per-class proportions ~ Dirichlet(α); α→∞ is IID, α→0 is 1-class
    nodes. Redraws until every node has ``min_per_node`` samples."""
    rng = np.random.default_rng(seed)
    classes = np.unique(labels)
    for _ in range(100):
        shards: list[list[np.ndarray]] = [[] for _ in range(n_nodes)]
        for c in classes:
            idx = np.flatnonzero(labels == c)
            rng.shuffle(idx)
            props = rng.dirichlet([alpha] * n_nodes)
            cuts = (np.cumsum(props) * len(idx)).astype(int)[:-1]
            for node, part in enumerate(np.split(idx, cuts)):
                shards[node].append(part)
        parts = [np.concatenate(s) if s else np.empty(0, np.int64) for s in shards]
        if min(len(p) for p in parts) >= min_per_node:
            for p in parts:
                rng.shuffle(p)
            return parts
    raise RuntimeError(
        f"dirichlet_partition could not give every node >= {min_per_node} "
        f"samples (n={len(labels)}, nodes={n_nodes}, alpha={alpha})"
    )


def writer_partition(groups: np.ndarray, n_nodes: int,
                     seed: int = 0) -> list[np.ndarray]:
    """LEAF-style natural non-IID: whole writers (source groups) are
    assigned to nodes, so every node inherits its writers' class skew
    and style — the reference's FEMNIST is partitioned exactly this
    way (femnist.py: one LEAF writer bundle per participant)."""
    rng = np.random.default_rng(seed)
    writers = rng.permutation(np.unique(groups))
    if len(writers) < n_nodes:
        raise ValueError(
            f"writer partition needs >= 1 writer per node: "
            f"{len(writers)} writers < {n_nodes} nodes"
        )
    assignment = {w: i % n_nodes for i, w in enumerate(writers)}
    node_of = np.vectorize(assignment.get, otypes=[np.int64])(groups)
    return [np.flatnonzero(node_of == i) for i in range(n_nodes)]


def partition_indices(
    labels: np.ndarray, n_nodes: int, scheme: str = "iid", seed: int = 0,
    alpha: float = 0.5, groups: np.ndarray | None = None,
) -> list[np.ndarray]:
    """Factory by scheme name (DataConfig.partition)."""
    if scheme == "iid":
        return iid_partition(labels, n_nodes, seed)
    if scheme in ("sorted", "non-iid", "noniid"):
        return sorted_partition(labels, n_nodes, seed)
    if scheme == "dirichlet":
        return dirichlet_partition(labels, n_nodes, alpha=alpha, seed=seed)
    if scheme == "writer":
        if groups is None:
            raise ValueError(
                "partition='writer' needs per-sample writer ids "
                "(dataset provides none)"
            )
        return writer_partition(groups, n_nodes, seed)
    raise ValueError(f"unknown partition scheme {scheme!r}")
