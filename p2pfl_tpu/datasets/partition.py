"""Federated partitioning of a dataset across N nodes.

Reference semantics reproduced:
- ``iid``: contiguous equal ranges after a seeded shuffle
  (mnist.py:100-118 — ``rows_by_sub = floor(len/number_sub)``,
  node i takes rows [i*k, (i+1)*k)).
- ``sorted``: label-sort the dataset *then* contiguous ranges, giving
  each node a few labels only (mnist.py:76-83 non-IID flag).
- ``dirichlet``: per-class Dirichlet(α) allocation across nodes — the
  standard non-IID benchmark knob (BASELINE.json: "non-IID Dirichlet
  shards"), absent in the reference.

All return ``list[np.ndarray]`` of row indices, length N.

Round 13 adds the cross-device path: at N=10k–1M virtual clients,
materializing N index arrays (and, for Dirichlet, N Python lists per
redraw) is the setup bottleneck, so :class:`ClientPartition` keeps ONE
grouped order array + offsets and materializes a client's indices only
when that client is sampled.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Dirichlet partitions at/above this width take the vectorized
# assignment path (see dirichlet_partition's seed contract note)
_DIRICHLET_VECTORIZE_AT = 512


def iid_partition(labels: np.ndarray, n_nodes: int, seed: int = 0) -> list[np.ndarray]:
    n = len(labels)
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    per = n // n_nodes
    return [order[i * per : (i + 1) * per] for i in range(n_nodes)]


def sorted_partition(labels: np.ndarray, n_nodes: int, seed: int = 0) -> list[np.ndarray]:
    order = np.argsort(labels, kind="stable")
    per = len(labels) // n_nodes
    return [order[i * per : (i + 1) * per] for i in range(n_nodes)]


def _dirichlet_assign(
    labels: np.ndarray, n_nodes: int, alpha: float, rng: np.random.Generator,
    min_per_node: int = 2, max_tries: int = 100,
) -> np.ndarray:
    """Vectorized Dirichlet allocation: one ``node_of[sample]`` array
    per attempt instead of ``classes x n_nodes`` Python list segments.

    Same allocation law as the legacy loop — per class, a shuffled
    index list cut at ``cumsum(Dirichlet(α)) * len`` — but the per-node
    ``np.split``/append/concatenate churn (the O(classes × N × retries)
    term that dominates setup at N=10k+) is replaced by a single
    ``searchsorted`` per class: position p of class c lands on the node
    whose cut interval contains p, which is exactly the segment
    ``np.split`` would have put it in.
    """
    if len(labels) < n_nodes * min_per_node:
        raise RuntimeError(
            f"dirichlet_partition could not give every node >= {min_per_node} "
            f"samples (n={len(labels)}, nodes={n_nodes}, alpha={alpha}): "
            f"need at least {n_nodes * min_per_node} samples"
        )
    classes = np.unique(labels)
    class_idx = [np.flatnonzero(labels == c) for c in classes]
    node_of = np.empty(len(labels), np.int64)
    # In the sparse regime (few samples per node on average) essentially
    # every draw leaves some node short, so redrawing is futile — fall
    # through to the deterministic repair after a handful of attempts.
    tries = max_tries if len(labels) >= 8 * min_per_node * n_nodes else 3
    for _ in range(tries):
        props = rng.dirichlet([alpha] * n_nodes, size=len(classes))
        for ci, idx in enumerate(class_idx):
            idx = idx.copy()
            rng.shuffle(idx)
            cuts = (np.cumsum(props[ci]) * len(idx)).astype(int)[:-1]
            node_of[idx] = np.searchsorted(
                cuts, np.arange(len(idx)), side="right"
            )
        counts = np.bincount(node_of, minlength=n_nodes)
        if counts.min() >= min_per_node:
            return node_of
    # Repair the last draw instead of failing: move surplus samples
    # (rank >= min_per_node within their node, so no donor ever drops
    # below the floor) from the largest nodes to the deficient ones.
    # Deterministic given the draw, so outputs stay a function of seed.
    deficit = np.maximum(min_per_node - counts, 0)
    total_deficit = int(deficit.sum())
    order = np.argsort(node_of, kind="stable")
    starts = np.zeros(n_nodes, np.int64)
    starts[1:] = np.cumsum(counts)[:-1]
    rank = np.arange(len(order), dtype=np.int64) - starts[node_of[order]]
    movable = order[rank >= min_per_node]
    mrank = rank[rank >= min_per_node]
    key = counts[node_of[movable]] * np.int64(len(labels) + 1) + mrank
    sel = movable[np.argsort(-key, kind="stable")[:total_deficit]]
    node_of[sel] = np.repeat(np.arange(n_nodes), deficit)
    return node_of


def dirichlet_partition(
    labels: np.ndarray, n_nodes: int, alpha: float = 0.5, seed: int = 0,
    min_per_node: int = 2,
) -> list[np.ndarray]:
    """Per-class proportions ~ Dirichlet(α); α→∞ is IID, α→0 is 1-class
    nodes. Redraws until every node has ``min_per_node`` samples; the
    vectorized large-N path additionally repairs a short draw by moving
    surplus samples from the largest nodes (redraws can never satisfy
    the floor at e.g. 10k clients on a 60k-sample dataset), raising
    only when ``len(labels) < n_nodes * min_per_node``.

    Seed contract: below ``n_nodes == 512`` the legacy draw order is
    kept, so small-N outputs are byte-identical to every earlier round.
    At ``n_nodes >= 512`` (round 13, cross-device scale) the redraw
    path is vectorized — the Dirichlet rows are drawn in one batched
    call and per-node segments assigned by ``searchsorted`` — which
    consumes the generator in a different order: large-N outputs are
    deterministic in ``seed`` but NOT comparable to what the legacy
    loop would have produced. No prior release supported that width,
    so no stored partition changes.
    """
    rng = np.random.default_rng(seed)
    if n_nodes >= _DIRICHLET_VECTORIZE_AT:
        node_of = _dirichlet_assign(labels, n_nodes, alpha, rng,
                                    min_per_node=min_per_node)
        order = np.argsort(node_of, kind="stable")
        counts = np.bincount(node_of, minlength=n_nodes)
        parts = np.split(order, np.cumsum(counts)[:-1])
        for p in parts:
            rng.shuffle(p)
        return parts
    classes = np.unique(labels)
    for _ in range(100):
        shards: list[list[np.ndarray]] = [[] for _ in range(n_nodes)]
        for c in classes:
            idx = np.flatnonzero(labels == c)
            rng.shuffle(idx)
            props = rng.dirichlet([alpha] * n_nodes)
            cuts = (np.cumsum(props) * len(idx)).astype(int)[:-1]
            for node, part in enumerate(np.split(idx, cuts)):
                shards[node].append(part)
        parts = [np.concatenate(s) if s else np.empty(0, np.int64) for s in shards]
        if min(len(p) for p in parts) >= min_per_node:
            for p in parts:
                rng.shuffle(p)
            return parts
    raise RuntimeError(
        f"dirichlet_partition could not give every node >= {min_per_node} "
        f"samples (n={len(labels)}, nodes={n_nodes}, alpha={alpha})"
    )


def writer_partition(groups: np.ndarray, n_nodes: int,
                     seed: int = 0) -> list[np.ndarray]:
    """LEAF-style natural non-IID: whole writers (source groups) are
    assigned to nodes, so every node inherits its writers' class skew
    and style — the reference's FEMNIST is partitioned exactly this
    way (femnist.py: one LEAF writer bundle per participant)."""
    rng = np.random.default_rng(seed)
    writers = rng.permutation(np.unique(groups))
    if len(writers) < n_nodes:
        raise ValueError(
            f"writer partition needs >= 1 writer per node: "
            f"{len(writers)} writers < {n_nodes} nodes"
        )
    assignment = {w: i % n_nodes for i, w in enumerate(writers)}
    node_of = np.vectorize(assignment.get, otypes=[np.int64])(groups)
    return [np.flatnonzero(node_of == i) for i in range(n_nodes)]


def partition_indices(
    labels: np.ndarray, n_nodes: int, scheme: str = "iid", seed: int = 0,
    alpha: float = 0.5, groups: np.ndarray | None = None,
) -> list[np.ndarray]:
    """Factory by scheme name (DataConfig.partition)."""
    if scheme == "iid":
        return iid_partition(labels, n_nodes, seed)
    if scheme in ("sorted", "non-iid", "noniid"):
        return sorted_partition(labels, n_nodes, seed)
    if scheme == "dirichlet":
        return dirichlet_partition(labels, n_nodes, alpha=alpha, seed=seed)
    if scheme == "writer":
        if groups is None:
            raise ValueError(
                "partition='writer' needs per-sample writer ids "
                "(dataset provides none)"
            )
        return writer_partition(groups, n_nodes, seed)
    raise ValueError(f"unknown partition scheme {scheme!r}")


# --------------------------------------------------------------------
# Lazy cross-device partition (round 13): index-on-demand at N=10k+
# --------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ClientPartition:
    """Partition of a dataset across N clients WITHOUT N eager arrays.

    The whole allocation is two arrays: ``order`` (every sample index,
    grouped by owning client) and ``offsets`` (``[n_clients + 1]``
    group boundaries). A client's indices materialize only when that
    client is sampled into a round — ``client_indices(i)`` is an O(1)
    slice view — so a 1M-client federation costs O(n_samples) memory
    at setup instead of a million Python objects.
    """

    order: np.ndarray  # [n_samples] sample indices grouped by client
    offsets: np.ndarray  # [n_clients + 1] int64 group boundaries

    @property
    def n_clients(self) -> int:
        return len(self.offsets) - 1

    def client_indices(self, client: int) -> np.ndarray:
        """Sample indices owned by ``client`` (a view, not a copy)."""
        return self.order[self.offsets[client]:self.offsets[client + 1]]

    def sizes(self) -> np.ndarray:
        """Per-client shard sizes, ``[n_clients]`` — the data-size
        weights for weighted K-of-N sampling."""
        return np.diff(self.offsets)

    def take_sizes(self, client_ids: np.ndarray) -> np.ndarray:
        """Shard sizes of just ``client_ids`` (any shape), O(k) —
        the streamed round's per-cohort weight lookup (round 20). At
        N=100k..1M a full ``sizes()`` diff every round would touch the
        whole population to weight the K sampled clients."""
        ids = np.asarray(client_ids, np.int64)
        return self.offsets[ids + 1] - self.offsets[ids]


def _partition_from_assignment(node_of: np.ndarray,
                               n_clients: int) -> ClientPartition:
    order = np.argsort(node_of, kind="stable")
    counts = np.bincount(node_of, minlength=n_clients)
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    return ClientPartition(order=order, offsets=offsets)


def lazy_partition_indices(
    labels: np.ndarray, n_clients: int, scheme: str = "iid", seed: int = 0,
    alpha: float = 0.5, min_per_client: int = 1,
) -> ClientPartition:
    """:func:`partition_indices` twin for the cross-device regime:
    same allocation laws, returned as a :class:`ClientPartition`
    instead of N eager arrays.

    Within-client sample order is NOT shuffled here (for dirichlet it
    is label-grouped) — consumers that cap a shard must shuffle at
    materialization time (CrossDeviceData does, seeded per client),
    exactly the guard FederatedDataset.make applies eagerly.
    """
    n = len(labels)
    if scheme == "iid":
        rng = np.random.default_rng(seed)
        per = n // n_clients
        if per < min_per_client:
            raise ValueError(
                f"{n} samples over {n_clients} clients gives {per} "
                f"per client < min_per_client={min_per_client}"
            )
        order = rng.permutation(n)[: per * n_clients]
        offsets = (np.arange(n_clients + 1, dtype=np.int64) * per)
        return ClientPartition(order=order, offsets=offsets)
    if scheme in ("sorted", "non-iid", "noniid"):
        per = n // n_clients
        if per < min_per_client:
            raise ValueError(
                f"{n} samples over {n_clients} clients gives {per} "
                f"per client < min_per_client={min_per_client}"
            )
        order = np.argsort(labels, kind="stable")[: per * n_clients]
        offsets = (np.arange(n_clients + 1, dtype=np.int64) * per)
        return ClientPartition(order=order, offsets=offsets)
    if scheme == "dirichlet":
        rng = np.random.default_rng(seed)
        node_of = _dirichlet_assign(labels, n_clients, alpha, rng,
                                    min_per_node=min_per_client)
        return _partition_from_assignment(node_of, n_clients)
    raise ValueError(
        f"unknown cross-device partition scheme {scheme!r}; "
        "have ('iid', 'sorted', 'dirichlet')"
    )
