"""Federated dataset views: per-node shards and SPMD-stacked arrays.

The reference gives each node a ``LightningDataModule`` holding its
shard (mnist.py:100-118) and a DataLoader; here the whole federation's
data is materialized as **stacked arrays with a leading node axis** —
``x: [n_nodes, S, ...]`` — padded to a common shard size S with a
boolean sample mask. That leading axis is exactly what gets sharded
over the TPU mesh (or vmapped single-chip), so "every node trains an
epoch" is one XLA program instead of N DataLoader processes.

Per-node train/val split mirrors ``val_percent``
(mnist.py:56-59: batch 32, 10% val).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from p2pfl_tpu.config.schema import DataConfig
from p2pfl_tpu.datasets.partition import (
    ClientPartition,
    lazy_partition_indices,
    partition_indices,
)
from p2pfl_tpu.datasets.sources import DatasetSplits, get_dataset


@dataclasses.dataclass
class NodeData:
    """One node's shard — the per-node view the learner consumes."""

    x: np.ndarray
    y: np.ndarray
    x_val: np.ndarray
    y_val: np.ndarray

    @property
    def n_samples(self) -> int:  # FedAvg weight (lightninglearner get_num_samples)
        return len(self.x)


@dataclasses.dataclass
class FederatedDataset:
    """All shards of a federation, ragged (per-node) and stacked (SPMD)."""

    name: str
    num_classes: int
    input_shape: tuple[int, ...]
    nodes: list[NodeData]
    x_test: np.ndarray
    y_test: np.ndarray
    synthetic: bool = False

    @property
    def n_nodes(self) -> int:
        return len(self.nodes)

    def stacked(self, pad_to: int | None = None):
        """Pad each node's train shard to a common size and stack.

        Returns ``(x, y, mask, n_samples)`` with shapes
        ``[n, S, ...], [n, S], [n, S], [n]``. Padding rows are masked
        out of loss/metrics and, being weight-0, out of FedAvg.
        """
        sizes = [nd.n_samples for nd in self.nodes]
        s = pad_to or max(sizes)
        if s < max(sizes):
            raise ValueError(f"pad_to={s} < largest shard {max(sizes)}")
        n = self.n_nodes
        x = np.zeros((n, s) + self.input_shape, np.float32)
        y = np.zeros((n, s), np.int32)
        mask = np.zeros((n, s), bool)
        for i, nd in enumerate(self.nodes):
            k = nd.n_samples
            x[i, :k] = nd.x
            y[i, :k] = nd.y
            mask[i, :k] = True
        return x, y, mask, np.asarray(sizes, np.int32)

    @staticmethod
    def make(
        config: DataConfig,
        n_nodes: int,
        splits: DatasetSplits | None = None,
    ) -> "FederatedDataset":
        """Build federated shards per the DataConfig partition scheme."""
        if splits is None:
            sizes = (
                (config.synthetic_train, config.synthetic_test or 4000)
                if config.synthetic_train else None
            )
            splits = get_dataset(config.dataset, seed=config.seed,
                                 synthetic_sizes=sizes,
                                 profile=getattr(config, "surrogate_profile",
                                                 "hard"))
        parts = partition_indices(
            splits.y_train, n_nodes, scheme=config.partition,
            seed=config.seed, alpha=config.dirichlet_alpha,
            groups=splits.writer_train,
        )
        nodes = []
        for node_i, idx in enumerate(parts):
            # shuffle before capping/splitting — sorted/dirichlet
            # partitions return label-ordered indices, and an unshuffled
            # head slice would be single-label
            rng = np.random.default_rng(config.seed * 100003 + node_i)
            idx = rng.permutation(idx)
            if config.samples_per_node is not None:
                idx = idx[: config.samples_per_node]
            n_val = int(len(idx) * config.val_percent)
            val_idx, train_idx = idx[:n_val], idx[n_val:]
            nodes.append(
                NodeData(
                    x=splits.x_train[train_idx],
                    y=splits.y_train[train_idx],
                    x_val=splits.x_train[val_idx],
                    y_val=splits.y_train[val_idx],
                )
            )
        return FederatedDataset(
            name=splits.name,
            num_classes=splits.num_classes,
            input_shape=splits.input_shape,
            nodes=nodes,
            x_test=splits.x_test,
            y_test=splits.y_test,
            synthetic=splits.synthetic,
        )


@dataclasses.dataclass
class CrossDeviceData:
    """Cross-device dataset view (round 13): client-state-as-index.

    At N=10k–1M virtual clients the :class:`FederatedDataset` recipe —
    N eager ``NodeData`` shards — is both the setup bottleneck and a
    memory multiplier. Here a client IS its row in a lazy
    :class:`ClientPartition`; actual arrays materialize per round, only
    for the K sampled clients, at one FIXED shard size ``shard_size``
    so every round's cohort batch has identical shapes (one compiled
    round program, zero mid-run recompiles).

    No per-client val split: sampled clients are transient, so quality
    tracking is central (the shared test set), like every cross-device
    system FedJAX models.
    """

    name: str
    num_classes: int
    input_shape: tuple[int, ...]
    x_train: np.ndarray
    y_train: np.ndarray
    part: ClientPartition
    x_test: np.ndarray
    y_test: np.ndarray
    shard_size: int  # fixed pad target for every materialized shard
    seed: int = 0
    synthetic: bool = False

    @property
    def n_clients(self) -> int:
        return self.part.n_clients

    @property
    def client_sizes(self) -> np.ndarray:
        """Effective (cap-clamped) per-client sample counts — the
        FedAvg weights and the weighted-sampling distribution."""
        return np.minimum(self.part.sizes(), self.shard_size)

    def cohort_sizes(self, client_ids: np.ndarray) -> np.ndarray:
        """``client_sizes[client_ids]`` without the O(N) full-population
        diff — O(k) per round via ``ClientPartition.take_sizes`` (the
        streamed driver's weight lookup, round 20)."""
        return np.minimum(self.part.take_sizes(client_ids),
                          self.shard_size).astype(np.int32)

    def cohort_buffers(self, k: int):
        """Preallocated host buffers for a ``k``-client
        ``cohort_batch(out=...)`` — the streamed driver's double
        buffer: two of these per run bound the host-side cohort
        residency at exactly two cohorts regardless of N or C."""
        s = self.shard_size
        return (np.zeros((k, s) + self.input_shape, np.float32),
                np.zeros((k, s), np.int32),
                np.zeros((k, s), bool),
                np.zeros((k,), np.int32))

    def cohort_batch(self, client_ids: np.ndarray, out=None):
        """Materialize the sampled clients' shards, padded to
        ``shard_size``: ``(x [k,S,...], y [k,S], mask [k,S],
        n_samples [k])``. Each client's rows are drawn through a
        per-client seeded shuffle before the cap — dirichlet partitions
        are label-grouped, and an unshuffled head slice would be
        single-label (the FederatedDataset.make guard, applied lazily).

        ``out`` (round 20): an existing ``cohort_buffers(k)`` tuple to
        fill in place instead of allocating — the values written are
        identical either way, so streaming through reused buffers
        cannot change round math.
        """
        k = len(client_ids)
        s = self.shard_size
        if out is None:
            x, y, mask, sizes = self.cohort_buffers(k)
        else:
            x, y, mask, sizes = out
            x[:k] = 0.0
            y[:k] = 0
            mask[:k] = False
            sizes[:k] = 0
        for j, cid in enumerate(client_ids):
            idx = self.part.client_indices(int(cid))
            rng = np.random.default_rng(self.seed * 100003 + int(cid))
            idx = rng.permutation(idx)[:s]
            m = len(idx)
            x[j, :m] = self.x_train[idx]
            y[j, :m] = self.y_train[idx]
            mask[j, :m] = True
            sizes[j] = m
        return x, y, mask, sizes

    @staticmethod
    def make(config: DataConfig, n_clients: int) -> "CrossDeviceData":
        """Build the lazy N-client view per the DataConfig scheme.
        ``samples_per_node`` caps (and thereby fixes) the shard size;
        without it the pad target is the largest client shard."""
        sizes = (
            (config.synthetic_train, config.synthetic_test or 4000)
            if config.synthetic_train else None
        )
        splits = get_dataset(config.dataset, seed=config.seed,
                             synthetic_sizes=sizes,
                             profile=getattr(config, "surrogate_profile",
                                             "hard"))
        part = lazy_partition_indices(
            splits.y_train, n_clients, scheme=config.partition,
            seed=config.seed, alpha=config.dirichlet_alpha,
        )
        largest = int(part.sizes().max())
        shard = (
            min(config.samples_per_node, largest)
            if config.samples_per_node is not None else largest
        )
        return CrossDeviceData(
            name=splits.name,
            num_classes=splits.num_classes,
            input_shape=splits.input_shape,
            x_train=splits.x_train,
            y_train=splits.y_train,
            part=part,
            x_test=splits.x_test,
            y_test=splits.y_test,
            shard_size=shard,
            seed=config.seed,
            synthetic=splits.synthetic,
        )
