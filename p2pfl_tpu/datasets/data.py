"""Federated dataset views: per-node shards and SPMD-stacked arrays.

The reference gives each node a ``LightningDataModule`` holding its
shard (mnist.py:100-118) and a DataLoader; here the whole federation's
data is materialized as **stacked arrays with a leading node axis** —
``x: [n_nodes, S, ...]`` — padded to a common shard size S with a
boolean sample mask. That leading axis is exactly what gets sharded
over the TPU mesh (or vmapped single-chip), so "every node trains an
epoch" is one XLA program instead of N DataLoader processes.

Per-node train/val split mirrors ``val_percent``
(mnist.py:56-59: batch 32, 10% val).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from p2pfl_tpu.config.schema import DataConfig
from p2pfl_tpu.datasets.partition import partition_indices
from p2pfl_tpu.datasets.sources import DatasetSplits, get_dataset


@dataclasses.dataclass
class NodeData:
    """One node's shard — the per-node view the learner consumes."""

    x: np.ndarray
    y: np.ndarray
    x_val: np.ndarray
    y_val: np.ndarray

    @property
    def n_samples(self) -> int:  # FedAvg weight (lightninglearner get_num_samples)
        return len(self.x)


@dataclasses.dataclass
class FederatedDataset:
    """All shards of a federation, ragged (per-node) and stacked (SPMD)."""

    name: str
    num_classes: int
    input_shape: tuple[int, ...]
    nodes: list[NodeData]
    x_test: np.ndarray
    y_test: np.ndarray
    synthetic: bool = False

    @property
    def n_nodes(self) -> int:
        return len(self.nodes)

    def stacked(self, pad_to: int | None = None):
        """Pad each node's train shard to a common size and stack.

        Returns ``(x, y, mask, n_samples)`` with shapes
        ``[n, S, ...], [n, S], [n, S], [n]``. Padding rows are masked
        out of loss/metrics and, being weight-0, out of FedAvg.
        """
        sizes = [nd.n_samples for nd in self.nodes]
        s = pad_to or max(sizes)
        if s < max(sizes):
            raise ValueError(f"pad_to={s} < largest shard {max(sizes)}")
        n = self.n_nodes
        x = np.zeros((n, s) + self.input_shape, np.float32)
        y = np.zeros((n, s), np.int32)
        mask = np.zeros((n, s), bool)
        for i, nd in enumerate(self.nodes):
            k = nd.n_samples
            x[i, :k] = nd.x
            y[i, :k] = nd.y
            mask[i, :k] = True
        return x, y, mask, np.asarray(sizes, np.int32)

    @staticmethod
    def make(
        config: DataConfig,
        n_nodes: int,
        splits: DatasetSplits | None = None,
    ) -> "FederatedDataset":
        """Build federated shards per the DataConfig partition scheme."""
        if splits is None:
            sizes = (
                (config.synthetic_train, config.synthetic_test or 4000)
                if config.synthetic_train else None
            )
            splits = get_dataset(config.dataset, seed=config.seed,
                                 synthetic_sizes=sizes,
                                 profile=getattr(config, "surrogate_profile",
                                                 "hard"))
        parts = partition_indices(
            splits.y_train, n_nodes, scheme=config.partition,
            seed=config.seed, alpha=config.dirichlet_alpha,
            groups=splits.writer_train,
        )
        nodes = []
        for node_i, idx in enumerate(parts):
            # shuffle before capping/splitting — sorted/dirichlet
            # partitions return label-ordered indices, and an unshuffled
            # head slice would be single-label
            rng = np.random.default_rng(config.seed * 100003 + node_i)
            idx = rng.permutation(idx)
            if config.samples_per_node is not None:
                idx = idx[: config.samples_per_node]
            n_val = int(len(idx) * config.val_percent)
            val_idx, train_idx = idx[:n_val], idx[n_val:]
            nodes.append(
                NodeData(
                    x=splits.x_train[train_idx],
                    y=splits.y_train[train_idx],
                    x_val=splits.x_train[val_idx],
                    y_val=splits.y_train[val_idx],
                )
            )
        return FederatedDataset(
            name=splits.name,
            num_classes=splits.num_classes,
            input_shape=splits.input_shape,
            nodes=nodes,
            x_test=splits.x_test,
            y_test=splits.y_test,
            synthetic=splits.synthetic,
        )
