"""Data pipeline: dataset sources, federated partitioning, batching.

Successor of the reference's LightningDataModules
(fedstellar/learning/pytorch/{mnist,femnist,cifar10,syscall,wadi}/):
same dataset families, same partitioning semantics (contiguous IID
shards mnist.py:100-118; label-sorted non-IID mnist.py:76-83), plus
Dirichlet non-IID (BASELINE.json config 3).

Torch/torchvision-free. Real data is read from ``$P2PFL_TPU_DATA_DIR``
(npz or MNIST idx-ubyte) when present; otherwise each dataset has a
deterministic, *learnable* synthetic surrogate with identical shapes
and class counts, so development, CI, and benchmarks run in a
zero-egress environment (the reference instead downloads at first use,
e.g. femnist.py:24-77).
"""

from p2pfl_tpu.datasets.partition import (
    ClientPartition,
    dirichlet_partition,
    iid_partition,
    lazy_partition_indices,
    partition_indices,
    sorted_partition,
)
from p2pfl_tpu.datasets.sources import DATASETS, DatasetSplits, get_dataset
from p2pfl_tpu.datasets.data import (
    CrossDeviceData,
    FederatedDataset,
    NodeData,
)

__all__ = [
    "ClientPartition",
    "dirichlet_partition",
    "iid_partition",
    "lazy_partition_indices",
    "partition_indices",
    "sorted_partition",
    "DATASETS",
    "DatasetSplits",
    "get_dataset",
    "CrossDeviceData",
    "FederatedDataset",
    "NodeData",
]
