"""Status-record key three-way sync pass (round 22).

``p2pfl_tpu.utils.monitor.STATUS_KEYS`` is the authoritative registry
of every key a status publisher may emit and a renderer or health rule
may read. The failure mode it exists for is silent: rename a gauge on
the publisher side and the monitor column renders "-" forever, the
health rule never fires, and nothing crashes. This pass fails (exit 1)
when any side drifts — the benchkeys discipline applied to the status
plane:

1. a **consumed** key (best-effort AST scan of the status readers —
   utils/monitor.py, webapp.py, obs/health.py — for ``rec.get("k")`` /
   ``rec["k"]`` reads inside functions that take a status record,
   snapshot, or status list) is not registered: the renderer is
   waiting on a key no publisher is contracted to emit;
2. an **emitted** key (AST scan of the publishers — p2p/launch.py,
   federation/scenario.py, obs/devprof.py, obs/cost_model.py — over
   ``publish_status`` dict literals, ``_*_status`` helper and gauge
   functions, and ``*.crossdev_last[...]`` / ``*.devprof_last[...]``
   stores) is not registered;
3. a **registered** key is never emitted anywhere (the envelope keys
   node/ts/seq come from ``publish_status`` itself): dead registry
   entries rot the contract in the other direction.

Dynamic keys (loop variables, f-strings) are out of scope by design —
they must be registered by hand, which checks 1/3 then police.
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[2]

# publishers scanned for emitted keys
_EMIT_FILES = ("p2pfl_tpu/p2p/launch.py", "p2pfl_tpu/federation/scenario.py",
               "p2pfl_tpu/obs/devprof.py", "p2pfl_tpu/obs/cost_model.py")
# readers scanned for consumed keys
_READ_FILES = ("p2pfl_tpu/utils/monitor.py", "p2pfl_tpu/webapp.py",
               "p2pfl_tpu/obs/health.py")

# gauge builders whose dict literals feed status records without going
# through a ``_*_status``-named helper
_GAUGE_FNS = {"fit_gauges", "round_gauges", "memory_watermark"}
# attributes whose item-stores are splatted into status records
_LAST_ATTRS = {"crossdev_last", "devprof_last"}
# record-shaped parameters marking a function as a status reader
_READER_PARAMS = {"statuses", "snap", "rec"}
# receiver names bound to one status record inside a reader; bare
# subscript reads only count on ``rec`` (``r``/``s`` also name rendered
# row dicts, e.g. monitor's ``r["age"]``)
_REC_NAMES = {"rec", "r", "s", "status"}
_SUBSCRIPT_NAMES = {"rec"}
# keys publish_status/make_record stamp on every record itself
_ENVELOPE = {"node", "ts", "seq"}


def _dict_keys(d: ast.Dict) -> set[str]:
    return {k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}


def _is_emitter(fn: ast.AST) -> bool:
    name = getattr(fn, "name", "")
    return ((name.startswith("_") and name.endswith("_status"))
            or name in _GAUGE_FNS)


def emitted_keys(tree: ast.Module) -> set[str]:
    """Constant keys a publisher file can put on a status record."""
    keys: set[str] = set()
    for node in ast.walk(tree):
        # publish_status(dir, node, {<literal>...})
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "publish_status"):
            for arg in node.args:
                if isinstance(arg, ast.Dict):
                    keys |= _dict_keys(arg)
        # self.crossdev_last["k"] = ... / self.devprof_last["k"] = ...
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Attribute)
                        and tgt.value.attr in _LAST_ATTRS
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)):
                    keys.add(tgt.slice.value)
        # _*_status helpers and the devprof/cost_model gauge builders:
        # every dict literal and constant item-store inside builds (a
        # piece of) a status record
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and _is_emitter(node)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    keys |= _dict_keys(sub)
                elif isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if (isinstance(tgt, ast.Subscript)
                                and isinstance(tgt.value, ast.Name)
                                and isinstance(tgt.slice, ast.Constant)
                                and isinstance(tgt.slice.value, str)):
                            keys.add(tgt.slice.value)
    return keys


def consumed_keys(tree: ast.Module) -> set[str]:
    """Constant keys a reader file looks up on a status record:
    ``rec.get("k")`` / ``rec["k"]`` where the receiver is a record
    name inside a function that takes a record/snapshot/status list."""
    keys: set[str] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        if not (params & _READER_PARAMS):
            continue
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in _REC_NAMES
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                keys.add(node.args[0].value)
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in _SUBSCRIPT_NAMES
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                keys.add(node.slice.value)
    return keys


def main() -> int:
    sys.path.insert(0, str(REPO))
    from p2pfl_tpu.utils.monitor import STATUS_KEYS

    registered = set(STATUS_KEYS)
    emitted: set[str] = set()
    for rel in _EMIT_FILES:
        emitted |= emitted_keys(ast.parse((REPO / rel).read_text()))
    consumed: set[str] = set()
    for rel in _READ_FILES:
        consumed |= consumed_keys(ast.parse((REPO / rel).read_text()))

    unregistered_reads = sorted(consumed - registered)
    unregistered_emits = sorted(emitted - registered)
    never_emitted = sorted(registered - emitted - _ENVELOPE)
    for k in unregistered_reads:
        print(f"status reader consumes a key missing from STATUS_KEYS: {k!r}")
    for k in unregistered_emits:
        print(f"publisher emits a key missing from STATUS_KEYS: {k!r}")
    for k in never_emitted:
        print(f"STATUS_KEYS entry no publisher emits: {k!r}")
    if unregistered_reads or unregistered_emits or never_emitted:
        return 1
    print(f"ok: {len(registered)} registered status keys, "
          f"{len(emitted)} emitted and {len(consumed)} consumed "
          "all in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
