"""``python -m p2pfl_tpu.analysis`` — run every static pass.

Currently three passes, run in order with the combined exit code being
the max (healthcheck-style: 0 clean, 1 findings, 2 operational error):

1. **fedlint** over the given paths (default ``p2pfl_tpu/``);
2. **bench-keys** three-way sync (registry vs docs/perf.md vs the
   regression gate's HEADLINE keys);
3. **status-keys** three-way sync (monitor.STATUS_KEYS vs the
   publishers' emitted keys vs the renderer/health-rule reads).

Extra CLI flags are forwarded to fedlint (``--json`` etc. apply to the
lint pass only; the key passes keep their one-line text contracts).
"""

from __future__ import annotations

import sys

from p2pfl_tpu.analysis import benchkeys, fedlint, statuskeys


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    print("== fedlint ==")
    lint_rc = fedlint.main(argv)
    print("== bench-keys ==")
    bench_rc = benchkeys.main()
    print("== status-keys ==")
    status_rc = statuskeys.main()
    return max(lint_rc, bench_rc, status_rc)


if __name__ == "__main__":
    sys.exit(main())
