"""fedlint CLI.

Usage::

    python -m p2pfl_tpu.analysis.fedlint [paths...] [--json]
        [--baseline PATH | --no-baseline] [--write-baseline]
        [--rules rule1,rule2] [--root DIR]

Exit codes (healthcheck-style, for CI alongside ``healthcheck`` and
``check_bench_regress.py``): 0 = no unsuppressed findings, 1 =
findings, 2 = operational error (unparseable file, unknown rule).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from p2pfl_tpu.analysis.core import (
    BASELINE_NAME,
    load_baseline,
    run_paths,
    write_baseline,
)
from p2pfl_tpu.analysis.rules import ALL_RULES, RULES_BY_NAME

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m p2pfl_tpu.analysis.fedlint",
        description="AST lint for the federation's learned invariants")
    p.add_argument("paths", nargs="*", default=["p2pfl_tpu"],
                   help="files or directories to lint "
                        "(default: p2pfl_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the full result as JSON on stdout")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help=f"baseline file (default: <repo>/{BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file entirely")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "and exit 0")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--root", default=None, metavar="DIR",
                   help="directory findings paths are relative to "
                        "(default: the repo root)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    root = pathlib.Path(args.root) if args.root else _REPO_ROOT

    rules = ALL_RULES
    if args.rules:
        try:
            rules = tuple(RULES_BY_NAME[r.strip()]
                          for r in args.rules.split(","))
        except KeyError as e:
            print(f"fedlint: unknown rule {e.args[0]!r} "
                  f"(have: {', '.join(sorted(RULES_BY_NAME))})",
                  file=sys.stderr)
            return 2

    baseline_path = pathlib.Path(args.baseline) if args.baseline \
        else root / BASELINE_NAME
    try:
        entries = [] if (args.no_baseline or args.write_baseline) \
            else load_baseline(baseline_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"fedlint: bad baseline: {e}", file=sys.stderr)
        return 2

    # relative paths that don't exist in the cwd (e.g. the default
    # "p2pfl_tpu" when invoked from elsewhere) resolve against --root
    paths = []
    for s in args.paths:
        p = pathlib.Path(s)
        if not p.exists() and not p.is_absolute() and (root / p).exists():
            p = root / p
        paths.append(p)

    try:
        res = run_paths(paths, rules, root=root,
                        baseline_entries=entries)
    except FileNotFoundError as e:
        print(f"fedlint: no such path: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"fedlint: cannot parse {e.filename}:{e.lineno}: {e.msg}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(baseline_path, res.findings)
        print(f"fedlint: wrote {len(res.findings)} entr"
              f"{'y' if len(res.findings) == 1 else 'ies'} to "
              f"{baseline_path}")
        return 0

    if args.as_json:
        print(json.dumps(res.as_dict(), indent=1))
        return res.exit_code

    for f in res.findings:
        print(f.render())
    for e in res.stale_baseline:
        print(f"fedlint: note: stale baseline entry "
              f"{e['path']} ({e['rule']}): {e['code']!r} no longer "
              "matches — remove it")
    print(f"fedlint: {len(res.findings)} finding(s), "
          f"{len(res.pragma_suppressed)} pragma-suppressed, "
          f"{len(res.baselined)} baselined, "
          f"{len(res.stale_baseline)} stale baseline entr"
          f"{'y' if len(res.stale_baseline) == 1 else 'ies'}, "
          f"{res.files} file(s)")
    return res.exit_code


if __name__ == "__main__":
    sys.exit(main())
