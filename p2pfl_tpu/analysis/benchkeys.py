"""Bench-key / documentation three-way sync pass (round 9, moved
here in round 15 so ``python -m p2pfl_tpu.analysis`` is the single
entry point for every static pass; ``scripts/check_bench_keys.py``
remains as a thin shim).

``bench.BENCH_KEYS`` is the authoritative registry of every top-level
key the bench can emit, and docs/perf.md §10 is its human-facing
reference. The pass fails (exit 1) when any side drifts:

1. a registered key is not mentioned anywhere in docs/perf.md
   (substring check — the §10 tables name each key in backticks);
2. bench.py emits a literal key that is not registered — best-effort
   AST scan of the emission sites: dict literals handed to
   ``_part(...)``, dicts assigned/updated/returned through the
   accumulator names (``out``/``part``/``part_w``/``state``/``mp``)
   inside emitting functions, and constant-key subscript stores to
   those names. Dynamic keys (f-strings, loop variables) are out of
   scope by design — they must still be registered by hand, which
   direction 1 then keeps documented;
3. the regression gate's HEADLINE keys
   (scripts/check_bench_regress.py) are not all registered in
   ``BENCH_KEYS`` — the gate must never anchor on a key the bench
   cannot emit (round 12).
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[2]

# names bench.py's emitting functions accumulate result dicts into
_EMIT_NAMES = {"out", "part", "part_w", "state", "mp"}
# emitters not discoverable from ``_part(<fn>())`` call shapes: main()
# owns the envelope dict; _vit32_inprocess streams through a subprocess
_EXTRA_EMITTERS = {"main", "_vit32_inprocess"}


def _dict_keys(d: ast.Dict) -> set[str]:
    return {k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}


def _emitting_functions(tree: ast.Module) -> set[str]:
    """``_phase_*`` children plus any function whose return value is
    passed straight to ``_part``."""
    names = set(_EXTRA_EMITTERS)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("_phase_"):
                names.add(node.name)
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "_part"):
            for arg in node.args:
                if (isinstance(arg, ast.Call)
                        and isinstance(arg.func, ast.Name)):
                    names.add(arg.func.id)
    return names


def emitted_literal_keys(tree: ast.Module) -> set[str]:
    emitters = _emitting_functions(tree)
    keys: set[str] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name not in emitters:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Subscript)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id in _EMIT_NAMES
                            and isinstance(tgt.slice, ast.Constant)
                            and isinstance(tgt.slice.value, str)):
                        keys.add(tgt.slice.value)
                    elif (isinstance(tgt, ast.Name)
                            and tgt.id in _EMIT_NAMES
                            and isinstance(node.value, ast.Dict)):
                        keys |= _dict_keys(node.value)
            elif isinstance(node, ast.AnnAssign):
                if (isinstance(node.target, ast.Name)
                        and node.target.id in _EMIT_NAMES
                        and isinstance(node.value, ast.Dict)):
                    keys |= _dict_keys(node.value)
            elif isinstance(node, ast.Return):
                vals = ([node.value] if isinstance(node.value, ast.Dict)
                        else node.value.values
                        if isinstance(node.value, ast.BoolOp) else [])
                for v in vals:
                    if isinstance(v, ast.Dict):
                        keys |= _dict_keys(v)
            elif isinstance(node, ast.Call):
                f = node.func
                args = [a for a in node.args if isinstance(a, ast.Dict)]
                if isinstance(f, ast.Name) and f.id == "_part":
                    for a in args:
                        keys |= _dict_keys(a)
                elif (isinstance(f, ast.Attribute) and f.attr == "update"
                        and isinstance(f.value, ast.Name)
                        and f.value.id in _EMIT_NAMES):
                    for a in args:
                        keys |= _dict_keys(a)
    return keys


def main() -> int:
    sys.path.insert(0, str(REPO))
    sys.path.insert(0, str(REPO / "scripts"))
    import bench

    registered = set(bench.BENCH_KEYS)
    doc = (REPO / "docs" / "perf.md").read_text()
    tree = ast.parse((REPO / "bench.py").read_text())
    emitted = emitted_literal_keys(tree)

    import check_bench_regress

    undocumented = sorted(k for k in registered if k not in doc)
    unregistered = sorted(emitted - registered)
    ungated = sorted(set(check_bench_regress.HEADLINE) - registered)
    for k in undocumented:
        print(f"BENCH_KEYS entry not documented in docs/perf.md: {k!r}")
    for k in unregistered:
        print(f"bench.py emits a key missing from BENCH_KEYS: {k!r}")
    for k in ungated:
        print("check_bench_regress.HEADLINE key missing from "
              f"BENCH_KEYS: {k!r}")
    if undocumented or unregistered or ungated:
        return 1
    print(f"ok: {len(registered)} registered keys documented, "
          f"{len(emitted)} literal emission keys all registered, "
          f"{len(check_bench_regress.HEADLINE)} regression-gate keys "
          "registered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
