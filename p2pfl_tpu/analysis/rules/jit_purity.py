"""jit-purity — host side effects inside traced functions.

A function handed to ``jax.jit`` / ``lax.scan`` / ``shard_map`` is
traced once and replayed as XLA; host-side work inside it either fails
at trace time (``np.asarray`` on a tracer) or silently runs exactly
once and never again (``print``, counter bumps, attr mutation). The
rule finds functions that are jit-compiled — by decorator or by being
passed to a tracing entry point — and flags:

- ``print(...)`` (use ``jax.debug.print`` for traced values),
- NumPy host-transfer calls (``np.asarray``/``np.array``/``np.save``/
  ...) which force a device sync or fail on tracers,
- host RNG calls (``np.random.*`` / ``random.*``): the draw happens
  ONCE at trace time and bakes a constant into the compiled program —
  every replay reuses the same "random" bits, which silently destroys
  DP noise and attack-noise semantics. Use ``jax.random`` with an
  explicit key (``jax.random.normal(key, ...)`` is pure and replays
  fresh per key),
- tracer/flight counter calls (``.count``/``.high_water``/``.span``),
- mutation of non-local state (attribute stores, subscript stores to
  names not bound in the function — Pallas ``o_ref[...] = x`` stays
  clean because refs are parameters).
"""

from __future__ import annotations

import ast
from typing import Iterator

from p2pfl_tpu.analysis.rules._util import (
    FUNC_DEFS,
    Rule,
    dotted_name,
    local_names,
    tail_name,
    walk_function_body,
)

NAME = "jit-purity"

_TRACE_ENTRY_TAILS = {"jit", "pjit", "shard_map", "scan", "vmap", "pmap",
                      "fori_loop", "while_loop"}
_NP_HOST_TAILS = {"asarray", "array", "copy", "save", "load", "frombuffer",
                  "savez"}
_COUNTER_TAILS = {"count", "high_water", "span"}


def _decorator_traces(dec: ast.AST) -> bool:
    if isinstance(dec, (ast.Name, ast.Attribute)):
        return tail_name(dec) in {"jit", "pjit", "shard_map"}
    if isinstance(dec, ast.Call):
        if tail_name(dec.func) in {"jit", "pjit", "shard_map"}:
            return True
        if tail_name(dec.func) == "partial" and dec.args:
            return tail_name(dec.args[0]) in {"jit", "pjit", "shard_map"}
    return False


def _jitted_functions(ctx) -> list[ast.AST]:
    by_name: dict[str, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, FUNC_DEFS):
            by_name[node.name] = node
    traced: dict[int, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, FUNC_DEFS):
            if any(_decorator_traces(d) for d in node.decorator_list):
                traced[id(node)] = node
        elif isinstance(node, ast.Call):
            tail = tail_name(node.func)
            if tail not in _TRACE_ENTRY_TAILS:
                continue
            # `scan` etc. must come off lax/jax to count
            dn = dotted_name(node.func)
            if tail in {"scan", "fori_loop", "while_loop"} and not (
                    "lax" in dn.split(".")):
                continue
            for arg in (*node.args, *(kw.value for kw in node.keywords)):
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    fn = by_name[arg.id]
                    traced[id(fn)] = fn
    return list(traced.values())


def _impurity(node: ast.AST, locals_: set[str]) -> str | None:
    if isinstance(node, ast.Call):
        dn = dotted_name(node.func)
        if dn == "print":
            return ("print() runs once at trace time, never per step; "
                    "use jax.debug.print")
        if dn.startswith(("np.random.", "numpy.random.", "random.")):
            # checked BEFORE the host-transfer tails: np.random draws
            # once at trace time and bakes a CONSTANT into the compiled
            # program — fatal for DP noise, silent for everything else.
            # "jax.random.normal" never matches ("jax." prefix).
            return (f"'{dn}' draws host randomness once at trace time "
                    "and replays the same bits forever; use jax.random "
                    "with an explicit key")
        if (dn.startswith(("np.", "numpy."))
                and tail_name(node.func) in _NP_HOST_TAILS):
            return (f"'{dn}' forces a host transfer (or fails on a "
                    "tracer); stay in jnp inside traced code")
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _COUNTER_TAILS
                and not dn.startswith(("jnp.", "jax.", "lax."))):
            return (f"tracer call '.{node.func.attr}()' fires once at "
                    "trace, not per execution; record metrics outside "
                    "the jitted function")
    elif isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Attribute):
                return ("attribute mutation inside a traced function "
                        "happens once at trace time and is invisible "
                        "to later calls")
            if (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id not in locals_):
                return (f"subscript store to non-local "
                        f"'{t.value.id}' inside a traced function "
                        "mutates host state at trace time only; return "
                        "the value instead")
    return None


def _check(ctx) -> Iterator:
    for fn in _jitted_functions(ctx):
        locals_ = local_names(fn)
        for node in walk_function_body(fn, skip_nested=True):
            reason = _impurity(node, locals_)
            if reason is not None:
                yield ctx.finding(
                    NAME, node,
                    f"host side effect in jit-compiled "
                    f"'{fn.name}': {reason}")


JIT_PURITY = Rule(
    name=NAME,
    incident=("host side effects inside jitted/scanned functions either "
              "fail at trace time or silently run once at trace and "
              "never again — metrics recorded this way read as frozen"),
    check=_check,
)
