"""donation-safety — the round-9 resume bug class.

Two sub-checks:

1. **read-after-donate**: a binding passed at a donated position of a
   ``jax.jit(..., donate_argnums=...)`` callee is dead after the call;
   reading it again dereferences a freed device buffer.
2. **non-owning seed**: leaves produced by ``msgpack_restore`` /
   ``from_state_dict`` / ``np.frombuffer`` are views of the serialized
   blob's bytes. Handing them to ``jnp.asarray`` / ``jnp.array``
   (without ``copy=True``) / ``jax.device_put`` — or straight into a
   donating callee — can alias host memory the blob owner is free to
   reuse; the read is heap-layout-dependent garbage. The fix is an
   owning construction: ``jnp.array(x, copy=True)`` / ``np.array(x)``
   / ``np.copy(x)``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p2pfl_tpu.analysis.rules._util import (
    FUNC_DEFS,
    Rule,
    dotted_name,
    enclosing_function,
    int_constants,
    tail_name,
)

NAME = "donation-safety"

#: calls whose result is a non-owning view of serialized bytes
_NON_OWNING_PRODUCERS = {"msgpack_restore", "from_state_dict", "frombuffer"}

#: calls that propagate ownership status from arg to result
_PASSTHROUGH = {"leaves", "tree_leaves", "flatten", "tree_flatten",
                "list", "tuple", "sorted", "reversed"}

#: device-transfer sinks that may alias a host view (jnp.array is only
#: a sink without an explicit copy=True)
_ALIASING_SINKS = {"jnp.asarray", "jax.numpy.asarray", "jax.device_put",
                   "jnp.array", "jax.numpy.array"}

_OWNING_TAILS = {"copy", "ascontiguousarray"}


def _is_owning_construction(call: ast.Call) -> bool:
    """``np.array(x)`` / ``*.array(x, copy=True)`` / ``np.copy`` /
    ``jnp.copy`` / ``ascontiguousarray`` — produces an owning buffer."""
    tail = tail_name(call.func)
    if tail in _OWNING_TAILS:
        return True
    if tail == "array":
        dn = dotted_name(call.func)
        if dn.startswith(("np.", "numpy.")):
            # numpy's default is copy=True; only copy=False opts out
            for kw in call.keywords:
                if kw.arg == "copy" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is True):
                    return False
            return True
        for kw in call.keywords:
            if (kw.arg == "copy" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True):
                return True
    return False


def _donating_bindings(tree: ast.AST) -> dict[str, set[int]]:
    """Names bound (by assignment or decorator) to a jit with
    ``donate_argnums`` -> the set of donated positional indices."""
    out: dict[str, set[int]] = {}

    def donated(call: ast.Call) -> set[int]:
        if tail_name(call.func) not in {"jit", "pjit"}:
            return set()
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                return set(int_constants(kw.value))
        return set()

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            idx = donated(node.value)
            # partial(jax.jit, donate_argnums=...)(fn) style
            if not idx and isinstance(node.value.func, ast.Call):
                idx = donated(node.value.func)
            if idx:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = idx
        elif isinstance(node, FUNC_DEFS):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    idx = donated(dec)
                    # @partial(jax.jit, donate_argnums=...)
                    if not idx and tail_name(dec.func) == "partial" and dec.args:
                        inner = ast.Call(func=dec.args[0], args=[],
                                         keywords=dec.keywords)
                        idx = donated(inner)
                    if idx:
                        out[node.name] = idx
    return out


def _name_nodes(fn: ast.AST, ident: str) -> list[ast.Name]:
    nodes = [n for n in ast.walk(fn)
             if isinstance(n, ast.Name) and n.id == ident]
    nodes.sort(key=lambda n: (n.lineno, n.col_offset))
    return nodes


def _check_read_after_donate(ctx, donors: dict[str, set[int]]
                             ) -> Iterator:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and tail_name(node.func) in donors):
            continue
        scope = enclosing_function(ctx, node) or ctx.tree
        for i in donors[tail_name(node.func)]:
            if not (i < len(node.args)
                    and isinstance(node.args[i], ast.Name)):
                continue
            ident = node.args[i].id
            # a store on the call's own line (`fed, m = step(fed, ...)`)
            # rebinds the name at runtime right after the donation
            stored = False
            for name in _name_nodes(scope, ident):
                if name.lineno < node.lineno:
                    continue
                owner = enclosing_function(ctx, name) or ctx.tree
                if owner is not scope:
                    continue  # a different scope's binding of the name
                if isinstance(name.ctx, ast.Store):
                    stored = True
                elif name.lineno == node.lineno:
                    continue  # the donated argument itself
                elif not stored:
                    yield ctx.finding(
                        NAME, name,
                        f"'{ident}' was donated to "
                        f"'{tail_name(node.func)}' on line "
                        f"{node.lineno} and must not be read afterwards "
                        "(the device buffer is freed); rebind the "
                        "result or pass a copy")


class _TaintScan:
    """Order-sensitive scan of one scope tracking names bound to
    non-owning (view) buffers."""

    def __init__(self, ctx, donors: dict[str, set[int]]):
        self.ctx = ctx
        self.donors = donors
        self.tainted: set[str] = set()
        self.findings: list = []

    # -- taint queries -------------------------------------------------
    def _expr_tainted(self, node: ast.AST, extra: set[str] = frozenset()
                      ) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted or node.id in extra
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self._expr_tainted(node.value, extra)
        if isinstance(node, ast.Call):
            tail = tail_name(node.func)
            if tail in _NON_OWNING_PRODUCERS:
                return True
            if _is_owning_construction(node):
                return False
            if tail in _PASSTHROUGH:
                return any(self._expr_tainted(a, extra) for a in node.args)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._expr_tainted(e, extra) for e in node.elts)
        return False

    # -- stores --------------------------------------------------------
    def _store(self, target: ast.AST, taint: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if taint
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._store(e, taint)

    def _store_zip(self, target: ast.AST, zip_call: ast.Call) -> None:
        """``for t, r in zip(a, b)``: taint only the targets aligned
        with tainted zip arguments — flagging ``t`` too was the false
        positive that would hit checkpoint's restore loop."""
        if (isinstance(target, (ast.Tuple, ast.List))
                and len(target.elts) == len(zip_call.args)):
            for elt, arg in zip(target.elts, zip_call.args):
                self._store(elt, self._expr_tainted(arg))
        else:
            self._store(target, any(self._expr_tainted(a)
                                    for a in zip_call.args))

    # -- sinks ---------------------------------------------------------
    def _scan_expr(self, node: ast.AST | None,
                   extra: set[str] = frozenset()) -> None:
        if node is None:
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            # comprehension targets inherit taint from their iterables
            # (with zip positional alignment, as in the For handler)
            comp_extra = set(extra)
            for gen in node.generators:
                it = gen.iter
                self._scan_expr(it, extra)
                if (isinstance(it, ast.Call)
                        and tail_name(it.func) == "zip"
                        and isinstance(gen.target, ast.Tuple)
                        and len(gen.target.elts) == len(it.args)):
                    for elt, arg in zip(gen.target.elts, it.args):
                        if (isinstance(elt, ast.Name)
                                and self._expr_tainted(arg, extra)):
                            comp_extra.add(elt.id)
                elif (self._expr_tainted(it, extra)
                      and isinstance(gen.target, ast.Name)):
                    comp_extra.add(gen.target.id)
                for cond in gen.ifs:
                    self._scan_expr(cond, comp_extra)
            for part in ("elt", "key", "value"):
                self._scan_expr(getattr(node, part, None), comp_extra)
            return
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn in _ALIASING_SINKS and not _is_owning_construction(node):
                for arg in node.args[:1]:
                    if self._expr_tainted(arg, extra):
                        self.findings.append(self.ctx.finding(
                            NAME, node,
                            f"'{dn}' over a non-owning deserialized "
                            "buffer may alias freed host memory; build "
                            "an owning copy first (jnp.array(x, "
                            "copy=True) / np.array(x))"))
            tail = tail_name(node.func)
            if tail in self.donors:
                for i in self.donors[tail]:
                    if (i < len(node.args)
                            and self._expr_tainted(node.args[i], extra)):
                        self.findings.append(self.ctx.finding(
                            NAME, node,
                            f"non-owning deserialized buffer donated to "
                            f"'{tail}' (donate_argnums={i}); donating a "
                            "view of the blob bytes is the round-9 "
                            "garbage-read bug — copy it first"))
        for child in ast.iter_child_nodes(node):
            self._scan_expr(child, extra)

    # -- statements ----------------------------------------------------
    def run(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, FUNC_DEFS + (ast.ClassDef,)):
            return  # nested scopes scanned on their own
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is None:
                return
            self._scan_expr(value)
            taint = self._expr_tainted(value)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                self._store(t, taint)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter)
            it = stmt.iter
            if isinstance(it, ast.Call) and tail_name(it.func) == "zip":
                self._store_zip(stmt.target, it)
            else:
                self._store(stmt.target, self._expr_tainted(it))
            self.run(stmt.body)
            self.run(stmt.orelse)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test)
            self.run(stmt.body)
            self.run(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
            self.run(stmt.body)
        elif isinstance(stmt, ast.Try):
            self.run(stmt.body)
            for h in stmt.handlers:
                self.run(h.body)
            self.run(stmt.orelse)
            self.run(stmt.finalbody)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_expr(child)


def _check(ctx) -> Iterator:
    donors = _donating_bindings(ctx.tree)
    yield from _check_read_after_donate(ctx, donors)
    scopes = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                           if isinstance(n, FUNC_DEFS)]
    for scope in scopes:
        scan = _TaintScan(ctx, donors)
        scan.run(scope.body)
        yield from scan.findings


DONATION_SAFETY = Rule(
    name=NAME,
    incident=("round-9: msgpack-restored leaves (non-owning views of the "
              "checkpoint blob) were handed to a donate_argnums callee — "
              "a heap-layout-dependent garbage read on resume"),
    check=_check,
)
