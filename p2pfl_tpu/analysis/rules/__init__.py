"""fedlint rule registry.

Each rule module exports one rule object with ``name``, ``incident``
(the real defect it encodes — see docs/analysis.md for the catalog)
and ``check(ctx) -> Iterator[Finding]``. Order is presentation-only;
findings are re-sorted by location.
"""

from p2pfl_tpu.analysis.rules.artifacts import ATOMIC_ARTIFACT
from p2pfl_tpu.analysis.rules.asynchrony import ASYNC_HYGIENE
from p2pfl_tpu.analysis.rules.donation import DONATION_SAFETY
from p2pfl_tpu.analysis.rules.jit_purity import JIT_PURITY
from p2pfl_tpu.analysis.rules.recompile import RECOMPILE_HAZARD

ALL_RULES = (
    DONATION_SAFETY,
    RECOMPILE_HAZARD,
    ASYNC_HYGIENE,
    JIT_PURITY,
    ATOMIC_ARTIFACT,
)

RULES_BY_NAME = {r.name: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_NAME"]
