"""async-hygiene — the round-11 prober class.

Two failure modes from the same incident family:

1. **blocking call on the event loop**: the round-11 prober killed
   healthy lanes because CPU/IO-bound work inside ``async def`` starved
   the heartbeat coroutines past their eviction deadline. Flagged:
   ``time.sleep``, synchronous file IO (``open``,
   ``Path.read_text``/``write_text``/``read_bytes``/``write_bytes``),
   ``subprocess.run``, and blocking ``Future.result()``. Use
   ``await asyncio.sleep``, ``run_in_executor``, or move the work to a
   worker thread.
2. **fire-and-forget task**: a bare ``asyncio.create_task(...)``
   statement keeps no reference — the task can be garbage-collected
   mid-flight, and its exception surfaces only at interpreter exit.
   Keep a reference and consume the exception in a done-callback (see
   ``Node._track_task``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from p2pfl_tpu.analysis.rules._util import (
    Rule,
    dotted_name,
    tail_name,
    walk_function_body,
)

NAME = "async-hygiene"

_SYNC_IO_TAILS = {"read_text", "write_text", "read_bytes", "write_bytes"}
_SPAWN_TAILS = {"create_task", "ensure_future"}


def _blocking_reason(call: ast.Call) -> str | None:
    dn = dotted_name(call.func)
    tail = tail_name(call.func)
    if dn == "time.sleep":
        return "time.sleep blocks the event loop; use await asyncio.sleep"
    if dn == "open" or dn.endswith("subprocess.run") or dn == "subprocess.run":
        return (f"'{dn}' is synchronous IO on the event loop; use "
                "run_in_executor or a worker thread")
    if tail in _SYNC_IO_TAILS and isinstance(call.func, ast.Attribute):
        return (f"'.{tail}()' is synchronous file IO on the event loop; "
                "use run_in_executor or a worker thread")
    if (tail == "result" and isinstance(call.func, ast.Attribute)
            and not call.args):
        return ("'.result()' blocks until the future resolves; await it "
                "instead")
    return None


def _check(ctx) -> Iterator:
    for node in ast.walk(ctx.tree):
        # blocking calls, scoped to the async function's own statements
        # (nested sync defs run off-loop via executors; nested async
        # defs get their own visit from this walk)
        if isinstance(node, ast.AsyncFunctionDef):
            for sub in walk_function_body(node, skip_nested=True):
                if isinstance(sub, ast.Call):
                    reason = _blocking_reason(sub)
                    if reason is not None:
                        yield ctx.finding(
                            NAME, sub,
                            f"blocking call in async def "
                            f"'{node.name}': {reason} (the round-11 "
                            "prober starved heartbeats this way)")
        # fire-and-forget tasks, anywhere
        elif (isinstance(node, ast.Expr)
              and isinstance(node.value, ast.Call)
              and tail_name(node.value.func) in _SPAWN_TAILS):
            yield ctx.finding(
                NAME, node.value,
                f"fire-and-forget '{tail_name(node.value.func)}': no "
                "reference is kept, so the task can be GC'd mid-flight "
                "and its exception is never retrieved; keep a reference "
                "and consume the exception in a done-callback")


ASYNC_HYGIENE = Rule(
    name=NAME,
    incident=("round-11: a CPU-bound fit inside an async prober blocked "
              "the event loop, heartbeats missed their deadline, and "
              "healthy peers were evicted; fire-and-forget probe tasks "
              "also swallowed the evidence"),
    check=_check,
)
