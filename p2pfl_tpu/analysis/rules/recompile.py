"""recompile-hazard — the §7b storm class.

The §7b postmortem counted ~450 mid-round XLA compiles (~32% of round
wall time). Three mechanical signatures cover what actually happened:

1. **shape-varying stack in a loop**: ``jnp.stack``/``jnp.concatenate``
   over a Python list inside a per-round/per-message loop retraces XLA
   once per distinct list length. Hoist to a fixed-width buffer or pad
   to a bucketed shape (see ``p2p/session.py``'s ``tree_stack``, which
   runs once per aggregation, not per message).
2. **jit in a loop**: calling ``jax.jit(...)`` inside a for/while body
   builds a fresh callable per iteration — every call is a cache miss.
   Bind the jitted function once, outside the loop.
3. **ungated f-string counter key**: ``count(f"...{x}")`` /
   ``high_water(f"...")`` allocates a fresh key string per frame even
   when tracing is off. Hot paths must gate under
   ``if tracer.enabled:`` so the disabled path is allocation-free.
4. **device_put in a loop** (round 20): ``jax.device_put`` inside a
   per-round/per-cohort loop body serializes a host→device copy into
   every iteration — the transfer rides the critical path instead of
   overlapping the previous step's compute. Hoist the placement out of
   the loop, or route it through the sanctioned double-buffered
   prefetch seam (``scenario.py``'s streamed ``gather_put``, which
   carries the line pragma) so the copy for cohort t+1 runs while
   cohort t trains.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p2pfl_tpu.analysis.rules._util import (
    Rule,
    dotted_name,
    inside_loop,
    tail_name,
)

NAME = "recompile-hazard"

_STACK_TAILS = {"stack", "vstack", "hstack", "concatenate"}
_JNP_BASES = ("jnp.", "jax.numpy.")
_COUNTER_TAILS = {"count", "high_water"}


def _is_jnp(func: ast.AST) -> bool:
    dn = dotted_name(func)
    return dn.startswith(_JNP_BASES)


def _enabled_gated(ctx, node: ast.AST) -> bool:
    """True when ``node`` sits under an ``if <tracer>.enabled:`` (or
    equivalent) guard."""
    for parent in ctx.parents(node):
        if isinstance(parent, (ast.If, ast.IfExp)):
            for sub in ast.walk(parent.test):
                if isinstance(sub, ast.Attribute) and sub.attr == "enabled":
                    return True
                if isinstance(sub, ast.Name) and sub.id == "enabled":
                    return True
        if isinstance(parent, ast.BoolOp):
            # `tr.enabled and tr.count(...)` short-circuit style
            for sub in ast.walk(parent):
                if isinstance(sub, ast.Attribute) and sub.attr == "enabled":
                    return True
    return False


def _has_dynamic_fstring(call: ast.Call) -> bool:
    for arg in call.args:
        if isinstance(arg, ast.JoinedStr) and any(
                isinstance(v, ast.FormattedValue) for v in arg.values):
            return True
    return False


def _check(ctx) -> Iterator:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = tail_name(node.func)
        if (tail in _STACK_TAILS and _is_jnp(node.func)
                and inside_loop(ctx, node)):
            yield ctx.finding(
                NAME, node,
                f"'{dotted_name(node.func)}' inside a loop retraces XLA "
                "once per distinct input length (the §7b storm); hoist "
                "out of the loop or pad to a bucketed shape")
        elif (tail in {"jit", "pjit"}
              and dotted_name(node.func) in {"jit", "pjit", "jax.jit",
                                             "jax.pjit"}
              and inside_loop(ctx, node)):
            yield ctx.finding(
                NAME, node,
                "jax.jit called inside a loop builds a fresh callable "
                "per iteration — every call misses the compile cache; "
                "bind the jitted function once outside the loop")
        elif (tail in _COUNTER_TAILS and _has_dynamic_fstring(node)
              and not _enabled_gated(ctx, node)):
            yield ctx.finding(
                NAME, node,
                f"f-string key for '{tail}' allocates per call even "
                "with tracing off; gate the call under "
                "'if tracer.enabled:' so the disabled path is "
                "allocation-free")
        elif (tail == "device_put"
              and dotted_name(node.func) in {"device_put",
                                             "jax.device_put"}
              and inside_loop(ctx, node)):
            yield ctx.finding(
                NAME, node,
                "jax.device_put inside a loop serializes a host->device "
                "copy into every iteration; hoist the placement out of "
                "the loop or route it through the double-buffered "
                "prefetch seam so the copy overlaps compute")


RECOMPILE_HAZARD = Rule(
    name=NAME,
    incident=("§7b: ~450 mid-round XLA compiles (~32% of wall) from "
              "shape-varying stacks in the socket hot path, plus "
              "per-frame f-string counter keys when tracing was off"),
    check=_check,
)
