"""atomic-artifact — the round-12/14 torn-read contracts.

Every artifact another process tails live (status JSON, checkpoints,
flight recordings, metrics, bench results, health probes) must be
published atomically: write to a ``tmp`` sibling, fsync, then
``os.replace`` — or append exactly one complete ``write()`` per record
to an ``"a"``-mode stream. A plain ``write_text``/``open(..., "w")``
straight onto the published path gives a tailer a window where the
file is empty or half-written; round-12 (metrics.jsonl) and round-14
(checkpoint manifests) both shipped fixes for exactly that.

Scoping keeps this precise: only write-sites whose source text looks
like a published artifact (status/checkpoint/flight/metrics/trace/
bench/health or a ``.json``/``.jsonl`` suffix) are candidates; writes
mentioning ``tmp`` and writes inside functions that also call
``os.replace``/``rename`` (i.e. the atomic pattern itself) are exempt,
as is append mode.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p2pfl_tpu.analysis.rules._util import (
    FUNC_DEFS,
    Rule,
    enclosing_function,
    tail_name,
)

NAME = "atomic-artifact"

_ARTIFACT_MARKERS = ("status", "checkpoint", "flight", "metrics", "trace",
                     "bench", "health", ".json", ".jsonl")
_WRITE_TAILS = {"write_text", "write_bytes"}


def _artifact_segment(seg: str) -> bool:
    low = seg.lower()
    if "tmp" in low:
        return False
    return any(marker in low for marker in _ARTIFACT_MARKERS)


def _scope_has_replace(ctx, node: ast.AST) -> bool:
    scope = enclosing_function(ctx, node) or ctx.tree
    for sub in ast.walk(scope):
        if (isinstance(sub, ast.Call)
                and tail_name(sub.func) in {"replace", "rename"}):
            return True
    return False


def _open_write_mode(call: ast.Call) -> str | None:
    """The mode of an ``open``-family call when it writes ('' when the
    call reads or the mode is dynamic)."""
    if tail_name(call.func) != "open":
        return None
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None  # default 'r'
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value if any(c in mode.value for c in "wax") else None
    return None


def _check(ctx) -> Iterator:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = tail_name(node.func)
        if tail in _WRITE_TAILS and isinstance(node.func, ast.Attribute):
            seg = ctx.segment(node)
            if _artifact_segment(seg) and not _scope_has_replace(ctx, node):
                yield ctx.finding(
                    NAME, node,
                    f"'.{tail}()' publishes an artifact in place — a "
                    "live tailer can see it empty or torn; write to a "
                    "tmp sibling, fsync, then os.replace (cf. "
                    "checkpoint._atomic_write_bytes)")
        else:
            mode = _open_write_mode(node)
            if mode is None or "a" in mode:
                continue  # reads and appends are fine
            seg = ctx.segment(node)
            if _artifact_segment(seg) and not _scope_has_replace(ctx, node):
                yield ctx.finding(
                    NAME, node,
                    f"open(..., {mode!r}) truncates a published "
                    "artifact in place — a live tailer can see it "
                    "empty or torn; write to a tmp sibling, fsync, "
                    "then os.replace, or append complete records in "
                    "'a' mode")


ATOMIC_ARTIFACT = Rule(
    name=NAME,
    incident=("round-12/round-14: live tailers (dashboard, resume) read "
              "half-written metrics.jsonl lines and checkpoint "
              "manifests; the fix was tmp+fsync+os.replace and "
              "single-write append contracts"),
    check=_check,
)
