"""Small AST helpers shared by the fedlint rules."""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Iterator

FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass(frozen=True)
class Rule:
    """One fedlint rule: a name, the incident it encodes, and a
    checker run once per file."""

    name: str
    incident: str
    check: Callable  # (FileContext) -> Iterator[Finding]


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def tail_name(node: ast.AST) -> str:
    """The last component of a call target: ``flax_ser.msgpack_restore``
    -> ``msgpack_restore``; plain names pass through."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def enclosing_function(ctx, node: ast.AST):
    """Nearest enclosing (Async)FunctionDef, or None at module scope."""
    for parent in ctx.parents(node):
        if isinstance(parent, FUNC_DEFS):
            return parent
    return None


def inside_loop(ctx, node: ast.AST, stop_at: ast.AST | None = None) -> bool:
    """True when ``node`` sits inside a for/while body (not crossing
    a nested function boundary; ``stop_at`` bounds the walk)."""
    for parent in ctx.parents(node):
        if parent is stop_at or isinstance(parent, FUNC_DEFS):
            return False
        if isinstance(parent, (ast.For, ast.AsyncFor, ast.While)):
            return True
    return False


def walk_function_body(fn: ast.AST,
                       skip_nested: bool = True) -> Iterator[ast.AST]:
    """Walk a function's body; ``skip_nested`` stops at nested
    function/lambda boundaries (they get their own visit from the
    module walk, or deliberately stay out of scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if skip_nested and isinstance(node, (*FUNC_DEFS, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def int_constants(node: ast.AST) -> list[int]:
    """Integer constants inside a Constant/Tuple/List node (the shape
    ``donate_argnums`` values take)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[int] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return out
    return []


def local_names(fn: ast.AST) -> set[str]:
    """Names bound inside a function: parameters plus every plain-Name
    store target (assignments, loop targets, comprehension targets,
    ``with ... as``)."""
    out: set[str] = set()
    args = fn.args
    for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
        out.add(a.arg)
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    for node in walk_function_body(fn, skip_nested=False):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
    return out
