"""fedlint — repo-native static analysis for the federation's
hard-won invariants.

Every rule in :mod:`p2pfl_tpu.analysis.rules` mechanizes an invariant
this codebase learned the expensive way:

- **donation-safety** — the round-9 resume bug: msgpack-restored
  leaves are non-owning views of the blob bytes, and handing them to a
  ``jit(..., donate_argnums=...)`` callee is a heap-layout-dependent
  garbage read; a binding passed to a donating callee must also never
  be read after the call.
- **recompile-hazard** — the §7b storm: ~450 mid-round XLA compiles
  (~32% of wall) from varying stack shapes in the socket hot path,
  plus f-string counter keys allocated per frame when tracing is off.
- **async-hygiene** — the round-11 prober incident: blocking calls on
  the event loop starve heartbeats and get healthy peers evicted, and
  a bare ``asyncio.create_task`` can be garbage-collected mid-flight
  with its exception reported only at interpreter exit.
- **jit-purity** — host side effects (prints, ``np.asarray``, tracer
  counters, attr/dict mutation) inside functions passed to
  ``jax.jit``/``lax.scan``/``shard_map`` either fail at trace time or
  silently run once at trace and never again.
- **atomic-artifact** — the round-12/14 torn-read contracts: every
  published status/checkpoint/flight/metrics artifact must be written
  via tmp+``os.replace`` (or appended one complete ``write()`` per
  line) so a live tailer never sees a torn file.

Entry points::

    python -m p2pfl_tpu.analysis.fedlint <paths>   # lint only
    python -m p2pfl_tpu.analysis [<paths>]         # all passes
                                                   # (fedlint + bench-keys sync)

Exit codes are healthcheck-style: 0 = clean, 1 = findings,
2 = operational error (unparseable file, bad arguments). Suppress a
single line with ``# fedlint: disable=<rule>[,<rule>...]``; grandfather
a true-but-deferred finding in ``FEDLINT_BASELINE.json`` (see
docs/analysis.md for the workflow).
"""

from p2pfl_tpu.analysis.core import (  # noqa: F401
    BASELINE_NAME,
    Finding,
    load_baseline,
    run_paths,
    write_baseline,
)
from p2pfl_tpu.analysis.rules import ALL_RULES  # noqa: F401
