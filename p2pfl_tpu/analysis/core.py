"""fedlint engine: findings, pragmas, baseline, file runner.

The engine is deliberately dumb and deterministic — parse each file
once with :mod:`ast`, hand the tree to every rule, subtract per-line
pragma suppressions and the checked-in baseline, report the rest. No
imports of the analyzed code, no type inference, no cross-file state:
a rule must be cheap enough to gate every PR from tier-1 and
predictable enough that a pragma or baseline entry is a reviewed
decision, not a dice roll.

Baseline entries are matched by **fingerprint** ``(rule, path,
stripped source line)`` — line numbers drift with every edit, the
offending line's text does not. A baseline entry whose line was fixed
or deleted therefore goes stale automatically and is reported (without
affecting the exit code) so the file shrinks over time instead of
accreting.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Any, Iterable, Iterator

#: checked-in grandfather file at the repo root
BASELINE_NAME = "FEDLINT_BASELINE.json"

_PRAGMA_RE = re.compile(
    r"#\s*fedlint:\s*disable(?:=(?P<rules>[\w-]+(?:\s*,\s*[\w-]+)*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    code: str  # stripped source line the finding anchors to

    def fingerprint(self) -> tuple[str, str, str]:
        """Line-number-free identity used for baseline matching."""
        return (self.rule, self.path, self.code)

    def as_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}")


class FileContext:
    """Everything a rule gets to look at for one file: the parsed
    tree (with parent links), the raw source, and the line table."""

    def __init__(self, path: pathlib.Path, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=str(path))
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._fedlint_parent = node  # type: ignore[attr-defined]

    # -- helpers every rule uses ---------------------------------------
    def parents(self, node: ast.AST) -> Iterator[ast.AST]:
        """Ancestors of ``node``, nearest first."""
        cur = getattr(node, "_fedlint_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_fedlint_parent", None)

    def segment(self, node: ast.AST) -> str:
        """Source text of a node ('' when unavailable)."""
        try:
            return ast.get_source_segment(self.src, node) or ""
        except Exception:
            return ""

    def code_line(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule, path=self.rel, line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message, code=self.code_line(line),
        )


# ---------------------------------------------------------------------
# pragma suppression
# ---------------------------------------------------------------------

def pragma_map(lines: list[str]) -> dict[int, set[str] | None]:
    """Per-line suppression: line number -> set of rule names, or
    ``None`` meaning every rule (a bare ``# fedlint: disable``)."""
    out: dict[int, set[str] | None] = {}
    for i, line in enumerate(lines, start=1):
        if "fedlint" not in line:
            continue
        m = _PRAGMA_RE.search(line)
        if m is None:
            continue
        rules = m.group("rules")
        if rules is None:
            out[i] = None
        else:
            out[i] = {r.strip() for r in rules.split(",")}
    return out


def suppressed(finding: Finding, pragmas: dict[int, set[str] | None]) -> bool:
    entry = pragmas.get(finding.line, ...)
    if entry is ...:
        return False
    return entry is None or finding.rule in entry


# ---------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------

# the --write-baseline scaffold marker; load_baseline refuses it, so a
# regenerated baseline cannot be merged without a human justification
SCAFFOLD_JUSTIFICATION = "TODO: justify or fix"


def load_baseline(path: str | pathlib.Path) -> list[dict[str, Any]]:
    """Baseline entries (``[]`` when the file doesn't exist). Every
    entry must carry ``rule``/``path``/``code`` plus a one-line
    ``justification`` — an unjustified grandfather is refused loudly,
    and so is the untouched ``--write-baseline`` scaffold text (the
    original design accepted it as "non-empty", which let a freshly
    regenerated baseline pass review with zero human words)."""
    path = pathlib.Path(path)
    if not path.is_file():
        return []
    doc = json.loads(path.read_text())
    entries = doc.get("entries", []) if isinstance(doc, dict) else doc
    for e in entries:
        missing = {"rule", "path", "code", "justification"} - set(e)
        if missing:
            raise ValueError(
                f"baseline {path}: entry {e!r} lacks {sorted(missing)}")
        just = str(e["justification"]).strip()
        if not just:
            raise ValueError(
                f"baseline {path}: entry for {e['path']} ({e['rule']}) "
                "has an empty justification")
        if just == SCAFFOLD_JUSTIFICATION:
            raise ValueError(
                f"baseline {path}: entry for {e['path']} ({e['rule']}) "
                "still carries the --write-baseline scaffold text "
                f"{SCAFFOLD_JUSTIFICATION!r} — replace it with the "
                "reason this finding is acceptable")
    return entries


def write_baseline(path: str | pathlib.Path, findings: Iterable[Finding],
                   justification: str = SCAFFOLD_JUSTIFICATION) -> None:
    """Regenerate the baseline from current findings (``--write-
    baseline``). Justifications default to a marker the reviewer must
    replace — ``load_baseline`` refuses the untouched marker, so the
    PR cannot land until every new grandfather is explained."""
    entries = [
        {"rule": f.rule, "path": f.path, "code": f.code,
         "justification": justification}
        for f in sorted(set(findings),
                        key=lambda f: (f.path, f.line, f.rule))
    ]
    doc = {"version": 1, "entries": entries}
    pathlib.Path(path).write_text(json.dumps(doc, indent=1) + "\n")


def baseline_index(entries: list[dict[str, Any]]) -> set[tuple[str, str, str]]:
    return {(e["rule"], e["path"], e["code"]) for e in entries}


# ---------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------

def iter_py_files(paths: Iterable[str | pathlib.Path]) -> Iterator[pathlib.Path]:
    for p in paths:
        p = pathlib.Path(p)
        if not p.exists():
            # a missing path must be loud, not a 0-file clean pass
            raise FileNotFoundError(str(p))
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if any(part.startswith(".") for part in f.parts):
                    continue
                yield f
        elif p.suffix == ".py":
            yield p


@dataclasses.dataclass
class LintResult:
    """One run's outcome, pre-split by disposition."""

    findings: list[Finding]          # unsuppressed — these gate
    pragma_suppressed: list[Finding]
    baselined: list[Finding]
    stale_baseline: list[dict[str, Any]]  # entries matching nothing
    files: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def as_dict(self) -> dict[str, Any]:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "pragma_suppressed": [f.as_dict()
                                  for f in self.pragma_suppressed],
            "baselined": [f.as_dict() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
            "files": self.files,
            "exit_code": self.exit_code,
        }


def run_paths(paths: Iterable[str | pathlib.Path], rules,
              root: str | pathlib.Path | None = None,
              baseline_entries: list[dict[str, Any]] | None = None,
              ) -> LintResult:
    """Run ``rules`` over every ``*.py`` under ``paths``.

    ``root`` anchors the repo-relative paths findings (and baseline
    fingerprints) use; defaults to the common current directory.
    Raises ``SyntaxError`` for an unparseable file — the CLI maps that
    to exit code 2 (operational error), never a silent skip.
    """
    root = pathlib.Path(root) if root is not None else pathlib.Path.cwd()
    entries = baseline_entries or []
    index = baseline_index(entries)
    res = LintResult([], [], [], [])
    matched: set[tuple[str, str, str]] = set()
    for path in iter_py_files(paths):
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        ctx = FileContext(path, rel, path.read_text())
        res.files += 1
        pragmas = pragma_map(ctx.lines)
        for rule in rules:
            for finding in rule.check(ctx):
                if suppressed(finding, pragmas):
                    res.pragma_suppressed.append(finding)
                elif finding.fingerprint() in index:
                    matched.add(finding.fingerprint())
                    res.baselined.append(finding)
                else:
                    res.findings.append(finding)
    res.stale_baseline = [
        e for e in entries
        if (e["rule"], e["path"], e["code"]) not in matched
    ]
    res.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return res
