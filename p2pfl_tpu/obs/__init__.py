"""Run-time observability: the span/counter tracer, the shared
timestamped-record shape, and the multi-process trace merge.

The two biggest perf wins so far (round 7's socket round speedup,
round 6's Pallas gate) were found by hand-profiling; this package makes
the next hidden floor visible from the framework itself. See
docs/observability.md.
"""

from p2pfl_tpu.obs.records import make_record
from p2pfl_tpu.obs.trace import (
    NULL_SPAN,
    Tracer,
    configure,
    configure_from_env,
    get_tracer,
    install_xla_listener,
    reset_xla_counters,
    xla_compile_seconds,
    xla_recompiles,
)

__all__ = [
    "NULL_SPAN",
    "Tracer",
    "configure",
    "configure_from_env",
    "get_tracer",
    "install_xla_listener",
    "make_record",
    "reset_xla_counters",
    "xla_compile_seconds",
    "xla_recompiles",
]
