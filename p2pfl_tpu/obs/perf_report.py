"""Automated perf attribution: *where did the round go*, as a report.

docs/perf.md §6 reaches its verdicts by hand: merge the traces, stare
at the critpath table, divide FLOPs by walls, cross-reference the BENCH
trajectory. This module mechanizes that loop over the artifacts the
stack already writes —

1. **critical-path components** (obs.critpath): per-round
   fit/wire/wait/agg/other over every ``node.round`` span, averaged
   across nodes and rounds into one ranked "where the round went"
   table;
2. **device-level step phases** (obs.devprof): when the trace carries
   ``devprof.*`` spans, the fit bucket is subdivided into
   data/forward/backward/update/accum so the verdict reaches *inside*
   the jitted program;
3. **recompile counters**: the per-process ``xla/backend_compiles``
   totals the tracer exports — a fat ``other``/``fit`` bucket with a
   nonzero steady-state compile count is a recompile storm, not a
   compute floor;
4. **the BENCH trajectory** (``--bench BENCH_*.json ...``): each
   HEADLINE key of the LAST file given (the candidate) is compared
   against the best-ever value across all given files with matching
   provenance (scripts/check_bench_regress's baseline discipline), and
   the component furthest over its floor is named.

Usage::

    python -m p2pfl_tpu.obs.perf_report <trace-dir> [--round N]
        [--bench BENCH_a.json BENCH_b.json ...] [--json]

Exit code 1 when there is nothing to attribute (no readable trace
files, or no ``node.round`` spans — tracing was off).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Any

from p2pfl_tpu.obs import critpath
from p2pfl_tpu.obs.devprof import PHASE_SPANS

_COMPONENTS = ("fit", "wire", "wait", "agg", "other")
_RECOMPILE_KEY = "xla/backend_compiles"


def devprof_phases(doc: dict) -> dict[str, dict[str, float]]:
    """Per-phase totals of the ``devprof.*`` spans across the whole
    merged trace: ``{phase: {total_s, count}}``. Empty when the run was
    not step-profiled (P2PFL_DEVPROF=step)."""
    out: dict[str, dict[str, float]] = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") != "X" or ev.get("name") not in PHASE_SPANS:
            continue
        rec = out.setdefault(ev["name"], {"total_s": 0.0, "count": 0})
        rec["total_s"] += float(ev.get("dur", 0.0)) / 1e6
        rec["count"] += 1
    for rec in out.values():
        rec["total_s"] = round(rec["total_s"], 6)
    return out


def recompile_total(doc: dict) -> int:
    """Summed post-warm-up backend-compile count across every traced
    process (the tracer's exported counters)."""
    total = 0
    by_pid = doc.get("metadata", {}).get("counters_by_pid", {}) or {}
    for counters in by_pid.values():
        total += int((counters or {}).get(_RECOMPILE_KEY, 0))
    return total


def attribute(doc: dict, round_no: int | None = None) -> dict[str, Any]:
    """The full attribution over one merged trace document.

    Components are the per-round means of the per-node critpath
    decomposition, then averaged across the analyzed rounds — the
    steady-state shape of a round, not one outlier's. The devprof fit
    split reports both the raw phase seconds and each phase's share of
    the fit bucket (phases are proportions: the step-profiled pipeline
    is not the fused production program, so its absolute seconds only
    bound, never equal, the production fit)."""
    result = critpath.analyze(doc, round_no=round_no)
    per_round: list[dict[str, float]] = []
    rounds_used: list[int] = []
    for rn, rec in sorted(result["rounds"].items()):
        nodes = rec["nodes"]
        if not nodes:
            continue
        rounds_used.append(rn)
        mean = {c: sum(n[f"{c}_s"] for n in nodes.values()) / len(nodes)
                for c in _COMPONENTS}
        mean["round"] = sum(n["round_s"] for n in nodes.values()) / len(nodes)
        per_round.append(mean)
    if not per_round:
        return {"rounds": [], "components": {}, "top": None}
    comps = {
        c: round(sum(r[c] for r in per_round) / len(per_round), 6)
        for c in _COMPONENTS
    }
    round_s = sum(r["round"] for r in per_round) / len(per_round)
    top = max(comps, key=comps.get)
    out: dict[str, Any] = {
        "rounds": rounds_used,
        "round_s": round(round_s, 6),
        "components": comps,
        "top": top,
        "recompiles": recompile_total(doc),
    }
    phases = devprof_phases(doc)
    if phases:
        phase_sum = sum(p["total_s"] for p in phases.values())
        split = {}
        for name, p in sorted(phases.items()):
            share = p["total_s"] / phase_sum if phase_sum else 0.0
            split[name] = {
                "total_s": p["total_s"], "count": p["count"],
                "share_of_fit": round(share, 4),
                "fit_s_est": round(share * comps["fit"], 6),
            }
        out["fit_phases"] = split
        if top == "fit" and split:
            top_phase = max(split, key=lambda k: split[k]["total_s"])
            out["top"] = f"fit.{top_phase.split('.', 1)[1]}"
    return out


# ---------------------------------------------------------------------
# BENCH trajectory join
# ---------------------------------------------------------------------

def _regress_module():
    """scripts/check_bench_regress, imported the way benchkeys does —
    one baseline discipline, not a reimplementation."""
    repo = pathlib.Path(__file__).resolve().parents[2]
    scripts = repo / "scripts"
    if str(scripts) not in sys.path:
        sys.path.insert(0, str(scripts))
    import check_bench_regress

    return check_bench_regress


def bench_attribution(bench_paths: list[str]) -> dict[str, Any]:
    """HEADLINE keys of the last envelope given vs the best-ever
    provenance-matched values over all of them; the top over-floor key
    is the component the next perf PR should attack. over_floor_pct is
    always worse-is-positive regardless of the key's direction."""
    cbr = _regress_module()
    history: list[tuple[str, dict]] = []
    for p in bench_paths:
        parsed = cbr.load_parsed(pathlib.Path(p))
        if parsed is not None:
            history.append((pathlib.Path(p).name, parsed))
    if not history:
        return {"rows": [], "top": None, "error": "no parseable envelopes"}
    cand_name, cand = history[-1]
    prov = cbr._provenance(cand)
    rows = []
    for key, direction in sorted(cbr.HEADLINE.items()):
        v = cand.get(key)
        if not isinstance(v, (int, float)):
            continue
        best = cbr.baseline_over(history, key, direction,
                                 cand.get("metric"), provenance=prov)
        if best is None or best[0] == 0:
            continue
        v = float(v)
        over = ((v - best[0]) if direction == "lower" else (best[0] - v))
        rows.append({
            "key": key, "value": v, "best": best[0], "best_from": best[1],
            "over_floor_pct": round(100.0 * over / abs(best[0]), 2),
        })
    rows.sort(key=lambda r: -r["over_floor_pct"])
    over_floor = [r for r in rows if r["over_floor_pct"] > 0]
    return {
        "candidate": cand_name,
        "rows": rows,
        "top": over_floor[0]["key"] if over_floor else None,
    }


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------

def _fmt_report(attr: dict, bench: dict | None) -> str:
    lines = []
    rounds = attr["rounds"]
    span = (f"round {rounds[0]}" if len(rounds) == 1
            else f"rounds {rounds[0]}-{rounds[-1]}")
    lines.append(f"where the round went (mean over {span}, "
                 f"{attr['round_s']:.3f}s/round)")
    lines.append(f"  {'COMPONENT':<12}{'S/ROUND':>10}{'SHARE':>8}")
    total = sum(attr["components"].values()) or 1.0
    ranked = sorted(attr["components"].items(), key=lambda kv: -kv[1])
    for name, v in ranked:
        lines.append(f"  {name:<12}{v:>10.3f}{100 * v / total:>7.1f}%")
    phases = attr.get("fit_phases")
    if phases:
        lines.append("  fit phases (devprof step profile):")
        lines.append(f"    {'PHASE':<12}{'SPAN_S':>10}{'OF FIT':>8}"
                     f"{'EST S/ROUND':>13}")
        for name, p in sorted(phases.items(),
                              key=lambda kv: -kv[1]["total_s"]):
            short = name.split(".", 1)[1]
            lines.append(
                f"    {short:<12}{p['total_s']:>10.3f}"
                f"{100 * p['share_of_fit']:>7.1f}%"
                f"{p['fit_s_est']:>13.3f}")
    lines.append(f"recompiles: {attr['recompiles']} post-warm-up backend "
                 "compiles across traced processes")
    lines.append(f"top component: {attr['top']}")
    if bench is not None:
        lines.append("")
        if bench.get("error"):
            lines.append(f"bench trajectory: {bench['error']}")
        else:
            lines.append(f"bench trajectory (candidate {bench['candidate']} "
                         "vs best-ever, provenance-matched)")
            lines.append(f"  {'KEY':<32}{'VALUE':>12}{'BEST':>12}"
                         f"{'OVER-FLOOR':>12}")
            for r in bench["rows"]:
                lines.append(
                    f"  {r['key']:<32}{r['value']:>12.4g}"
                    f"{r['best']:>12.4g}{r['over_floor_pct']:>+11.1f}%")
            if bench["top"]:
                top = bench["rows"][0]
                lines.append(
                    f"top over-floor: {top['key']} "
                    f"{top['over_floor_pct']:+.1f}% vs {top['best_from']}")
            else:
                lines.append("top over-floor: none — every headline key "
                             "is at its historical floor")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.obs.perf_report")
    ap.add_argument("inputs", nargs="+",
                    help="trace directory (searched recursively for "
                         "*.trace.json) or individual trace files")
    ap.add_argument("--round", type=int, default=None,
                    help="restrict attribution to one round")
    ap.add_argument("--bench", nargs="+", default=None, metavar="BENCH",
                    help="BENCH_*.json envelopes, oldest first; the "
                         "last is the candidate judged against the "
                         "best-ever of the rest")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON instead of the report")
    args = ap.parse_args(argv)
    doc = critpath.load_merged(args.inputs)
    if doc["metadata"]["files"] == 0:
        print(f"no readable trace files under {args.inputs}",
              file=sys.stderr)
        return 1
    attr = attribute(doc, round_no=args.round)
    if not attr["rounds"]:
        print("no node.round spans found (was tracing enabled?)",
              file=sys.stderr)
        return 1
    bench = bench_attribution(args.bench) if args.bench else None
    if args.json:
        out = dict(attr)
        if bench is not None:
            out["bench"] = bench
        print(json.dumps(out, sort_keys=True))
    else:
        print(_fmt_report(attr, bench))
    return 0


if __name__ == "__main__":
    sys.exit(main())
