"""Device-level profiling below ``node.fit`` — phases, MFU, HBM.

The critical-path plane (obs.critpath) attributes round wall to
fit/wire/wait/agg, but ``fit`` stays a black box: one jitted
``train_epochs`` program whose internals no host clock can see. This
module opens that box two ways, both gated on ``P2PFL_DEVPROF``:

**gauges** (``P2PFL_DEVPROF=1``) — the cheap, always-safe level. After
every fit the learner computes a live MFU / achieved-TFLOPs gauge
(honest FLOPs from obs.cost_model over the measured fit wall) and the
peak-HBM / RSS watermarks, and stows them in ``devprof_last`` for the
status publisher. Nothing touches the training program; the only
added work is a once-per-shape FLOP probe (cached) and two gauge
reads per fit. This is the arm the bench's ``devprof_overhead_pct``
A/B gates at <= 2%.

**step** (``P2PFL_DEVPROF=step``) — explicit opt-in step profiling.
The fit runs a *phase-split* pipeline instead of the fused scan:
separate jitted sub-programs per phase, each drained with
``block_until_ready`` inside its Tracer span —

- ``devprof.data``: per-epoch shuffle + batch layout (host-gather),
- ``devprof.forward``: the forward pass (``jax.vjp`` primal, residuals
  included — a TRUE forward/backward split, no recompute),
- ``devprof.backward``: the vjp cotangent pass alone,
- ``devprof.update``: optimizer update (decay/gate/fused-SGD path),
- ``devprof.accum``: the accumulate-epilogue (metric assembly + final
  drain; federated cross-device runs fold their aggregate here).

Because every span measures work the profiled fit actually executes
exactly once, the phases sum to the wrapping ``learner.fit`` span by
construction — pinned under the same <=10% gate as critpath's
components-vs-wall check. The caveat is the converse: the phase-split
pipeline is NOT the production program (XLA cannot fuse across the
phase boundaries), so step mode measures *where the step's work
lives*, not the fused program's exact wall. Leave it off for timing
runs; the gauges level exists so the dashboard number comes from the
real program.

Spans ride the existing Tracer: disabled tracing keeps the shared
NULL_SPAN no-allocation path, and devprof itself is one env read per
fit when off.
"""

from __future__ import annotations

import functools
import os
from types import SimpleNamespace
from typing import Any

from p2pfl_tpu.obs import cost_model
from p2pfl_tpu.obs.trace import get_tracer

ENV_VAR = "P2PFL_DEVPROF"

# span names the step level records (perf_report / bench join on them)
PHASE_SPANS = ("devprof.data", "devprof.forward", "devprof.backward",
               "devprof.update", "devprof.accum")


def mode() -> str:
    """``off`` / ``gauges`` / ``step`` from ``P2PFL_DEVPROF``. Read
    per call — fits happen at round cadence, not frame cadence, so an
    env read is free and keeps child processes config-less."""
    raw = os.environ.get(ENV_VAR, "")
    if raw in ("", "0", "off"):
        return "off"
    return "step" if raw == "step" else "gauges"


def enabled() -> bool:
    return mode() != "off"


def step_enabled() -> bool:
    return mode() == "step"


# ---------------------------------------------------------------------
# phase-split fit (step level)
# ---------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _phase_jits(fns) -> SimpleNamespace:
    """Jitted phase programs for one StepFns. Cached on the (frozen,
    hashable) StepFns itself so SharedTrainer federations compile the
    split once, like the production programs."""
    import jax

    return SimpleNamespace(
        prep=jax.jit(fns.prepare_epoch),
        fwd=jax.jit(fns.forward),
        bwd=jax.jit(fns.backward),
        upd=jax.jit(fns.apply_update),
    )


def profiled_epoch(learner, x, y, mask):
    """One epoch of ``learner``'s fit through the phase-split pipeline,
    each phase drained inside its span. Returns ``(state, metrics)``
    with the same ``{"loss": ...}`` contract as ``train_epochs`` —
    the learner adopts the state exactly as on the fused path."""
    import jax

    tracer = get_tracer()
    jits = _phase_jits(learner.fns)
    state = learner.state
    with tracer.span("devprof.data"):
        rng, (bx, by, bm) = jits.prep(state, x, y, mask)
        jax.block_until_ready((bx, by, bm))
    state = state.replace(rng=rng)
    steps = int(bx.shape[0])
    loss_sum = 0.0
    for i in range(steps):
        with tracer.span("devprof.forward"):
            loss, vjp_fn = jits.fwd(state.params, bx[i], by[i], bm[i])
            # drain residuals too: an unblocked residual producer
            # would bill its device time to the backward span
            jax.block_until_ready((loss, vjp_fn))
        with tracer.span("devprof.backward"):
            grads = jits.bwd(vjp_fn, loss)
            jax.block_until_ready(grads)
        with tracer.span("devprof.update"):
            state = jits.upd(state, grads)
            jax.block_until_ready(state.params)
        loss_sum += float(loss)
    with tracer.span("devprof.accum"):
        metrics = {"loss": loss_sum / max(steps, 1)}
        jax.block_until_ready(state)
    return state, metrics


# ---------------------------------------------------------------------
# live gauges (gauges + step levels)
# ---------------------------------------------------------------------

# (id(fns), data shape) -> per-epoch honest FLOPs; learners sharing a
# SharedTrainer hit the same entry, so the probe compiles once
_FLOPS_CACHE: dict[tuple, float | None] = {}


def fit_flops(learner) -> float | None:
    """Cached per-epoch honest FLOPs for one learner (cost_model's
    trip-1 probe; see its docstring for the two corrections)."""
    memo = getattr(learner, "_devprof_flops", None)
    if memo is not None:
        return memo or None  # 0.0 sentinel = probed, unknown
    try:
        shape = tuple(getattr(learner.data.x, "shape", (len(learner.data.x),)))
    except Exception:
        shape = ()
    key = (id(learner.fns), shape, learner.batch_size)
    if key not in _FLOPS_CACHE:
        _FLOPS_CACHE[key] = cost_model.learner_fit_flops(learner)
    flops = _FLOPS_CACHE[key]
    learner._devprof_flops = flops or 0.0
    return flops


def fit_gauges(learner, wall_s: float, epochs: int) -> dict[str, Any]:
    """The ``devprof_*`` status gauges for one completed fit: measured
    wall, achieved TFLOPs and MFU (against one chip — a JaxLearner fit
    runs on one device), and the memory watermarks."""
    out: dict[str, Any] = {"devprof_fit_s": round(wall_s, 4)}
    flops = fit_flops(learner)
    if flops and wall_s > 0:
        achieved = flops * max(epochs, 1) / wall_s
        out["devprof_tflops"] = round(achieved / 1e12, 4)
        util = cost_model.mfu(flops * max(epochs, 1), wall_s, n_devices=1)
        if util is not None:
            out["devprof_mfu"] = round(util, 4)
    out.update(cost_model.memory_watermark())
    return out


def round_gauges(flops: float | None, wall_s: float,
                 n_devices: int) -> dict[str, Any]:
    """Federation-plane gauges: one SPMD round program spanning
    ``n_devices`` (the scenario drivers publish the same number for
    every node — utilization is a property of the shared program)."""
    out: dict[str, Any] = {"devprof_fit_s": round(wall_s, 4)}
    if flops and wall_s > 0:
        out["devprof_tflops"] = round(flops / wall_s / 1e12, 4)
        util = cost_model.mfu(flops, wall_s, n_devices=n_devices)
        if util is not None:
            out["devprof_mfu"] = round(util, 4)
    out.update(cost_model.memory_watermark())
    return out
