"""Honest-FLOP accounting shared by the bench and the live gauges.

One cost model, two consumers: ``bench.py`` (the headline MFU keys)
and ``obs.devprof`` (the live per-node MFU gauge) must agree on what a
FLOP is, or the dashboard number silently diverges from the audited
one. Two corrections make the raw ``cost_analysis()`` read honest:

1. **Count only what XLA counts correctly** (docs/perf.md §4): the
   grouped-conv lowering used before round 4 made ``cost_analysis``
   bill conv1 as if it contracted all 64 groups' channels — a ~64x
   per-op inflation (7.2 TF counted vs the analytic 4.2 TF). The fix
   was upstream (the PatchConv model lowers to ops XLA counts right);
   this module keeps the contract by reading the compiled program's
   own cost analysis rather than re-deriving analytic counts that
   would drift from the model zoo.
2. **Un-count the scan body collapse** (docs/perf.md §6.3):
   ``cost_analysis`` counts a ``lax.scan`` body ONCE regardless of
   trip count, so a batched epoch program under-reports by ~#steps.
   :func:`learner_fit_flops` probes with a mathematically equivalent
   trip-count-1 program (batch = the samples the real program uses
   per epoch) — same matmul/conv FLOPs over the same sample count,
   accurately counted — and takes the max of probe and direct read.

The peak table and the watermark reader live here too so every MFU /
HBM number in the repo shares one denominator. Module-level imports
stay jax-free: the bench parent process imports this without touching
the accelerator.
"""

from __future__ import annotations

import os
from typing import Any

# bf16 peak FLOP/s per chip, by device_kind substring (the table
# bench.py's headline MFU has used since round 1; moved here round 22)
PEAKS = {
    "v5 lite": 197e12,  # v5e
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,  # Trillium
    "v6e": 918e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

ENV_PEAK = "P2PFL_PEAK_FLOPS"  # per-chip override (tests, odd parts)


def peak_flops(device: Any | None = None) -> float | None:
    """Per-chip bf16 peak FLOP/s, or None off the table (CPU dev
    boxes). ``P2PFL_PEAK_FLOPS`` overrides — how tests exercise the
    MFU arithmetic without a TPU, and how an unlisted part gets a
    denominator without a code change."""
    env = os.environ.get(ENV_PEAK)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if device is None:
        try:
            import jax

            device = jax.local_devices()[0]
        except Exception:
            return None
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAKS.items():
        if key in kind:
            return peak
    return None


def compiled_flops(compiled: Any) -> float | None:
    """The ``flops`` entry of one compiled program's cost analysis;
    None when the backend publishes no analysis (some CPU builds)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax wraps in a list
            cost = cost[0] if cost else None
        flops = cost.get("flops") if isinstance(cost, dict) else None
        return float(flops) if flops else None
    except Exception:
        return None


def program_flops(jitfn: Any, *args: Any, **kwargs: Any) -> float | None:
    """Lower + compile ``jitfn`` at the given (aval or concrete)
    arguments and read its counted FLOPs. Compile cost is paid once
    per shape signature (jit/persistent caches apply)."""
    try:
        return compiled_flops(jitfn.lower(*args, **kwargs).compile())
    except Exception:
        return None


def avals(tree: Any) -> Any:
    """Shape/dtype skeleton of a pytree — ``.lower()`` needs only
    shapes, and materializing real arrays just to read their avals
    would double host->device traffic (learner.warm_up's trick)."""
    import jax
    import numpy as np

    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
        if not hasattr(a, "aval")
        else jax.ShapeDtypeStruct(a.shape, a.dtype),
        tree,
    )


def learner_fit_flops(learner: Any) -> float | None:
    """Honest FLOPs of ONE epoch of a ``JaxLearner`` fit.

    ``max(direct, probe)``: the direct read of the real scan program
    under-counts by ~#steps (correction 2 above); the probe rebuilds
    the step functions at batch = used-samples so the epoch scan's
    trip count is 1 and every op is counted once per sample actually
    trained. The probe compiles one extra program per (model, shape)
    signature — callers cache (obs.devprof does)."""
    import jax
    import numpy as np

    from p2pfl_tpu.learning.learner import make_step_fns

    if learner.state is None or learner.data is None:
        return None
    x = np.asarray(learner.data.x)
    y = np.asarray(learner.data.y)
    s = len(x)
    bsz = min(learner.batch_size, s)
    if bsz <= 0:
        return None
    used = (s // bsz) * bsz
    state_avals = avals(learner.state)
    xa = jax.ShapeDtypeStruct(x.shape, x.dtype)
    ya = jax.ShapeDtypeStruct(y.shape, y.dtype)
    ma = jax.ShapeDtypeStruct((s,), np.dtype(bool))
    direct = None
    if getattr(learner, "_train_jit", None) is not None:
        direct = program_flops(learner._train_jit, state_avals,
                               xa, ya, ma, epochs=1)
    probe = None
    try:
        fns = make_step_fns(
            learner.model, objective=learner.objective,
            optimizer=learner.optimizer_name,
            learning_rate=learner.learning_rate,
            momentum=learner.momentum,
            weight_decay=learner.weight_decay,
            momentum_dtype=learner.momentum_dtype,
            batch_size=used,
        )
        probe = program_flops(
            jax.jit(fns.train_epochs, static_argnames=("epochs",)),
            state_avals, xa, ya, ma, epochs=1,
        )
    except Exception:
        probe = None
    counted = [f for f in (direct, probe) if f]
    return max(counted) if counted else None


def mfu(flops: float | None, wall_s: float | None,
        n_devices: int = 1, peak: float | None = None) -> float | None:
    """Model-FLOP utilization: achieved FLOP/s over the aggregate peak
    of the devices the program spans. None without a peak (CPU)."""
    if not flops or not wall_s or wall_s <= 0:
        return None
    peak = peak if peak is not None else peak_flops()
    if not peak:
        return None
    return flops / wall_s / (peak * max(int(n_devices), 1))


def memory_watermark() -> dict[str, float]:
    """Peak-memory gauges for a status record: device HBM high-water
    (and its limit) via ``memory_stats()`` where the backend publishes
    them, host RSS peak as the always-available fallback — CPU
    backends publish no device stats, and an OOM-bound socket
    federation is host-memory-bound anyway."""
    out: dict[str, float] = {}
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        if peak:
            out["devprof_hbm_peak_mb"] = round(float(peak) / 1e6, 1)
        if limit:
            out["devprof_hbm_limit_mb"] = round(float(limit) / 1e6, 1)
    except Exception:
        pass
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB; darwin reports bytes
        scale = 1024.0 if os.uname().sysname == "Linux" else 1.0
        out["devprof_rss_peak_mb"] = round(ru * scale / 1e6, 1)
    except Exception:
        pass
    return out
