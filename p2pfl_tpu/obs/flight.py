"""Always-on bounded flight recorder for federation control events.

The span tracer (obs.trace) is opt-in because it meters *hot* paths;
this recorder is the opposite trade: it captures only *rare* control
transitions — membership suspect/probe/evict, session open/quorum/
close, reputation exclusions, attack injections, checkpoint ops,
wire-dtype negotiations — so it can stay on in every run, traced or
not. When a node crashes, is evicted, or a child process dies to an
unhandled exception, the ring is dumped as ``flight_<pid>.json`` and
the churn becomes explainable after the fact instead of requiring a
re-run with tracing enabled.

Design discipline (mirrors obs.trace, priority order):

1. **Recording is one deque.append.** ``record()`` builds one tuple
   and appends to a bounded ``collections.deque`` — atomic under
   CPython, so asyncio callbacks and executor threads share the ring
   without a lock. No per-event I/O, no serialization until dump time.
2. **Disabled is one attribute read.** ``P2PFL_FLIGHT=0`` (the bench
   A/B's off-arm) short-circuits before any allocation.
3. **Dump is atomic and re-entrant.** ``dump()`` rewrites the same
   ``flight_<pid>.json`` via tmp+rename; repeated dumps (crash then
   eviction) keep the latest, fullest picture with every trigger
   reason accumulated.

Like the tracer, the process recorder is a singleton configured IN
PLACE (call sites cache the reference). The launcher and the SPMD
scenario point ``dump_dir`` at ``<log_dir>/<name>/flight``; without a
configured directory postmortems land in the system temp dir so an
unconfigured crash still leaves evidence somewhere predictable.
"""

from __future__ import annotations

import json
import os
import pathlib
import tempfile
import threading
import time
from collections import deque
from typing import Any

from p2pfl_tpu.obs import trace as _trace

ENV_VAR = "P2PFL_FLIGHT"
_RING_MAX = 1 << 12  # control-plane events are rare; 4096 spans hours


class FlightRecorder:
    """Bounded ring of (ts, kind, fields) control events + postmortem
    dump. One per process; nodes sharing an event loop share it (the
    ``node`` field attributes events, like the tracer's lanes)."""

    def __init__(self, ring_max: int = _RING_MAX):
        self.enabled = os.environ.get(ENV_VAR, "") != "0"
        self.dump_dir: pathlib.Path | None = None
        self._ring_max = ring_max
        self._events: deque = deque(maxlen=ring_max)
        self._lock = threading.Lock()  # dump/configure only, never record
        self._dump_reasons: list[str] = []
        self.wall_t0 = time.time()

    # -- configuration --------------------------------------------------
    def configure(self, enabled: bool | None = None,
                  dump_dir: str | pathlib.Path | None = None,
                  ring_max: int | None = None) -> "FlightRecorder":
        """Mutate IN PLACE (call sites cache the singleton)."""
        with self._lock:
            if ring_max is not None and ring_max != self._ring_max:
                self._ring_max = ring_max
                self._events = deque(self._events, maxlen=ring_max)
            if dump_dir is not None:
                self.dump_dir = pathlib.Path(dump_dir)
            if enabled is not None:
                self.enabled = bool(enabled)
        return self

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dump_reasons = []
            self.wall_t0 = time.time()

    # -- recording ------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        """Append one control event. Cheap enough for every call site
        to run unconditionally: one enabled check, one tuple, one
        atomic deque.append."""
        if not self.enabled:
            return
        # Stamp the active trace identity so a postmortem's control
        # events can be joined against the span timeline. One attribute
        # read when tracing is off — the recorder stays always-on cheap.
        tr = _trace.get_tracer()
        if tr.enabled and "trace" not in fields:
            fields["trace"] = tr.trace_id
        self._events.append((time.time(), kind, fields))

    # -- reading --------------------------------------------------------
    def events(self, kind: str | None = None) -> list[dict[str, Any]]:
        """Snapshot of the ring as dicts, oldest first; ``kind``
        filters by event kind."""
        return [
            {"ts": ts, "kind": k, **f}
            for ts, k, f in list(self._events)
            if kind is None or k == kind
        ]

    def __len__(self) -> int:
        return len(self._events)

    # -- postmortem -----------------------------------------------------
    def dump(self, reason: str,
             path: str | pathlib.Path | None = None) -> pathlib.Path | None:
        """Write ``flight_<pid>.json`` (atomic tmp+rename). Returns the
        path, or None when recording is disabled. Repeated dumps from
        one process overwrite the same file — every trigger reason is
        kept in ``reasons`` so the last dump tells the whole story."""
        if not self.enabled:
            return None
        with self._lock:
            self._dump_reasons.append(str(reason))
            reasons = list(self._dump_reasons)
        if path is None:
            base = self.dump_dir or pathlib.Path(tempfile.gettempdir())
            path = pathlib.Path(base) / f"flight_{os.getpid()}.json"
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        doc = {
            "pid": os.getpid(),
            "wall_t0": self.wall_t0,
            "dumped_at": time.time(),
            "reasons": reasons,
            "events": self.events(),
        }
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(doc))
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------
# process singleton
# ---------------------------------------------------------------------
_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """The process flight recorder. Cache-safe: configure() mutates in
    place."""
    return _RECORDER


def record(kind: str, **fields: Any) -> None:
    """Module-level shorthand — the one-liner every call site uses."""
    _RECORDER.record(kind, **fields)


def dump(reason: str,
         path: str | pathlib.Path | None = None) -> pathlib.Path | None:
    return _RECORDER.dump(reason, path=path)


def configure(enabled: bool | None = None,
              dump_dir: str | pathlib.Path | None = None,
              ring_max: int | None = None) -> FlightRecorder:
    return _RECORDER.configure(enabled=enabled, dump_dir=dump_dir,
                               ring_max=ring_max)
