"""``python -m p2pfl_tpu.obs.healthcheck <dir>`` — health as an exit code.

One-shot mode evaluates a scenario/status directory once and exits
0 (healthy) / 1 (warnings) / 2 (critical), so shell scripts and CI can
gate on federation health the same way they gate on a test run:

    python -m p2pfl_tpu.obs.healthcheck /tmp/fl_logs/mnist_8 || exit 1

``--watch`` keeps a persistent engine polling the directory, printing
fire/clear *transitions* as they happen (and alert lines on ``--json``
as JSONL); the exit code then reflects the worst severity seen, which
is what the bench's detection-latency probe consumes.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

from p2pfl_tpu.obs.health import HealthConfig, HealthEngine, evaluate_dir

_EXIT = {"ok": 0, "warn": 1, "crit": 2}


def _fmt(alert) -> str:
    who = "federation" if alert.node is None else f"node {alert.node}"
    return f"[{alert.severity.upper():4s}] {alert.rule:20s} {who}: " \
           f"{alert.message}"


def build_engine(args: argparse.Namespace) -> HealthEngine:
    cfg = HealthConfig()
    if args.liveness_s is not None:
        cfg.liveness_s = args.liveness_s
    if args.stall_rounds is not None:
        cfg.stall_rounds = args.stall_rounds
    if args.stall_s is not None:
        cfg.stall_s = args.stall_s
    return HealthEngine(config=cfg)


def run_once(directory: str, engine: HealthEngine,
             as_json: bool) -> int:
    alerts, _ = evaluate_dir(directory, engine=engine)
    if as_json:
        print(json.dumps({
            "severity": engine.worst(),
            "alerts": [a.to_dict() for a in alerts],
        }))
    else:
        if not alerts:
            print("healthy: no alerts")
        for a in alerts:
            print(_fmt(a))
    return _EXIT[engine.worst()]


def run_watch(directory: str, engine: HealthEngine, interval_s: float,
              as_json: bool, max_s: float | None) -> int:
    worst_seen = "ok"
    t0 = time.monotonic()
    n_transitions = 0
    while True:
        evaluate_dir(directory, engine=engine)
        for tr in engine.transitions[n_transitions:]:
            if as_json:
                print(json.dumps(tr), flush=True)
            else:
                node = "federation" if tr["node"] is None \
                    else f"node {tr['node']}"
                if tr["event"] == "fire":
                    print(f"FIRE  {tr['rule']} {node}: {tr['message']}",
                          flush=True)
                else:
                    print(f"CLEAR {tr['rule']} {node}", flush=True)
        n_transitions = len(engine.transitions)
        w = engine.worst()
        if _EXIT[w] > _EXIT[worst_seen]:
            worst_seen = w
        if max_s is not None and time.monotonic() - t0 >= max_s:
            return _EXIT[worst_seen]
        time.sleep(interval_s)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m p2pfl_tpu.obs.healthcheck",
        description="Evaluate federation health rules over a scenario "
                    "or status directory; exit 0 healthy / 1 warn / "
                    "2 crit.")
    ap.add_argument("directory",
                    help="scenario dir (containing status/) or the "
                         "status dir itself")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one JSON doc, or "
                         "JSONL transitions under --watch)")
    ap.add_argument("--watch", action="store_true",
                    help="poll continuously, print fire/clear "
                         "transitions")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="watch poll period seconds (default 1.0)")
    ap.add_argument("--max-s", type=float, default=None,
                    help="watch: stop after this many seconds and exit "
                         "with the worst severity seen")
    ap.add_argument("--liveness-s", type=float, default=None,
                    help="override node-dead liveness threshold")
    ap.add_argument("--stall-rounds", type=int, default=None,
                    help="override round-stall cohort-lag threshold")
    ap.add_argument("--stall-s", type=float, default=None,
                    help="override round-stall no-advance threshold")
    args = ap.parse_args(argv)

    engine = build_engine(args)
    if args.watch:
        try:
            return run_watch(args.directory, engine, args.interval,
                             args.json, args.max_s)
        except KeyboardInterrupt:
            return _EXIT[engine.worst()]
    return run_once(args.directory, engine, args.json)


if __name__ == "__main__":
    sys.exit(main())
