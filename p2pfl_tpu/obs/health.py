"""Declarative health rules over the federation's passive telemetry.

The monitor (utils.monitor) and the webapp *render* the status records
every node publishes; nothing in the stack *judges* them — a stalled
round, a silently evicted node, or a trust collapse is only visible to
a human staring at the table. This module is the judging half: a small
rule engine evaluated over the same two streams the dashboards already
tail (``node_<i>.status.json`` records and the ``metrics.jsonl``
event stream), with **firing/clear semantics** — an alert is a stateful
object that fires once when its condition appears, updates while it
holds, and clears when it goes away, so a watcher (the monitor's
alerts pane, the healthcheck CLI's exit code, the bench's detection-
latency probe) sees transitions, not a re-printed condition.

Built-in rules (severity in parentheses; all thresholds live on
``HealthConfig``):

- ``round-stall`` (warn): a live node's round lags the cohort's max
  round by ``stall_rounds``+, or — with engine state across
  evaluations — a live node's round hasn't advanced in ``stall_s``.
- ``node-dead`` (warn → crit): a node's status record is older than
  ``liveness_s``. Escalates to crit — dead *beyond quorum* — when the
  remaining live cohort falls below ``quorum_frac`` of the published
  federation, with an extra federation-level finding.
- ``trust-collapse`` (crit): a published trust score fell below
  ``trust_floor`` (reputation-weighted runs only).
- ``byte-rate`` (warn): a node's cumulative wire traffic exceeds
  ``byte_ratio`` x the cohort median by at least ``byte_floor`` bytes
  — the signature of a relay storm or a gossip loop.
- ``recompile-storm`` (warn): a node reports more than
  ``recompile_storm`` post-warm-up XLA backend compiles (the round-7
  storm, perf.md §7b, as a live alert instead of a bench postmortem).
- ``accuracy-divergence`` (warn): a node's accuracy sits
  ``divergence`` below the cohort median (statuses first, newest
  ``metrics.jsonl`` Test/accuracy rows as fallback).
- ``epsilon-budget`` (warn → crit): a node's published DP spend
  (``dp_epsilon`` in the status record, from the privacy accountant)
  reached ``eps_warn_frac`` (warn) or 100% (crit) of the configured
  ``dp_epsilon_budget``. A crit here means the formal (ε, δ)
  guarantee the run was provisioned for is EXHAUSTED — every further
  round leaks beyond the stated budget, which is an operator-stop
  condition, not a performance smell.
- ``mfu-collapse`` (warn): a node's live MFU gauge (``devprof_mfu``,
  obs.devprof) fell below ``mfu_collapse_frac`` of the best it has
  published this run — compute throughput collapsed while the node
  still looks alive (input starvation, thermal/SMC throttle, a
  recompile loop eating the round). Delta-state rule: the engine
  remembers each node's best-seen MFU, and a run that never exceeded
  ``mfu_floor`` (CPU smoke runs) can't fire it.
- ``hbm-watermark`` (warn → crit): device peak-memory high-water
  (``devprof_hbm_peak_mb``) reached ``hbm_warn_frac`` (warn) /
  ``hbm_crit_frac`` (crit) of the published HBM limit — the next
  shape bump or retained buffer OOMs the round. Inert when the
  backend publishes no limit (CPU hosts).
- ``partition-suspected`` (crit): the live cohort's per-peer byte
  counters (``peer_bytes_in``/``peer_bytes_out`` in the status
  records) split into 2+ disjoint reachability components — traffic
  keeps flowing INSIDE each side of a cut while every cross-cut
  counter goes one-sided, which is exactly what the plain per-node
  totals cannot show. Needs engine state across evaluations (counter
  deltas); a single snapshot never fires it.

The engine is deliberately read-only and dependency-light: it never
talks to nodes, only to the filesystem artifacts they already publish,
so it runs identically against a live run, a finished run's corpse, or
a synthetic directory in a test.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import time
from typing import Any, Callable

from p2pfl_tpu.obs import flight
from p2pfl_tpu.utils.monitor import DEFAULT_LIVENESS_S, read_statuses

SEVERITY_ORDER = ("ok", "warn", "crit")


def worse(a: str, b: str) -> str:
    return a if SEVERITY_ORDER.index(a) >= SEVERITY_ORDER.index(b) else b


@dataclasses.dataclass(frozen=True)
class Alert:
    """One firing rule instance. ``node`` None = federation-level."""

    rule: str
    severity: str
    node: int | None
    message: str
    since: float

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class HealthConfig:
    """Thresholds for the built-in rules (see module doc)."""

    liveness_s: float = DEFAULT_LIVENESS_S
    stall_rounds: int = 2
    stall_s: float = 30.0
    quorum_frac: float = 0.5
    trust_floor: float = 0.15
    byte_ratio: float = 8.0
    byte_floor: float = 1e6
    recompile_storm: int = 32
    divergence: float = 0.15
    min_cohort: int = 3  # cohort-relative rules need a real median
    # epsilon-budget: warn when dp_epsilon reaches this fraction of
    # dp_epsilon_budget; crit at/over the full budget
    eps_warn_frac: float = 0.8
    # sidecar-stalled: descriptor-queue depth at/above this while slot
    # releases sit flat across two evaluations reads as a wedged aggd
    sidecar_backlog: int = 4
    # mfu-collapse: fire when live MFU drops below this fraction of the
    # node's best-seen; peaks below mfu_floor never arm the rule (CPU
    # runs report achieved-TFLOPs only, or single-digit-permille MFU)
    mfu_collapse_frac: float = 0.5
    mfu_floor: float = 0.02
    # hbm-watermark: peak bytes vs published device limit
    hbm_warn_frac: float = 0.85
    hbm_crit_frac: float = 0.97


@dataclasses.dataclass
class Snapshot:
    """One evaluation's inputs: the status records, a metrics tail,
    and the clock they are judged against."""

    statuses: list[dict[str, Any]]
    metrics: list[dict[str, Any]]
    now: float
    cfg: HealthConfig

    def age(self, rec: dict[str, Any]) -> float:
        return max(self.now - float(rec.get("ts", 0.0)), 0.0)

    def alive(self) -> list[dict[str, Any]]:
        return [r for r in self.statuses
                if self.age(r) <= self.cfg.liveness_s]

    def node_accuracy(self) -> dict[int, float]:
        """Latest accuracy per node: status field first, newest
        Test/accuracy metrics row as fallback."""
        out: dict[int, float] = {}
        for rec in self.metrics:  # oldest→newest; later rows win
            if rec.get("node") is not None and "Test/accuracy" in rec:
                out[int(rec["node"])] = float(rec["Test/accuracy"])
        for rec in self.statuses:
            if rec.get("accuracy") is not None:
                out[int(rec.get("node", -1))] = float(rec["accuracy"])
        return out


# ---------------------------------------------------------------------
# built-in rules: (Snapshot, HealthEngine) -> [finding dict]
# a finding is {"node": int|None, "message": str, "severity"?: str}
# ---------------------------------------------------------------------

def rule_round_stall(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    out = []
    alive = [r for r in snap.alive() if r.get("round") is not None]
    rounds = [int(r["round"]) for r in alive]
    front = max(rounds) if rounds else 0
    for rec in alive:
        node, rnd = int(rec.get("node", -1)), int(rec["round"])
        lag = front - rnd
        seen = eng.round_progress.get(node)
        stuck_s = (snap.now - seen[1]) if seen and seen[0] == rnd else 0.0
        if len(alive) >= 2 and lag >= snap.cfg.stall_rounds:
            out.append({"node": node,
                        "message": f"round {rnd} lags cohort front "
                                   f"{front} by {lag}"})
        elif stuck_s > snap.cfg.stall_s:
            out.append({"node": node,
                        "message": f"round {rnd} unchanged for "
                                   f"{stuck_s:.0f}s"})
    return out


def rule_node_dead(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    dead = [r for r in snap.statuses
            if snap.age(r) > snap.cfg.liveness_s]
    if not dead:
        return []
    n = len(snap.statuses)
    n_alive = n - len(dead)
    quorum = max(1, int(snap.cfg.quorum_frac * n + 0.9999))
    broken = n_alive < quorum
    sev = "crit" if broken else "warn"
    out = [
        {"node": int(r.get("node", -1)), "severity": sev,
         "message": f"silent for {snap.age(r):.0f}s "
                    f"(liveness {snap.cfg.liveness_s:.0f}s)"}
        for r in dead
    ]
    if broken:
        out.append({"node": None, "severity": "crit",
                    "message": f"quorum lost: {n_alive}/{n} alive "
                               f"(need {quorum})"})
    return out


def rule_trust_collapse(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    return [
        {"node": int(r.get("node", -1)),
         "message": f"trust {float(r['trust']):.3f} < floor "
                    f"{snap.cfg.trust_floor}"}
        for r in snap.alive()
        if r.get("trust") is not None
        and float(r["trust"]) < snap.cfg.trust_floor
    ]


def rule_byte_rate(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    recs = [r for r in snap.alive() if r.get("bytes_out") is not None]
    if len(recs) < snap.cfg.min_cohort:
        return []
    vals = sorted(float(r["bytes_out"]) for r in recs)
    med = vals[len(vals) // 2]
    out = []
    for r in recs:
        b = float(r["bytes_out"])
        if b > med * snap.cfg.byte_ratio and b - med > snap.cfg.byte_floor:
            out.append({"node": int(r.get("node", -1)),
                        "message": f"bytes_out {b / 1e6:.1f}MB vs cohort "
                                   f"median {med / 1e6:.1f}MB"})
    return out


def rule_recompile_storm(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    return [
        {"node": int(r.get("node", -1)),
         "message": f"{int(r['recompiles'])} post-warm-up XLA compiles "
                    f"(> {snap.cfg.recompile_storm})"}
        for r in snap.alive()
        if r.get("recompiles") is not None
        and int(r["recompiles"]) > snap.cfg.recompile_storm
    ]


def rule_accuracy_divergence(snap: Snapshot,
                             eng: "HealthEngine") -> list[dict]:
    acc = snap.node_accuracy()
    if len(acc) < snap.cfg.min_cohort:
        return []
    vals = sorted(acc.values())
    med = vals[len(vals) // 2]
    return [
        {"node": node,
         "message": f"accuracy {a:.4f} is {med - a:.4f} below cohort "
                    f"median {med:.4f}"}
        for node, a in sorted(acc.items())
        if med - a > snap.cfg.divergence
    ]


def rule_epsilon_budget(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    """DP spend vs budget, judged per node from the status records the
    accountant already publishes. Fires warn at ``eps_warn_frac`` of
    the budget and crit at/over 100% — past that point the federation
    is spending privacy it never provisioned. Inert unless a record
    carries BOTH a spend and a positive budget, so non-DP runs (and DP
    runs that opted out of a budget) never see it."""
    out = []
    for rec in snap.alive():
        eps, budget = rec.get("dp_epsilon"), rec.get("dp_epsilon_budget")
        if eps is None or not budget:
            continue
        eps, budget = float(eps), float(budget)
        frac = eps / budget
        if frac >= 1.0:
            out.append({"node": int(rec.get("node", -1)), "severity": "crit",
                        "message": f"DP budget exhausted: eps {eps:.3f} >= "
                                   f"budget {budget:.3f}"})
        elif frac >= snap.cfg.eps_warn_frac:
            out.append({"node": int(rec.get("node", -1)), "severity": "warn",
                        "message": f"DP spend eps {eps:.3f} at "
                                   f"{100 * frac:.0f}% of budget "
                                   f"{budget:.3f}"})
    return out


def _peer_totals(rec: dict) -> dict[int, int] | None:
    """Combined per-peer wire totals from one status record; None when
    the record predates the per-link counters. JSON stringifies the
    peer-index keys — normalize back to ints here."""
    pin, pout = rec.get("peer_bytes_in"), rec.get("peer_bytes_out")
    if pin is None and pout is None:
        return None
    tot: dict[int, int] = {}
    for d in (pin or {}, pout or {}):
        for k, v in d.items():
            tot[int(k)] = tot.get(int(k), 0) + int(v)
    return tot


def rule_partition_suspected(snap: Snapshot,
                             eng: "HealthEngine") -> list[dict]:
    """Disjoint reachability from per-link counter deltas: a link
    (a, b) is UP when either side moved bytes toward the other since
    the previous evaluation; a partition is the live cohort splitting
    into 2+ connected components of that graph. One federation-level
    finding (node=None) naming the cohorts — the cut is a property of
    the federation, not of any single node."""
    cur: dict[int, dict[int, int]] = {}
    for rec in snap.alive():
        tot = _peer_totals(rec)
        if tot is not None:
            cur[int(rec.get("node", -1))] = tot
    prev = eng.peer_bytes
    # only nodes seen in BOTH evaluations can be judged: a first-ever
    # snapshot has no delta, and a brand-new node's silence toward
    # everyone would read as an instant (false) singleton cohort
    nodes = sorted(set(cur) & set(prev))
    if len(nodes) < snap.cfg.min_cohort:
        return []

    def grew(a: int, b: int) -> bool:
        return cur[a].get(b, 0) > prev[a].get(b, 0)

    up: dict[int, set[int]] = {a: set() for a in nodes}
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            if grew(a, b) or grew(b, a):
                up[a].add(b)
                up[b].add(a)
    if not any(up.values()):
        # NOTHING moved anywhere — a fully quiescent cohort (finished
        # run corpse, global stall) is round-stall/node-dead territory,
        # not a partition: a real cut keeps each side gossiping inside
        # itself while only the cross-cut counters go one-sided
        return []
    comps, seen = [], set()
    for a in nodes:
        if a in seen:
            continue
        stack, comp = [a], []
        while stack:
            x = stack.pop()
            if x in seen:
                continue
            seen.add(x)
            comp.append(x)
            stack.extend(up[x] - seen)
        comps.append(sorted(comp))
    if len(comps) < 2:
        return []
    comps.sort(key=lambda c: (-len(c), c))
    desc = " | ".join("{" + ",".join(map(str, c)) + "}" for c in comps)
    return [{"node": None,
             "message": f"per-peer traffic one-sided across a cohort "
                        f"cut: {desc}"}]


def rule_sidecar_stalled(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    """A healthy aggd drains its descriptor queue and releases payload
    slots every round; a wedged one (worker stuck in a decode, arena
    exhausted by leaked slots) shows the queue DEEPENING while the
    release counter sits flat. Delta-state rule like
    partition-suspected: judged against the previous evaluation's
    (depth, releases) baseline, so a single busy snapshot can't fire."""
    out = []
    for rec in snap.alive():
        depth, rel = rec.get("aggd_desc_q_depth"), rec.get("aggd_slot_releases")
        if depth is None or rel is None:
            continue
        node = int(rec.get("node", -1))
        prev = eng.aggd_state.get(node)
        if prev is None:
            continue  # first sighting — no delta to judge
        depth, rel = int(depth), int(rel)
        if (depth > prev[0] and depth >= snap.cfg.sidecar_backlog
                and rel == prev[1]):
            out.append({
                "node": node,
                "message": f"aggregation sidecar stalled: descriptor "
                           f"queue {prev[0]}->{depth} deep with slot "
                           f"releases flat at {rel}",
            })
    return out


def rule_mfu_collapse(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    """Live MFU vs the node's own best: utilization is workload- and
    chip-relative, so an absolute floor would be wrong on every part at
    once — but HALVING against your own run's best while still alive is
    a regression wherever it happens. Judged against the engine's
    previous-evaluation peak (``_note_progress`` folds the current
    gauge in afterward), so the collapse is measured, not self-reset."""
    out = []
    for rec in snap.alive():
        v = rec.get("devprof_mfu")
        if v is None:
            continue
        node = int(rec.get("node", -1))
        peak = eng.mfu_peak.get(node, 0.0)
        if peak < snap.cfg.mfu_floor:
            continue  # never armed — nothing meaningful to halve from
        v = float(v)
        if v < snap.cfg.mfu_collapse_frac * peak:
            out.append({
                "node": node,
                "message": f"MFU collapsed to {100 * v:.1f}% from "
                           f"best-seen {100 * peak:.1f}% "
                           f"(< {snap.cfg.mfu_collapse_frac:.0%})",
            })
    return out


def rule_hbm_watermark(snap: Snapshot, eng: "HealthEngine") -> list[dict]:
    """Device peak-memory high-water against the backend's published
    limit. Warn means the headroom is one retained buffer from gone;
    crit means the next allocation of any size may OOM the round.
    Inert without a limit gauge — CPU hosts publish RSS only, and a
    host watermark has no hard ceiling to judge against."""
    out = []
    for rec in snap.alive():
        peak, limit = (rec.get("devprof_hbm_peak_mb"),
                       rec.get("devprof_hbm_limit_mb"))
        if peak is None or not limit:
            continue
        frac = float(peak) / float(limit)
        if frac < snap.cfg.hbm_warn_frac:
            continue
        sev = "crit" if frac >= snap.cfg.hbm_crit_frac else "warn"
        out.append({
            "node": int(rec.get("node", -1)), "severity": sev,
            "message": f"HBM high-water {float(peak):.0f}MB is "
                       f"{100 * frac:.0f}% of the "
                       f"{float(limit):.0f}MB limit",
        })
    return out


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    severity: str  # default severity; a finding may override
    check: Callable[[Snapshot, "HealthEngine"], list[dict]]


def default_rules() -> list[Rule]:
    return [
        Rule("round-stall", "warn", rule_round_stall),
        Rule("node-dead", "warn", rule_node_dead),
        Rule("trust-collapse", "crit", rule_trust_collapse),
        Rule("byte-rate", "warn", rule_byte_rate),
        Rule("recompile-storm", "warn", rule_recompile_storm),
        Rule("accuracy-divergence", "warn", rule_accuracy_divergence),
        Rule("epsilon-budget", "warn", rule_epsilon_budget),
        Rule("partition-suspected", "crit", rule_partition_suspected),
        Rule("sidecar-stalled", "warn", rule_sidecar_stalled),
        Rule("mfu-collapse", "warn", rule_mfu_collapse),
        Rule("hbm-watermark", "warn", rule_hbm_watermark),
    ]


class HealthEngine:
    """Stateful evaluator: tracks which (rule, node) pairs are firing,
    records fire/clear transitions (also into the flight recorder —
    alerts are themselves control events worth a postmortem), and
    remembers per-node round progress so the stall rule can see time,
    not just a single snapshot."""

    def __init__(self, rules: list[Rule] | None = None,
                 config: HealthConfig | None = None):
        self.rules = list(rules) if rules is not None else default_rules()
        self.config = config or HealthConfig()
        self.active: dict[tuple[str, int | None], Alert] = {}
        self.transitions: list[dict[str, Any]] = []
        # node -> (round, ts first seen at that round)
        self.round_progress: dict[int, tuple[int, float]] = {}
        # node -> per-peer combined wire totals at the previous
        # evaluation (partition-suspected's delta baseline)
        self.peer_bytes: dict[int, dict[int, int]] = {}
        # node -> (desc-queue depth, slot releases) at the previous
        # evaluation (sidecar-stalled's delta baseline)
        self.aggd_state: dict[int, tuple[int, int]] = {}
        # node -> best devprof_mfu seen (mfu-collapse's baseline)
        self.mfu_peak: dict[int, float] = {}

    # -- evaluation -----------------------------------------------------
    def _note_progress(self, snap: Snapshot) -> None:
        for rec in snap.statuses:
            if rec.get("round") is None:
                continue
            node, rnd = int(rec.get("node", -1)), int(rec["round"])
            seen = self.round_progress.get(node)
            if seen is None or seen[0] != rnd:
                self.round_progress[node] = (rnd, snap.now)
        for rec in snap.statuses:
            tot = _peer_totals(rec)
            if tot is not None:
                self.peer_bytes[int(rec.get("node", -1))] = tot
        for rec in snap.statuses:
            depth = rec.get("aggd_desc_q_depth")
            rel = rec.get("aggd_slot_releases")
            if depth is not None and rel is not None:
                self.aggd_state[int(rec.get("node", -1))] = (
                    int(depth), int(rel))
        for rec in snap.statuses:
            v = rec.get("devprof_mfu")
            if v is not None:
                node = int(rec.get("node", -1))
                self.mfu_peak[node] = max(self.mfu_peak.get(node, 0.0),
                                          float(v))

    def evaluate(self, statuses: list[dict[str, Any]],
                 metrics: list[dict[str, Any]] | None = None,
                 now: float | None = None) -> list[Alert]:
        now = time.time() if now is None else now
        snap = Snapshot(statuses, list(metrics or ()), now, self.config)
        found: dict[tuple[str, int | None], tuple[str, str]] = {}
        for rule in self.rules:
            for f in rule.check(snap, self):
                key = (rule.name, f.get("node"))
                found[key] = (f.get("severity", rule.severity),
                              f["message"])
        # progress bookkeeping AFTER the rules: a round advance must be
        # judged against the PREVIOUS evaluation's state, or a stalled
        # node would reset its own stall clock every tick
        self._note_progress(snap)
        for key, (sev, msg) in found.items():
            cur = self.active.get(key)
            if cur is None:
                self.active[key] = Alert(key[0], sev, key[1], msg, now)
                self.transitions.append(
                    {"event": "fire", "rule": key[0], "node": key[1],
                     "severity": sev, "message": msg, "ts": now})
                flight.record("health.fire", rule=key[0], node=key[1],
                              severity=sev, message=msg)
            else:  # still firing: refresh message/severity, keep since
                self.active[key] = dataclasses.replace(
                    cur, severity=sev, message=msg)
        for key in [k for k in self.active if k not in found]:
            gone = self.active.pop(key)
            self.transitions.append(
                {"event": "clear", "rule": gone.rule, "node": gone.node,
                 "severity": gone.severity, "ts": now})
            flight.record("health.clear", rule=gone.rule, node=gone.node)
        return self.alerts()

    # -- reading --------------------------------------------------------
    def alerts(self) -> list[Alert]:
        """Active alerts, most severe first, then by rule/node."""
        return sorted(
            self.active.values(),
            key=lambda a: (-SEVERITY_ORDER.index(a.severity), a.rule,
                           -1 if a.node is None else a.node),
        )

    def worst(self) -> str:
        sev = "ok"
        for a in self.active.values():
            sev = worse(sev, a.severity)
        return sev


# ---------------------------------------------------------------------
# filesystem plumbing: evaluate a scenario directory
# ---------------------------------------------------------------------

def tail_jsonl(path: str | pathlib.Path, max_bytes: int = 256 * 1024
               ) -> list[dict[str, Any]]:
    """Tolerant JSONL tail: O(window) read, first line dropped when the
    window is clipped mid-line, and any torn row (a writer's partial
    trailing line observed live) skipped instead of raised."""
    path = pathlib.Path(path)
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            data = f.read()
    except OSError:
        return []
    text = data.decode("utf-8", errors="replace")
    lines = text.splitlines()
    if size > max_bytes and lines:
        lines = lines[1:]
    out = []
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn or foreign row — skip, never raise
        if isinstance(rec, dict):
            out.append(rec)
    return out


def resolve_dirs(directory: str | pathlib.Path
                 ) -> tuple[pathlib.Path, list[pathlib.Path]]:
    """(status dir, metrics.jsonl candidates) for a target that may be
    the status dir itself or the scenario dir containing it."""
    d = pathlib.Path(directory)
    status = d / "status" if (d / "status").is_dir() else d
    metrics = [
        p for p in (status / "metrics.jsonl",
                    status.parent / "metrics.jsonl",
                    d / "metrics.jsonl")
        if p.is_file()
    ]
    seen: set[pathlib.Path] = set()
    uniq = [p for p in metrics
            if p.resolve() not in seen and not seen.add(p.resolve())]
    return status, uniq


def evaluate_dir(directory: str | pathlib.Path,
                 engine: HealthEngine | None = None,
                 now: float | None = None) -> tuple[list[Alert], HealthEngine]:
    """One evaluation over a scenario/status directory. Pass the same
    engine across calls to get firing/clear transitions and the
    stateful stall clock; a fresh engine gives a one-shot view."""
    engine = engine or HealthEngine()
    status_dir, metric_files = resolve_dirs(directory)
    metrics: list[dict[str, Any]] = []
    for p in metric_files:
        metrics.extend(tail_jsonl(p))
    alerts = engine.evaluate(read_statuses(status_dir), metrics, now=now)
    return alerts, engine
