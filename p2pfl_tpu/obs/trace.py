"""Near-zero-overhead span/event tracer for federation hot paths.

Design constraints (in priority order):

1. **Disabled means free.** The data plane pushes ~400k frames per
   24-node round pair (perf.md §7b); instrumentation that allocates
   per frame while off would show up in the very numbers it exists to
   explain. Every hot call site gates on one attribute read
   (``tracer.enabled``); ``span()`` while disabled returns one shared
   ``NULL_SPAN`` singleton (no allocation), and ``count()`` returns
   before touching any state.
2. **Enabled means cheap.** A closed span is one tuple appended to a
   bounded ``collections.deque`` — an atomic, thread-safe operation
   under CPython, so asyncio callbacks and executor threads (the
   learner's fit runs in one, node.py _fit) record into the same ring
   without a lock on the span path. Counters take a small lock; they
   fire at per-message rate only when tracing is on.
3. **Mergeable across processes.** Each tracer records a wall-clock /
   perf_counter anchor pair at reset; exported span timestamps are
   perf_counter-relative (monotonic, immune to NTP steps mid-run) and
   the anchor lets ``p2pfl_tpu.obs.traceview`` shift every process
   onto one wall-clock timeline.

The process tracer is a singleton that is **configured in place**
(never replaced): call sites may cache the reference, so
``configure()`` mutates the one object everyone holds.

Enablement comes from ``P2PFL_TRACE``: unset/``0`` = off, ``1`` = on
(the launcher decides the export dir), any other value = on with that
value as the export directory.

Export is Chrome trace-event JSON (the ``{"traceEvents": [...]}``
object form) — loadable in ``chrome://tracing`` / Perfetto directly,
or merged first via ``python -m p2pfl_tpu.obs.traceview``.

The XLA recompile counter hooks ``jax.monitoring``'s duration events:
every real backend compile fires ``.../backend_compile_duration``
(jit-cache hits do not), so a repeat of the round-7 recompile storm
(~450 mid-round compiles, ≈32% of wall — perf.md §7b) is loudly
visible in every bench record instead of needing a hand profile.
"""

from __future__ import annotations

import itertools
import json
import os
import pathlib
import threading
import time
from collections import deque
from typing import Any

from p2pfl_tpu.obs.records import make_record

ENV_VAR = "P2PFL_TRACE"
_RING_MAX = 1 << 16  # spans kept per process; oldest evicted first


class _NullSpan:
    """The disabled-path span: one shared, stateless instance. Usable
    as a context manager; records nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """One live span. Closing appends (name, lane, t0, dur, args) to
    the owning tracer's ring — a single deque.append, no lock."""

    __slots__ = ("_tracer", "name", "lane", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, lane: str | None,
                 args: dict | None):
        self._tracer = tracer
        self.name = name
        self.lane = lane
        self.args = args
        self.t0 = 0.0

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer._events.append(
            (self.name, self.lane, self.t0,
             time.perf_counter() - self.t0, self.args)
        )
        return False


class Tracer:
    """Span ring + counters + high-water gauges for one process.

    ``lane`` names a timeline row in the merged view — nodes sharing a
    process (k-nodes-per-proc launch layouts) each trace into their own
    lane (``node<idx>``) of the same tracer.
    """

    def __init__(self, ring_max: int = _RING_MAX):
        self.enabled = False
        self.export_dir: pathlib.Path | None = None
        self._ring_max = ring_max
        self._lock = threading.Lock()
        self._reset_locked()

    # -- configuration --------------------------------------------------
    def _reset_locked(self) -> None:
        self._events: deque = deque(maxlen=self._ring_max)
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        # wall/perf anchor pair: spans are perf_counter-relative; the
        # anchor maps them back onto the wall clock for cross-process
        # merging (traceview shifts by wall_t0 deltas)
        self.wall_t0 = time.time()
        self.perf_t0 = time.perf_counter()
        # per-process trace identity: span ids minted by next_span_id()
        # are prefixed with this, so they stay unique in a merged
        # multi-process trace and a wire-propagated parent id resolves
        # without pid coordination
        self.trace_id = os.urandom(4).hex()
        self._span_ids = itertools.count(1)

    def configure(self, enabled: bool | None = None,
                  export_dir: str | pathlib.Path | None = None,
                  ring_max: int | None = None) -> "Tracer":
        """Mutate IN PLACE (call sites cache the singleton)."""
        with self._lock:
            if ring_max is not None and ring_max != self._ring_max:
                self._ring_max = ring_max
                self._events = deque(self._events, maxlen=ring_max)
            if export_dir is not None:
                self.export_dir = pathlib.Path(export_dir)
            if enabled is not None:
                self.enabled = bool(enabled)
        return self

    def reset(self) -> None:
        """Drop all recorded state and re-anchor the clocks."""
        with self._lock:
            self._reset_locked()

    # -- recording ------------------------------------------------------
    def span(self, name: str, lane: str | None = None,
             args: dict | None = None):
        """Context manager timing one operation. Disabled: returns the
        shared NULL_SPAN — no allocation. Hot per-frame sites should
        additionally gate on ``tracer.enabled`` so even the call's
        argument construction is skipped."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, lane, args)

    def next_span_id(self) -> str:
        """Mint a globally-unique span id (``<trace_id>.<n>``) for a
        span whose identity must cross the wire (the ``tc`` header).
        Only meaningful while enabled — callers gate on ``enabled``
        first, so the disabled path never reaches the allocation.
        ``itertools.count`` is GIL-atomic, no lock."""
        return f"{self.trace_id}.{next(self._span_ids)}"

    def count(self, key: str, n: float = 1) -> None:
        """Accumulate a counter (message/byte totals, compile seconds)."""
        if not self.enabled:
            return
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def high_water(self, key: str, value: float) -> None:
        """Record a max-seen gauge (egress-lane queue depths)."""
        if not self.enabled:
            return
        with self._lock:
            if value > self._gauges.get(key, float("-inf")):
                self._gauges[key] = value

    # -- reading --------------------------------------------------------
    def counters(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def spans(self) -> list[tuple]:
        """Snapshot of the ring: (name, lane, t0, dur_s, args) tuples."""
        return list(self._events)

    def summarize(self) -> dict[str, Any]:
        """Per-span-name totals + counters + gauges, in the shared
        record shape (obs.records.make_record) — what bench.py turns
        into attribution keys."""
        agg: dict[str, list[float]] = {}
        for name, _lane, _t0, dur, _args in list(self._events):
            agg.setdefault(name, [0, 0.0, 0.0])
            s = agg[name]
            s[0] += 1
            s[1] += dur
            s[2] = max(s[2], dur)
        return make_record(
            None,
            spans={
                k: {"count": int(c), "total_s": round(t, 6),
                    "max_s": round(m, 6)}
                for k, (c, t, m) in sorted(agg.items())
            },
            counters=self.counters(),
            gauges=self.gauges(),
        )

    # -- export ---------------------------------------------------------
    def chrome_events(self, pid: int | None = None,
                      process_name: str | None = None) -> list[dict]:
        """The ring + counters as Chrome trace-event dicts. Span
        timestamps are µs relative to this tracer's perf anchor; lanes
        map to small tids with thread_name metadata."""
        pid = os.getpid() if pid is None else pid
        lanes: dict[str | None, int] = {None: 0}
        events: list[dict] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name or f"p2pfl[{pid}]"},
        }]
        out: list[dict] = []
        last_ts = 0.0
        for name, lane, t0, dur, args in list(self._events):
            if lane not in lanes:
                lanes[lane] = len(lanes)
            ts = (t0 - self.perf_t0) * 1e6
            last_ts = max(last_ts, ts + dur * 1e6)
            ev = {"name": name, "ph": "X", "pid": pid,
                  "tid": lanes[lane], "ts": ts, "dur": dur * 1e6}
            if args:
                ev["args"] = args
                # cross-process causal edges render as Perfetto flow
                # arrows: a span that minted a wire-propagated id is a
                # flow source; one recorded with a parent id is a sink
                sid = args.get("sid")
                if sid is not None:
                    out.append({"name": "tc", "cat": "tc", "ph": "s",
                                "id": sid, "pid": pid,
                                "tid": lanes[lane], "ts": ts})
                parent = args.get("parent")
                if parent is not None:
                    out.append({"name": "tc", "cat": "tc", "ph": "f",
                                "bp": "e", "id": parent, "pid": pid,
                                "tid": lanes[lane], "ts": ts})
            out.append(ev)
        for lane, tid in lanes.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": lane or "main"},
            })
        events.extend(out)
        for key, val in sorted(self.counters().items()):
            events.append({
                "name": key, "ph": "C", "pid": pid, "tid": 0,
                "ts": last_ts, "args": {"value": val},
            })
        return events

    def export(self, path: str | pathlib.Path | None = None,
               process_name: str | None = None) -> pathlib.Path | None:
        """Write this process's trace file. Default target is
        ``<export_dir>/proc<pid>.trace.json``; returns None when no
        path is known (tracer enabled ad hoc without a directory)."""
        if path is None:
            if self.export_dir is None:
                return None
            path = self.export_dir / f"proc{os.getpid()}.trace.json"
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        doc = {
            "traceEvents": self.chrome_events(process_name=process_name),
            "displayTimeUnit": "ms",
            "metadata": {
                "wall_t0": self.wall_t0,
                "perf_t0": self.perf_t0,
                "pid": os.getpid(),
                "counters": self.counters(),
                "gauges": self.gauges(),
            },
        }
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(doc))
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------
# process singleton
# ---------------------------------------------------------------------
_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process tracer. Cache-safe: configure() mutates in place."""
    return _TRACER


def configure(enabled: bool | None = None,
              export_dir: str | pathlib.Path | None = None,
              ring_max: int | None = None) -> Tracer:
    return _TRACER.configure(enabled=enabled, export_dir=export_dir,
                             ring_max=ring_max)


def configure_from_env(
    default_dir: str | pathlib.Path | None = None,
    env: dict | None = None,
) -> Tracer:
    """Apply the ``P2PFL_TRACE`` convention: unset/empty/``0`` →
    disabled; ``1`` → enabled, exporting to ``default_dir`` (the
    launcher wires it next to the status dir); any other value →
    enabled, exporting to that path."""
    raw = (env if env is not None else os.environ).get(ENV_VAR, "")
    if raw in ("", "0"):
        return _TRACER.configure(enabled=False)
    if raw == "1":
        return _TRACER.configure(enabled=True, export_dir=default_dir)
    return _TRACER.configure(enabled=True, export_dir=raw)


# ---------------------------------------------------------------------
# XLA recompile counter (jax.monitoring)
# ---------------------------------------------------------------------
# Plain module ints, counted whether or not span tracing is on: the
# recompile signal must reach bench records and assertions even in an
# untraced run (tracking two ints per compile is free at compile
# granularity). The tracer mirrors them as counters when enabled.
_xla_lock = threading.Lock()
_xla_installed = False
_xla_recompiles = 0
_xla_compile_s = 0.0


def _on_xla_event(event: str, duration: float, **_kw) -> None:
    # key on backend_compile specifically: jaxpr tracing/lowering
    # events fire even for programs that then hit the compile cache,
    # and internal array-building programs compile too — only
    # backend_compile counts real XLA work
    if "backend_compile" not in event:
        return
    global _xla_recompiles, _xla_compile_s
    with _xla_lock:
        _xla_recompiles += 1
        _xla_compile_s += duration
    if _TRACER.enabled:
        _TRACER.count("xla/backend_compiles")
        _TRACER.count("xla/backend_compile_s", duration)


def install_xla_listener() -> bool:
    """Idempotently hook jax.monitoring's compile-duration events into
    the recompile counter. Returns False when jax (or the monitoring
    module) is unavailable — callers treat the counter as absent."""
    global _xla_installed
    with _xla_lock:
        if _xla_installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        monitoring.register_event_duration_secs_listener(_on_xla_event)
        _xla_installed = True
        return True


def xla_recompiles() -> int:
    """Backend compiles observed since the last reset (0 until
    install_xla_listener() has run)."""
    return _xla_recompiles


def xla_compile_seconds() -> float:
    return _xla_compile_s


def reset_xla_counters() -> None:
    """Zero the compile counters (after warm-up, before a measured
    region — steady-state rounds are expected to stay at 0)."""
    global _xla_recompiles, _xla_compile_s
    with _xla_lock:
        _xla_recompiles = 0
        _xla_compile_s = 0.0
