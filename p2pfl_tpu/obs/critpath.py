"""Per-round critical-path attribution over a merged federation trace.

The tracer (obs.trace) records spans; traceview merges the per-process
files onto one wall-anchored timeline; this module turns that timeline
into the answer operators actually need: *where does the round wall go*.
For every ``node.round`` span it decomposes the round into five
components —

``fit``
    learner compute on this node's lane (``node.fit`` / ``learner.fit``
    interval union, so nesting never double-counts).
``wire``
    network transit attributed from the causal trace context each PARAMS
    frame carries: ``p2p.rx`` spans record the sender's ``tx_ns`` stamp
    and the receiver's ``rx_ns``, and the one-way deltas are corrected
    for clock skew pairwise (see :func:`estimate_skew`) before being
    carved out of the wait bucket they overlap.
``wait``
    quorum / barrier / adoption blocking (``node.wait`` spans) minus the
    aggregation and wire time that elapsed inside those loops.
``aggregate``
    ``session.aggregate`` + ``session.fuse`` device/host reduce time.
``other``
    the residual (voting, serialization, scheduling) clamped >= 0.

plus the federation-wide **longest chain**: a backward walk from the
round's last-closing ``node.round`` span through the causal parent
edges (rx -> tx flow ids) hopping lanes until the round start — the
sequence of lane segments no amount of parallelism can hide.

Clock-skew caveat: ``tx_ns``/``rx_ns`` are ``time.time_ns()`` stamps
from two different hosts. The pairwise estimate assumes the *minimum*
observed one-way delta in each direction rides the same symmetric
network floor; a federation with asymmetric routes will fold half the
asymmetry into ``wire``. Within one host (the simulators, the
multi-process launcher) skew is negligible and the estimate converges
to ~0.

Usage::

    python -m p2pfl_tpu.obs.critpath <trace-dir> [--round N] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

from p2pfl_tpu.obs import traceview

_FIT_SPANS = ("node.fit", "learner.fit")
_WAIT_SPANS = ("node.wait",)
_AGG_SPANS = ("session.aggregate", "session.fuse")
_MAX_CHAIN_HOPS = 64  # backward-walk bound; rounds never chain deeper


# ---------------------------------------------------------------------
# trace model
# ---------------------------------------------------------------------
def load_merged(inputs: list[str]) -> dict:
    """Merged Chrome trace doc from a trace dir / file list (reuses
    traceview's torn-file-tolerant merge)."""
    paths: list = []
    for inp in inputs:
        paths.extend(traceview.find_trace_files(inp))
    return traceview.merge(paths)


def _lane_names(events: list[dict]) -> dict[tuple, str]:
    """(pid, tid) -> lane name from the thread_name metadata events."""
    lanes: dict[tuple, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            lanes[(ev.get("pid"), ev.get("tid"))] = ev["args"]["name"]
    return lanes


def _union_s(ivals: list[tuple[float, float]]) -> float:
    """Total seconds covered by a set of [t0, t1) µs intervals —
    interval union, so nested/overlapping spans count once."""
    total = 0.0
    end = float("-inf")
    for t0, t1 in sorted(ivals):
        if t1 <= end:
            continue
        total += t1 - max(t0, end)
        end = t1
    return total / 1e6


def _overlap_s(inner: list[tuple[float, float]],
               outer: list[tuple[float, float]]) -> float:
    """Seconds of ``inner`` intervals that fall inside ``outer``."""
    total = 0.0
    for a0, a1 in inner:
        for b0, b1 in outer:
            lo, hi = max(a0, b0), min(a1, b1)
            if hi > lo:
                total += hi - lo
    return total / 1e6


def estimate_skew(rx_spans: list[dict]) -> dict[tuple, float]:
    """Pairwise clock-offset estimates in seconds.

    For each directed pair ``(sender, receiver)`` the minimum observed
    one-way delta ``d = rx_ns - tx_ns`` is ``floor_latency + offset``
    where ``offset = clock_recv - clock_send``. With both directions
    observed, ``offset(r-s) ~= (min_d_sr - min_d_rs) / 2`` (the shared
    floor cancels). Returns ``{(sender, receiver): offset_s}``; pairs
    seen in only one direction fall back to offset 0 (skew folded into
    wire — the documented caveat).
    """
    min_d: dict[tuple, float] = {}
    for ev in rx_spans:
        args = ev.get("args") or {}
        s, r = str(args.get("from")), ev["_lane"]
        d = (args["rx_ns"] - args["tx_ns"]) / 1e9
        key = (s, r)
        if key not in min_d or d < min_d[key]:
            min_d[key] = d
    skew: dict[tuple, float] = {}
    for (s, r), d_sr in min_d.items():
        d_rs = min_d.get((r, s))
        skew[(s, r)] = 0.0 if d_rs is None else (d_sr - d_rs) / 2.0
    return skew


# ---------------------------------------------------------------------
# per-round decomposition
# ---------------------------------------------------------------------
def analyze(doc: dict, round_no: int | None = None) -> dict[str, Any]:
    """Per-round critical-path breakdown of a merged trace document.

    Returns ``{"rounds": {N: {"nodes": {name: {...}}, "chain": {...}}}}``
    with per-node ``fit_s/wire_s/wait_s/agg_s/other_s/round_s`` and the
    federation-wide longest chain for each round.
    """
    events = doc.get("traceEvents", [])
    lanes = _lane_names(events)
    spans = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ev = dict(ev)
        key = (ev.get("pid"), ev.get("tid"))
        ev["_lane"] = lanes.get(key, f"{key[0]}/{key[1]}")
        ev["_t0"] = float(ev.get("ts", 0.0))
        ev["_t1"] = ev["_t0"] + float(ev.get("dur", 0.0))
        spans.append(ev)

    # node.round spans indexed by round number
    rounds: dict[int, list[dict]] = {}
    for ev in spans:
        if ev["name"] == "node.round":
            rn = int((ev.get("args") or {}).get("round", -1))
            rounds.setdefault(rn, []).append(ev)
    if round_no is not None:
        rounds = {round_no: rounds.get(round_no, [])}

    all_rx = [ev for ev in spans
              if ev["name"] == "p2p.rx" and (ev.get("args") or {})]
    skew = estimate_skew(all_rx)
    by_sid = {(ev.get("args") or {}).get("sid"): ev for ev in spans
              if ev["name"] == "p2p.tx" and (ev.get("args") or {}).get("sid")}

    out: dict[str, Any] = {"rounds": {}}
    for rn, round_spans in sorted(rounds.items()):
        nodes: dict[str, dict] = {}
        for rspan in round_spans:
            lane, lo, hi = rspan["_lane"], rspan["_t0"], rspan["_t1"]
            in_win = [ev for ev in spans
                      if ev["_lane"] == lane and ev["_t0"] >= lo
                      and ev["_t1"] <= hi]
            fit_iv = [(e["_t0"], e["_t1"]) for e in in_win
                      if e["name"] in _FIT_SPANS]
            wait_iv = [(e["_t0"], e["_t1"]) for e in in_win
                       if e["name"] in _WAIT_SPANS]
            agg_iv = [(e["_t0"], e["_t1"]) for e in in_win
                      if e["name"] in _AGG_SPANS]
            wall = (hi - lo) / 1e6
            fit = _union_s(fit_iv)
            agg = _union_s(agg_iv)
            # wait excludes the aggregation that ran inside its loops
            wait_raw = _union_s(wait_iv) - _overlap_s(agg_iv, wait_iv)
            # wire: skew-corrected one-way latencies of the PARAMS this
            # node received during the round, carved OUT of wait (a
            # node blocks on quorum while frames are in flight)
            wire_raw = 0.0
            for ev in in_win:
                if ev["name"] != "p2p.rx":
                    continue
                args = ev.get("args") or {}
                if int(args.get("round", rn)) != rn:
                    continue
                d = (args["rx_ns"] - args["tx_ns"]) / 1e9
                d -= skew.get((str(args.get("from")), lane), 0.0)
                if 0.0 < d < 60.0:
                    wire_raw += d
            wire = min(wire_raw, max(0.0, wait_raw))
            wait = max(0.0, wait_raw - wire)
            other = max(0.0, wall - fit - wire - wait - agg)
            nodes[lane] = {
                "round_s": round(wall, 6), "fit_s": round(fit, 6),
                "wire_s": round(wire, 6), "wait_s": round(wait, 6),
                "agg_s": round(agg, 6), "other_s": round(other, 6),
            }
        chain = _longest_chain(round_spans, spans, by_sid)
        out["rounds"][rn] = {"nodes": nodes, "chain": chain}
    return out


def _longest_chain(round_spans: list[dict], spans: list[dict],
                   by_sid: dict) -> dict[str, Any]:
    """Backward walk from the round's last-closing ``node.round`` span
    through causal rx->tx edges, hopping lanes. Each chain segment is
    the time spent on one lane between causal hop points — the sequence
    nothing can overlap away."""
    if not round_spans:
        return {"segments": [], "total_s": 0.0}
    tail = max(round_spans, key=lambda e: e["_t1"])
    start = min(e["_t0"] for e in round_spans)
    segments: list[dict] = []
    lane, cursor = tail["_lane"], tail["_t1"]
    for _ in range(_MAX_CHAIN_HOPS):
        # latest causally-parented rx on this lane before the cursor
        rx = None
        for ev in spans:
            if (ev["name"] == "p2p.rx" and ev["_lane"] == lane
                    and start <= ev["_t1"] <= cursor
                    and (ev.get("args") or {}).get("parent") in by_sid):
                if rx is None or ev["_t1"] > rx["_t1"]:
                    rx = ev
        if rx is None:
            segments.append({"node": lane,
                             "span_s": round((cursor - start) / 1e6, 6),
                             "via": "round-start"})
            break
        segments.append({"node": lane,
                         "span_s": round((cursor - rx["_t1"]) / 1e6, 6),
                         "via": "rx from %s" % (rx["args"].get("from"),)})
        tx = by_sid[rx["args"]["parent"]]
        lane, cursor = tx["_lane"], tx["_t0"]
        if cursor <= start:
            break
    segments.reverse()
    total = sum(s["span_s"] for s in segments)
    return {"segments": segments, "total_s": round(total, 6),
            "tail_node": tail["_lane"]}


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------
def _fmt_table(result: dict) -> str:
    lines = []
    for rn, rec in sorted(result["rounds"].items()):
        lines.append(f"round {rn}")
        hdr = (f"  {'NODE':<10}{'ROUND_S':>9}{'FIT':>8}{'WIRE':>8}"
               f"{'WAIT':>8}{'AGG':>8}{'OTHER':>8}")
        lines.append(hdr)
        for name, c in sorted(rec["nodes"].items()):
            lines.append(
                f"  {name:<10}{c['round_s']:>9.3f}{c['fit_s']:>8.3f}"
                f"{c['wire_s']:>8.3f}{c['wait_s']:>8.3f}"
                f"{c['agg_s']:>8.3f}{c['other_s']:>8.3f}")
        chain = rec["chain"]
        if chain["segments"]:
            hops = " -> ".join(f"{s['node']}({s['span_s']:.3f}s)"
                               for s in chain["segments"])
            lines.append(f"  longest chain [{chain['total_s']:.3f}s]: "
                         f"{hops}")
        lines.append("")
    return "\n".join(lines).rstrip()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.obs.critpath")
    ap.add_argument("inputs", nargs="+",
                    help="trace directory (searched recursively for "
                         "*.trace.json) or individual trace files")
    ap.add_argument("--round", type=int, default=None,
                    help="restrict the report to one round")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON instead of a table")
    args = ap.parse_args(argv)
    doc = load_merged(args.inputs)
    if doc["metadata"]["files"] == 0:
        print(f"no readable trace files under {args.inputs}",
              file=sys.stderr)
        return 1
    result = analyze(doc, round_no=args.round)
    if not any(rec["nodes"] for rec in result["rounds"].values()):
        print("no node.round spans found (was tracing enabled?)",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        print(_fmt_table(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
