"""One timestamped record shape for every outward-flowing row.

Before round 9 the repo had two ad-hoc conventions: metrics rows were
``{"ts", "step", "round", "node", ...}`` (utils/metrics.py) and status
files were ``{"node", "ts", ...}`` (utils/monitor.py). Both now stamp
through this helper, and the tracer's summaries use it too — one
``ts`` meaning (epoch seconds, float, stamped at emission) everywhere,
so a merge across streams never has to guess which clock a row used.
"""

from __future__ import annotations

import time
from typing import Any


def make_record(node: int | None, **fields: Any) -> dict[str, Any]:
    """Canonical emission record: ``node`` (None = federation-level),
    ``ts`` (epoch seconds at emission), then the caller's fields. A
    caller-supplied ``ts`` in ``fields`` wins — replayed/merged rows
    keep their original stamp."""
    rec: dict[str, Any] = {"node": node, "ts": time.time()}
    rec.update(fields)
    return rec
