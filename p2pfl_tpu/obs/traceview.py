"""Merge per-process federation trace files onto one timeline.

Each process of a multi-process federation (p2p/launch.py) exports one
``proc<pid>.trace.json`` into the scenario's trace directory
(``<log_dir>/<name>/trace`` — wired the same way as the status dir).
Span timestamps inside each file are perf_counter-relative (monotonic
within the process, meaningless across processes); the file's metadata
carries the wall-clock/perf anchor recorded at tracer reset. The merge
shifts every file onto a shared axis anchored at the EARLIEST process's
wall_t0, so cross-process causality (node 0's send span ending before
node 2's recv span starts) reads directly off the merged view.

Usage::

    python -m p2pfl_tpu.obs.traceview <trace-dir-or-files> [-o merged.json]

The output is one valid Chrome trace-event JSON (object form) —
loadable in chrome://tracing or https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys


def find_trace_files(root: str | pathlib.Path) -> list[pathlib.Path]:
    """All per-process trace files under ``root`` (recursively — a
    scenario log dir works as well as the trace dir itself)."""
    root = pathlib.Path(root)
    if root.is_file():
        return [root]
    return sorted(root.rglob("*.trace.json"))


def merge(paths: list[pathlib.Path | str]) -> dict:
    """One Chrome trace-event document from many per-process files.

    Every event's ``ts`` becomes µs since the earliest file's wall
    anchor: within a file the (monotonic) perf_counter spacing is kept
    exactly; across files only the anchors' wall-clock delta shifts —
    NTP steps mid-run cannot reorder spans within a process.
    """
    docs = []
    for p in paths:
        # A crashed or still-writing process leaves a zero-byte or torn
        # file; skip it with a warning (like status tail_jsonl) so one
        # bad exporter cannot take down the whole postmortem merge.
        try:
            doc = json.loads(pathlib.Path(p).read_text())
        except (OSError, ValueError) as exc:
            print(f"traceview: skipping unreadable trace file {p}: {exc}",
                  file=sys.stderr)
            continue
        if not isinstance(doc, dict):
            print(f"traceview: skipping non-object trace file {p}",
                  file=sys.stderr)
            continue
        meta = doc.get("metadata", {})
        docs.append((float(meta.get("wall_t0", 0.0)), doc))
    if not docs:
        return {"traceEvents": [], "metadata": {"files": 0}}
    base = min(w for w, _ in docs)
    events: list[dict] = []
    counters: dict[str, dict] = {}
    for wall_t0, doc in docs:
        shift_us = (wall_t0 - base) * 1e6
        pid = doc.get("metadata", {}).get("pid")
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            events.append(ev)
        if pid is not None:
            counters[str(pid)] = doc.get("metadata", {}).get("counters", {})
    # metadata ("M") events must precede use of their pid/tid for some
    # viewers; a stable sort keeps them first at equal ts (they carry
    # no ts and sort as -inf here)
    events.sort(key=lambda e: e.get("ts", float("-inf")))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "base_wall_t0": base,
            "files": len(docs),
            "counters_by_pid": counters,
        },
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="p2pfl_tpu.obs.traceview")
    ap.add_argument("inputs", nargs="+",
                    help="trace directory (searched recursively for "
                         "*.trace.json) or individual trace files")
    ap.add_argument("-o", "--output", default="merged.trace.json",
                    help="merged Chrome trace-event JSON path")
    args = ap.parse_args(argv)
    paths: list[pathlib.Path] = []
    for inp in args.inputs:
        paths.extend(find_trace_files(inp))
    if not paths:
        print(f"no *.trace.json files under {args.inputs}", file=sys.stderr)
        return 1
    merged = merge(paths)
    if merged["metadata"]["files"] == 0:
        print(f"no readable trace files under {args.inputs}", file=sys.stderr)
        return 1
    out = pathlib.Path(args.output)
    out.write_text(json.dumps(merged))
    n_spans = sum(1 for e in merged["traceEvents"] if e.get("ph") == "X")
    print(f"merged {len(paths)} file(s), {n_spans} spans -> {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
