"""Sequence-parallel attention over a mesh axis.

Two standard schemes, both pure-JAX collectives (XLA schedules them
over ICI):

- **Ring attention** (`ring_self_attention`): each device holds a
  sequence shard of Q, K, V. K/V blocks rotate around the ring with
  ``ppermute`` while flash-style running-softmax statistics (row max m,
  row sum l) accumulate the output — O(seq/n) memory per device and
  the K/V transfer overlaps with the block matmuls.
- **Ulysses** (`ulysses_attention`): ``all_to_all`` swaps the sharded
  axis from sequence to heads, runs ordinary full-sequence attention
  on head shards, and swaps back — cheaper for many-head models on
  small meshes.

Use inside ``shard_map`` with the sequence axis sharded over
``axis_name``. No counterpart exists in the reference (no attention
models at all — SURVEY.md §5.7); this is the long-context capability
the TPU build adds, wired into models.vit.ViT via ``seq_axis``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def reference_attention(q, k, v):
    """Plain softmax attention ([b, s, h, d] layout) — the on-device
    block used inside the sequence-parallel schemes and the parity
    oracle for attention tests.

    This IS the hot attention path: the round-5 crossover measurements
    (docs/perf.md §5b) showed the hand-tiled Pallas flash kernel
    losing to this XLA block 1.5-1.7x at every shard length up to 4096
    on the bench chip, so the kernel was removed in round 6.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / (d**0.5)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _axis_size(axis_name: str) -> int:
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)  # 0.4.x spelling; folds to a constant


def _block_attn(q, k, v, m, l, o, scale):
    """One blockwise-softmax accumulation step (flash-attention update).

    q: [b, sq, h, d]; k, v: [b, sk, h, d];
    m, l: [b, h, sq] running max / sum; o: [b, h, sq, d] accumulator.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p.astype(v.dtype), v
    ).astype(jnp.float32)
    return m_new, l_new, o_new


def ring_self_attention(q, k, v, axis_name: str):
    """Ring attention: q/k/v are this device's sequence shards
    [batch, seq_shard, heads, head_dim]; returns the local output shard.
    """
    n = _axis_size(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / (d**0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # mark accumulators device-varying so the fori_loop carry types match
    # the collective-produced outputs (JAX >= 0.8 vma tracking)
    if hasattr(jax.lax, "pcast"):
        vary = lambda x: jax.lax.pcast(x, (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):  # pragma: no cover - pre-0.9 spelling
        vary = lambda x: jax.lax.pvary(x, axis_name)
    else:  # 0.4.x: no vma tracking, carries already type-match
        vary = lambda x: x
    m = vary(jnp.full((b, h, sq), -jnp.inf, jnp.float32))
    l = vary(jnp.zeros((b, h, sq), jnp.float32))
    o = vary(jnp.zeros((b, h, sq, d), jnp.float32))

    def body(i, carry):
        m, l, o, k, v = carry
        m, l, o = _block_attn(q, k, v, m, l, o, scale)
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m, l, o, k, v

    m, l, o, k, v = jax.lax.fori_loop(0, n, body, (m, l, o, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [b, sq, h, d]


def ulysses_attention(q, k, v, axis_name: str):
    """Ulysses-style: all_to_all seq→heads, full attention, heads→seq.

    Requires heads divisible by the axis size. q/k/v: sequence shards
    [b, s_shard, h, d]; attention itself sees [b, s_full, h_shard, d].
    """
    n = _axis_size(axis_name)
    b, s, h, d = q.shape
    if h % n:
        raise ValueError(f"heads ({h}) must divide over axis size ({n})")

    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    scale = 1.0 / (d**0.5)
    s_mat = jnp.einsum("bqhd,bkhd->bhqk", qf, kf).astype(jnp.float32) * scale
    p = jax.nn.softmax(s_mat, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vf.dtype), vf)
    return heads_to_seq(out).astype(q.dtype)
