"""Pallas fused train-epoch kernel: a whole local epoch per grid cell
with params + momentum RESIDENT IN VMEM.

docs/perf.md §4: the federated round's floor is set by streaming every
node's full training state through HBM once per SGD step (params,
momentum, gradients — ~5 x |params| bytes per step). XLA cannot hoist
that traffic across `lax.scan` steps because each step's output state
round-trips HBM. This kernel keeps one node's state in VMEM for the
entire epoch: HBM traffic becomes `read params+momentum once + stream
the batches + write params+momentum once` — O(|params|) per EPOCH
instead of per STEP.

Scope (deliberately): 3-layer MLP classifiers (the mnist-mlp /
syscall-mlp family shape) with SGD+momentum and softmax
cross-entropy — dense layers are where VMEM residency pays first
(weights dominate state; convs need a different blocking). One grid
cell per federated node: the stacked `[n, ...]` federation trains
n nodes in parallel, each on its own shard, exactly like the vmapped
XLA path.

Semantics match `learning/learner.make_step_fns` with
``optimizer="sgd"`` over PRE-BATCHED data ``[steps, batch, d]`` (the
caller does the per-epoch shuffle; see `_shuffle` there). Gradients
are mean-over-batch of softmax CE, matching
`objectives.classification`'s masked mean with an all-true mask.

Status: prototype + parity tests; lowers and runs on real-TPU Mosaic.
NOT wired into the round program, because measured honestly it does
not yet win: at the mnist-mlp shape (64 nodes x 235K params, batch 32,
19 steps) the kernel runs 17.4 ms/epoch vs the vmapped XLA path's
12.4 ms on a v5e. The grid serializes nodes (one core), so each cell's
[32, 784]x[784, 256] matmuls underutilize the MXU, while XLA batches
all 64 nodes' matmuls per step — and at this state size (60 MB/step
federation-wide) XLA's HBM streaming isn't the bottleneck anyway. The
VMEM-residency win needs the big-state regime (the 6.4 M-param
FEMNIST CNN, where state streaming is ~10 GB/step), which requires a
conv-capable kernel and per-cell state that still fits VMEM — the
actual round-4 problem. This file is the validated stepping stone:
fused fwd+bwd+SGD math, multi-step fori residency, and the Mosaic
layout constraints are all proven here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _dot(a, b, dims=((1,), (0,))):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _kernel(bx_ref, by_ref,
            w0_ref, b0_ref, w1_ref, b1_ref, w2_ref, b2_ref,
            mw0_ref, mb0_ref, mw1_ref, mb1_ref, mw2_ref, mb2_ref,
            ow0_ref, ob0_ref, ow1_ref, ob1_ref, ow2_ref, ob2_ref,
            omw0_ref, omb0_ref, omw1_ref, omb1_ref, omw2_ref, omb2_ref,
            loss_ref,
            *, steps: int, lr: float, momentum: float, n_classes: int):
    """One node's epoch. All state refs are VMEM blocks; batches
    stream from the node's data block via dynamic slices."""
    import jax.experimental.pallas as pl

    bsz = bx_ref.shape[0] // steps

    def step(i, carry):
        w0, b0, w1, b1, w2, b2, m0, c0, m1, c1, m2, c2, loss_sum = carry
        x = bx_ref[pl.ds(i * bsz, bsz), :].astype(jnp.float32)
        y = by_ref[pl.ds(i * bsz, bsz), :]  # [bsz, 1] int32

        # ---- forward ------------------------------------------------
        h0 = jnp.maximum(_dot(x, w0) + b0, 0.0)  # [bsz, d1]
        h1 = jnp.maximum(_dot(h0, w1) + b1, 0.0)  # [bsz, d2]
        logits = _dot(h1, w2) + b2  # [bsz, C]

        # ---- softmax cross-entropy + dlogits ------------------------
        zmax = jnp.max(logits, axis=-1, keepdims=True)
        z = logits - zmax
        ez = jnp.exp(z)
        se = jnp.sum(ez, axis=-1, keepdims=True)
        logp = z - jnp.log(se)
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) == y
        ).astype(jnp.float32)
        loss = -jnp.sum(onehot * logp) / bsz
        dlogits = (ez / se - onehot) / bsz  # [bsz, C]

        # ---- backward (mean-CE grads) -------------------------------
        gw2 = _dot(h1, dlogits, ((0,), (0,)))  # h1^T @ dlogits
        gb2 = jnp.sum(dlogits, axis=0, keepdims=True)
        dh1 = _dot(dlogits, w2, ((1,), (1,))) * (h1 > 0)
        gw1 = _dot(h0, dh1, ((0,), (0,)))
        gb1 = jnp.sum(dh1, axis=0, keepdims=True)
        dh0 = _dot(dh1, w1, ((1,), (1,))) * (h0 > 0)
        gw0 = _dot(x, dh0, ((0,), (0,)))
        gb0 = jnp.sum(dh0, axis=0, keepdims=True)

        # ---- SGD + momentum (optax.sgd: m = beta*m + g; p -= lr*m) --
        m0 = momentum * m0 + gw0
        c0 = momentum * c0 + gb0
        m1 = momentum * m1 + gw1
        c1 = momentum * c1 + gb1
        m2 = momentum * m2 + gw2
        c2 = momentum * c2 + gb2
        return (w0 - lr * m0, b0 - lr * c0, w1 - lr * m1, b1 - lr * c1,
                w2 - lr * m2, b2 - lr * c2, m0, c0, m1, c1, m2, c2,
                loss_sum + loss)

    init = (
        w0_ref[:].astype(jnp.float32), b0_ref[:].astype(jnp.float32),
        w1_ref[:].astype(jnp.float32), b1_ref[:].astype(jnp.float32),
        w2_ref[:].astype(jnp.float32), b2_ref[:].astype(jnp.float32),
        mw0_ref[:].astype(jnp.float32), mb0_ref[:].astype(jnp.float32),
        mw1_ref[:].astype(jnp.float32), mb1_ref[:].astype(jnp.float32),
        mw2_ref[:].astype(jnp.float32), mb2_ref[:].astype(jnp.float32),
        jnp.zeros((), jnp.float32),
    )
    out = jax.lax.fori_loop(0, steps, step, init)
    w0, b0, w1, b1, w2, b2, m0, c0, m1, c1, m2, c2, loss_sum = out
    del n_classes  # shape-derived in the forward; kept for clarity
    ow0_ref[:] = w0.astype(ow0_ref.dtype)
    ob0_ref[:] = b0.astype(ob0_ref.dtype)
    ow1_ref[:] = w1.astype(ow1_ref.dtype)
    ob1_ref[:] = b1.astype(ob1_ref.dtype)
    ow2_ref[:] = w2.astype(ow2_ref.dtype)
    ob2_ref[:] = b2.astype(ob2_ref.dtype)
    omw0_ref[:] = m0.astype(omw0_ref.dtype)
    omb0_ref[:] = c0.astype(omb0_ref.dtype)
    omw1_ref[:] = m1.astype(omw1_ref.dtype)
    omb1_ref[:] = c1.astype(omb1_ref.dtype)
    omw2_ref[:] = m2.astype(omw2_ref.dtype)
    omb2_ref[:] = c2.astype(omb2_ref.dtype)
    # lane-replicated scalar (degenerate lane-1 layouts are the
    # fragile path on Mosaic)
    loss_ref[:] = jnp.full(loss_ref.shape, loss_sum / steps,
                           loss_ref.dtype)


def fused_mlp_train_epoch(params, momentum_state, bx, by,
                          lr: float, momentum: float = 0.9,
                          batch_size: int = 32,
                          interpret: bool | None = None):
    """One SGD+momentum epoch for a stack of 3-layer MLPs, params
    resident in VMEM.

    ``params`` / ``momentum_state``: tuples ``(w0, b0, w1, b1, w2,
    b2)`` with leading node axis ``[n, ...]`` (biases ``[n, 1, d]``).
    ``bx``: ``[n, steps*batch, d_in]`` pre-shuffled inputs; ``by``:
    ``[n, steps*batch, 1]`` int32 labels — pass data already truncated
    to ``steps*batch`` rows (the `learner._shuffle` product).

    Returns ``(new_params, new_momentum, mean_loss[n])``.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    return _call(params, momentum_state, bx, by, float(lr),
                 float(momentum), int(batch_size), bool(interpret))


_LOSS_LANES = 128  # loss rides a full (8, 128) f32 tile per node


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7))
def _call(params, mom, bx, by, lr, momentum, batch_size, interpret):
    import jax.experimental.pallas as pl

    n, rows, d_in = bx.shape
    steps = rows // batch_size
    if steps == 0:
        steps, batch_size = 1, rows
    if rows % batch_size:
        raise ValueError(
            f"data rows ({rows}) must be a multiple of batch_size "
            f"({batch_size}) — pass the steps*batch truncation the "
            "docstring describes, or the kernel would silently train "
            "at a different batch size"
        )
    n_classes = params[4].shape[-1]

    def spec(x):
        return pl.BlockSpec((None,) + x.shape[1:],
                            lambda i: (i,) + (0,) * (x.ndim - 1))

    in_arrs = (bx, by) + tuple(params) + tuple(mom)
    out_shape = tuple(
        jax.ShapeDtypeStruct(p.shape, p.dtype) for p in params
    ) + tuple(
        jax.ShapeDtypeStruct(m.shape, m.dtype) for m in mom
    ) + (jax.ShapeDtypeStruct((n, 8, _LOSS_LANES), jnp.float32),)
    out_specs = tuple(spec(p) for p in params) + tuple(
        spec(m) for m in mom
    ) + (pl.BlockSpec((None, 8, _LOSS_LANES), lambda i: (i, 0, 0)),)

    out = pl.pallas_call(
        functools.partial(_kernel, steps=steps, lr=lr, momentum=momentum,
                          n_classes=n_classes),
        grid=(n,),
        in_specs=[spec(a) for a in in_arrs],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*in_arrs)
    new_params = out[:6]
    new_mom = out[6:12]
    loss = out[12][:, 0, 0]
    return new_params, new_mom, loss


def mlp_params_to_tuple(stacked_flax_params):
    """Bridge a stacked 3-Dense flax MLP param dict (leading node
    axis) to this kernel's ``(w0, b0, w1, b1, w2, b2)`` layout."""
    p = stacked_flax_params["params"]
    out = []
    for i in range(3):
        d = p[f"Dense_{i}"]
        out += [d["kernel"], d["bias"][:, None, :]]
    return tuple(out)


def tuple_to_mlp_params(t):
    return {
        "params": {
            f"Dense_{i}": {"kernel": t[2 * i], "bias": t[2 * i + 1][:, 0, :]}
            for i in range(3)
        }
    }
