"""TPU compute ops: sequence-parallel attention, fused kernels.

The reference has no native/accelerator ops of its own (SURVEY.md §0:
100% Python over torch kernels); this package is where the TPU build
keeps its hot custom ops:

- ``ring_attention``: blockwise self-attention with K/V rotation via
  ``ppermute`` over a mesh axis — sequence/context parallelism for the
  long-context path (ViT & transformer workloads). The on-device block
  (and the parity oracle) is ``reference_attention``: plain XLA
  softmax attention, which measured FASTER than a hand-tiled Pallas
  flash kernel at every shard length tried (docs/perf.md §5b) — the
  kernel was removed in round 6.
- ``ulysses_attention``: all-to-all (DeepSpeed-Ulysses-style) sequence
  parallelism — heads sharded during attention, sequence sharded
  elsewhere.
- ``pallas_gemm``: hand-tiled GEMM kernels for the FEMNIST round's
  over-floor hot ops (conv1 patches GEMM, dense1 backward) behind a
  measured auto-select gate with XLA fallback (docs/perf.md §6.4).
"""

from p2pfl_tpu.ops.ring_attention import (
    reference_attention,
    ring_self_attention,
    ulysses_attention,
)

__all__ = [
    "reference_attention",
    "ring_self_attention",
    "ulysses_attention",
]
