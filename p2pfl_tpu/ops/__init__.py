"""TPU compute ops: sequence-parallel attention, fused kernels.

The reference has no native/accelerator ops of its own (SURVEY.md §0:
100% Python over torch kernels); this package is where the TPU build
keeps its hot custom ops:

- ``ring_attention``: blockwise self-attention with K/V rotation via
  ``ppermute`` over a mesh axis — sequence/context parallelism for the
  long-context path (ViT & transformer workloads).
- ``ulysses_attention``: all-to-all (DeepSpeed-Ulysses-style) sequence
  parallelism — heads sharded during attention, sequence sharded
  elsewhere.
- ``flash_attention``: Pallas fused attention kernel for the on-device
  block — O(block) memory, streaming K/V through VMEM with running
  softmax stats; shape-guarded fallback to the XLA path.
"""

from p2pfl_tpu.ops.flash import flash_attention, reference_attention
from p2pfl_tpu.ops.ring_attention import ring_self_attention, ulysses_attention

__all__ = [
    "flash_attention",
    "reference_attention",
    "ring_self_attention",
    "ulysses_attention",
]
