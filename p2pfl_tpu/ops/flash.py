"""Pallas flash attention — the TPU-kernel local-attention hot op.

The sequence-parallel layer (p2pfl_tpu.ops.ring_attention) handles the
CROSS-device axis with ppermute; this module handles the ON-device
block: a fused attention kernel that never materializes the [sq, sk]
score matrix in HBM. Per (batch x head, q-block) grid cell, the kernel
streams K/V blocks through VMEM, keeps flash running-softmax stats
(row max m, row sum l) in registers, and hits the MXU with the
q @ k^T and p @ v contractions. Memory: O(block_q x d) per cell
instead of O(sq x sk).

``flash_attention`` is shape-guarded: inputs whose sequence lengths
don't tile by the block sizes (or whose head_dim exceeds one VMEM
lane tile) fall back to the mathematically identical XLA path, so
callers can use it unconditionally. ``interpret=True`` runs the same
kernel on CPU for CI parity tests (tests/test_flash.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def reference_attention(q, k, v):
    """Plain softmax attention ([b, s, h, d] layout) — the fallback and
    the parity oracle."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / (d**0.5)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float):
    """One (batch*head, q-block) grid cell: full pass over K/V blocks
    with flash running-softmax accumulation."""
    bq, d = q_ref.shape
    sk = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * scale

    def body(i, carry):
        m, l, acc = carry
        import jax.experimental.pallas as pl

        k = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(  # [bq, bk] on the MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, sk // block_k, body, (m0, l0, a0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None):
    """Fused attention for [b, s, h, d] inputs; falls back to the XLA
    path when shapes don't tile (the kernel demands sq % block_q ==
    sk % block_k == 0 and head_dim <= 128).

    ``interpret=None`` auto-selects: real Mosaic lowering on TPU, the
    Pallas interpreter on CPU hosts (pallas has no compiled CPU path —
    this keeps the one code path runnable on the CI mesh).

    Differentiable: the forward pass is the fused kernel; the backward
    pass recomputes through the XLA oracle (rematerialized scores on
    backward only — the standard first rung before a fused backward
    kernel)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    if block_q is None or block_k is None or d > 128:
        return reference_attention(q, k, v)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    return _flash(q, k, v, block_q, block_k, interpret)


def _pick_block(s: int, block: int) -> int | None:
    """A block size that tiles the sequence AND the hardware: clamped
    to the sequence, dividing it exactly, sublane-aligned (8 for f32 —
    a 100-row block would fail Mosaic lowering on a real chip even
    though it divides a 100-long sequence). None = use the fallback."""
    b = min(block, s)
    if s % b == 0 and b % 8 == 0:
        return b
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, block_q: int, block_k: int, interpret: bool):
    return _flash_forward(q, k, v, block_q, block_k, interpret)


def _flash_fwd(q, k, v, block_q, block_k, interpret):
    return _flash_forward(q, k, v, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(reference_attention, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _flash_forward(q, k, v, block_q: int, block_k: int, interpret: bool):
    import jax.experimental.pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d**0.5)
    # [b, s, h, d] -> [b*h, s, d]: one grid row per (batch, head)
    def fold(x, s):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    qr, kr, vr = fold(q, sq), fold(k, sk), fold(v, sk)
    out = pl.pallas_call(
        functools.partial(_attn_kernel, block_k=block_k, scale=scale),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
