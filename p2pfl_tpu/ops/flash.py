"""Pallas flash attention — the TPU-kernel local-attention hot op.

The sequence-parallel layer (p2pfl_tpu.ops.ring_attention) handles the
CROSS-device axis with ppermute; this module handles the ON-device
block: fused attention kernels that never materialize the [sq, sk]
score matrix in HBM.

Forward: per (batch x head, q-block) grid cell, K/V blocks stream
through VMEM with flash running-softmax stats (row max m, row sum l)
while the MXU takes both contractions; the log-sum-exp per query row
is emitted alongside the output as the backward residual.

Backward is fused too (no score-matrix rematerialization in XLA): a
dq kernel (per q-block, streaming K/V) and a dk/dv kernel (per
k-block, streaming Q/dO) recompute probabilities from the saved LSE —
the standard flash-attention backward schedule. Memory stays
O(block x d) per grid cell in both directions.

``flash_attention`` is shape-guarded: inputs whose sequence lengths
don't tile by the block sizes (or whose head_dim exceeds one VMEM
lane tile) fall back to the mathematically identical XLA path, so
callers can use it unconditionally. ``interpret=True`` runs the same
kernels on CPU for CI parity tests (tests/test_flash.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


#: lane width of the saved softmax stats (lse/delta): a full TPU lane
#: tile, value replicated, instead of a degenerate lane-dim-1 layout.
#: Cost: 128x the residual memory of a [b*h, sq] stats layout. That is
#: fine under remat (the stats are recomputed per backward block, not
#: saved across the whole forward), which is how the federated ViT
#: configs run flash (use_flash is expected to pair with remat=True —
#: noted at models/vit.py); with
#: remat=False at federation scale (vmapped nodes x batch x heads) the
#: saved residual grows ~128x into GBs — if a no-remat flash path is
#: ever needed, switch to the [b*h, sq] layout with sq in the lane
#: dimension first.
_STATS_LANES = 128


def reference_attention(q, k, v):
    """Plain softmax attention ([b, s, h, d] layout) — the fallback and
    the parity oracle."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / (d**0.5)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                 block_k: int, scale: float):
    """One (batch*head, q-block) grid cell: full pass over K/V blocks
    with flash running-softmax accumulation; also emits the per-row
    log-sum-exp of the SCALED scores (the backward residual)."""
    import jax.experimental.pallas as pl

    bq, d = q_ref.shape
    sk = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * scale

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = _dot(q, k, ((1,), (1,)))  # [bq, bk] on the MXU
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + _dot(p, v, ((1,), (0,)))
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, sk // block_k, body, (m0, l0, a0))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    # stats stored lane-REPLICATED across a full 128-lane tile: a
    # lane-dim-1 layout lowers through Mosaic's degenerate-tile path,
    # which intermittently faulted the TPU worker inside the federated
    # ViT workload (vmap + remat + donation memory pressure); a natural
    # (8, 128) tile costs 127 redundant f32 lanes per row and is
    # robust. Readers slice [:, :1].
    lse_ref[:] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, block_k: int, scale: float):
    """dq for one q-block: stream K/V, recompute P from the saved LSE.
    delta = rowsum(dO * O) — the softmax-jacobian correction."""
    import jax.experimental.pallas as pl

    bq, d = q_ref.shape
    sk = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, :1]  # lane-replicated tile -> [bq, 1]
    delta = delta_ref[:, :1]

    def body(i, acc):
        k = k_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = _dot(q, k, ((1,), (1,))) * scale
        p = jnp.exp(s - lse)
        dp = _dot(do, v, ((1,), (1,)))
        dsm = p * (dp - delta)
        return acc + _dot(dsm, k, ((1,), (0,)))

    acc = jax.lax.fori_loop(0, sk // block_k, body,
                            jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = (acc * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, scale: float):
    """dk and dv for one k-block: stream Q/dO blocks."""
    import jax.experimental.pallas as pl

    bk, d = k_ref.shape
    sq = q_ref.shape[0]
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    def body(i, carry):
        dk_acc, dv_acc = carry
        sl = pl.ds(i * block_q, block_q)
        q = q_ref[sl, :].astype(jnp.float32)
        do = do_ref[sl, :].astype(jnp.float32)
        lse = lse_ref[sl, :1]  # lane-replicated -> [bq, 1]
        delta = delta_ref[sl, :1]
        s = _dot(q, k, ((1,), (1,))) * scale  # [bq, bk]
        p = jnp.exp(s - lse)
        dv_acc = dv_acc + _dot(p, do, ((0,), (0,)))  # p^T @ do
        dp = _dot(do, v, ((1,), (1,)))
        dsm = p * (dp - delta)
        dk_acc = dk_acc + _dot(dsm, q, ((0,), (0,)))  # dsm^T @ q
        return dk_acc, dv_acc

    zero = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, sq // block_q, body, (zero, zero))
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None):
    """Fused attention for [b, s, h, d] inputs; falls back to the XLA
    path when shapes don't tile (the kernels demand sq % block_q ==
    sk % block_k == 0 and head_dim <= 128).

    ``interpret=None`` auto-selects: real Mosaic lowering on TPU, the
    Pallas interpreter on CPU hosts (pallas has no compiled CPU path —
    this keeps the one code path runnable on the CI mesh).

    Differentiable with FUSED kernels in both directions: the forward
    saves the per-row log-sum-exp; the backward recomputes block
    probabilities from it (dq kernel + dk/dv kernel), never
    materializing the score matrix."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    if block_q is None or block_k is None or d > 128:
        return reference_attention(q, k, v)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    return _flash(q, k, v, block_q, block_k, interpret)


def _pick_block(s: int, block: int) -> int | None:
    """A block size that tiles the sequence AND the hardware: clamped
    to the sequence, dividing it exactly, sublane-aligned (8 for f32 —
    a 100-row block would fail Mosaic lowering on a real chip even
    though it divides a 100-long sequence). None = use the fallback."""
    b = min(block, s)
    if s % b == 0 and b % 8 == 0:
        return b
    return None


def _fold(x):
    """[b, s, h, d] -> [b*h, s, d]: one kernel grid row per (batch, head)."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, block_q: int, block_k: int, interpret: bool):
    out, _ = _flash_forward(q, k, v, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_forward(q, k, v, block_q: int, block_k: int, interpret: bool):
    import jax.experimental.pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d**0.5)
    qr, kr, vr = _fold(q), _fold(k), _fold(v)
    out, lse = pl.pallas_call(
        functools.partial(_attn_kernel, block_k=block_k, scale=scale),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            # stats ride a full 128-lane tile, value replicated across
            # lanes (see _attn_kernel) — the earlier lane-dim-1 layout
            # lowered but intermittently faulted the TPU worker under
            # the federated ViT's memory pressure
            pl.BlockSpec((None, block_q, _STATS_LANES),
                         lambda i, j: (i, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, _STATS_LANES), jnp.float32),
        ),
        interpret=interpret,
    )(qr, kr, vr)
    return _unfold(out, b, h), lse


def _flash_bwd(block_q, block_k, interpret, residuals, g):
    import jax.experimental.pallas as pl

    q, k, v, out, lse = residuals
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d**0.5)
    qr, kr, vr = _fold(q), _fold(k), _fold(v)
    dor = _fold(g)
    # softmax-jacobian correction: delta_i = rowsum(dO_i * O_i),
    # lane-replicated like the saved lse (see _attn_kernel)
    delta = jnp.broadcast_to(
        jnp.sum(
            dor.astype(jnp.float32) * _fold(out).astype(jnp.float32),
            axis=-1, keepdims=True,
        ),
        lse.shape,
    )
    qkv_specs = [
        pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # q blk
        pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),  # k full
        pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),  # v full
        pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # do blk
        pl.BlockSpec((None, block_q, _STATS_LANES),
                     lambda i, j: (i, j, 0)),  # lse blk
        pl.BlockSpec((None, block_q, _STATS_LANES),
                     lambda i, j: (i, j, 0)),  # delta
    ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, scale=scale),
        grid=(b * h, sq // block_q),
        in_specs=qkv_specs,
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)

    kv_specs = [
        pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),  # q full
        pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),  # k blk
        pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),  # v blk
        pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),  # do full
        pl.BlockSpec((None, sq, _STATS_LANES),
                     lambda i, j: (i, 0, 0)),  # lse full
        pl.BlockSpec((None, sq, _STATS_LANES),
                     lambda i, j: (i, 0, 0)),  # delta full
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, scale=scale),
        grid=(b * h, sk // block_k),
        in_specs=kv_specs,
        out_specs=(
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ),
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)
    return (_unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h))


_flash.defvjp(_flash_fwd, _flash_bwd)
