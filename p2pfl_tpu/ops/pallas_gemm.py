"""Hand-tiled Pallas GEMM kernels for the ops furthest over their
derived floors (docs/perf.md §6.2): conv1's patches GEMM and dense1's
backward.

Why these two: the §6.2 ceiling table puts the headline FEMNIST round
at 17.9% device-true MFU against a 31% achievable ceiling, and the
overage is concentrated in (a) conv1's `[M≈263k, 25] @ [25, 32]`
patches matmul (13.3 ms measured vs a 2.8 ms floor — XLA's grouped /
small-tile lowering, not the MXU tile fill, is what loses the 4.7x)
and (b) dense1's backward (7.5 ms vs 2.9 ms — two separate XLA GEMMs
re-streaming the [3136, 2048] weight and both activations through
HBM). Neither kernel can beat the MXU's 128-lane tile fill — the
floors already price that in — so the target is XLA's overhead above
the floor, not the floor itself.

Kernel shapes (per federated node; the federation's `vmap` over the
node axis batches `pallas_call` by prepending a grid dimension, so
kernels are written 2-D):

- ``stream_gemm``: ``[M, K] @ [K, N]`` with K, N small (≤128 each,
  i.e. one MXU tile). The weight stays VMEM-stationary across the
  whole grid; M streams through in ``block_m`` row tiles. Covers
  conv1 fwd (``patches @ wf``) and conv1 dgrad (``g @ wf^T`` — same
  shape class with K and N swapped).
- ``stream_wgrad``: ``[M, K]^T @ [M, N] -> [K, N]`` — M-streamed
  accumulation into a stationary f32 output block. Covers conv1
  wgrad. Ragged-edge M tiles mask BOTH operands: an out-of-bounds
  block row may read garbage (even NaN), and ``NaN * 0 = NaN`` would
  poison the accumulator if only one side were zeroed.
- ``_dense_bwd_kernel``: fused dgrad+wgrad for ``y = x @ w`` — grid
  over the contraction-free ``d_in`` axis with the cotangent
  VMEM-stationary, producing ``dx`` and ``dw`` tiles from one pass
  over ``x`` and ``w`` (one HBM read of each instead of XLA's two
  independent GEMMs).
- ``conv2_matmul`` (round 17): the same stationary-weight stream
  recipe applied to conv2's ``[M, 800] @ [800, 64]`` patches GEMM
  (fwd via ``stream_gemm``, wgrad via ``stream_wgrad`` with the
  ragged-tile mask; dgrad stays XLA — §6.2 measures it AT its floor).
  The gate's "conv2" kind measures the whole per-node conv end to
  end — patch formation + kernel vs the grouped-conv lowering — so
  the im2col memory inflation that sank whole-model XLA im2col
  (scripts/exp_im2col.py) is priced into the decision.
- ``sgd_accum`` (round 17): fused SGD(+momentum) update — and
  optionally a weighted FedAvg accumulate — as one M-streamed
  elementwise pass: params, momentum and grads are read once and the
  new params/momentum (plus ``acc + w * p_new``) written back,
  attacking the §6.4 "SGD state stream" overage (6.3 ms measured vs a
  5.0 ms floor). Arithmetic replicates ``optax.sgd`` bit-for-bit
  (same promotion order, accumulator-dtype cast last).

Selection: every call site asks :func:`choose`, which measures the
Pallas and XLA variants at the actual (vmapped) shape on the real
backend — scan-slope timing, same methodology as
``scripts/exp_ceiling.py`` — caches the verdict per shape, and falls
back to XLA whenever Pallas does not win. ``P2PFL_PALLAS_GEMM``
(auto|on|off) forces either path; non-TPU backends always take XLA
(interpret-mode Pallas is a correctness tool, not a fast path). The
decision table is exported into the bench output
(``pallas_gemm_decisions``) so every headline run records the
before/after per-op numbers that justified its path.
"""

from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp

__all__ = [
    "patches_matmul",
    "dense_matmul",
    "conv2_matmul",
    "sgd_accum",
    "fedavg_accum",
    "stream_gemm",
    "stream_wgrad",
    "dense_bwd",
    "choose",
    "decisions",
    "set_nodes_hint",
    "clear_cache",
]

#: env knob: "auto" (measure, default), "on"/"pallas" (force kernels),
#: "off"/"xla" (force XLA). Documented in README + docs/perf.md §6.4.
ENV_KNOB = "P2PFL_PALLAS_GEMM"

_BLOCK_M = 2048  # M rows per grid step (conv1: 129 tiles of 263424)
_BLOCK_D = 448   # d_in rows per dense-bwd grid step (7 x 448 = 3136)


def _interp(interpret):
    if interpret is None:
        return jax.default_backend() == "cpu"
    return bool(interpret)


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# stream_gemm: [M, K] @ [K, N], weight stationary, M streamed
# ---------------------------------------------------------------------------


def _gemm_kernel(x_ref, w_ref, o_ref):
    o_ref[:] = _dot(x_ref[:], w_ref[:], ((1,), (0,))).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _stream_gemm(x, w, block_m, interpret):
    import jax.experimental.pallas as pl

    m, k = x.shape
    n = w.shape[1]
    bm = min(block_m, m)
    out = pl.pallas_call(
        _gemm_kernel,
        grid=(pl.cdiv(m, bm),),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),  # stationary
        ],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(x, w)
    return out


def stream_gemm(x, w, *, block_m: int = _BLOCK_M,
                interpret: bool | None = None):
    """``x [M, K] @ w [K, N]`` with w VMEM-stationary, f32 accumulate.

    Raw kernel (no custom VJP) — the building block for
    :func:`patches_matmul`'s forward and dgrad.
    """
    return _stream_gemm(x, w, int(block_m), _interp(interpret))


# ---------------------------------------------------------------------------
# stream_wgrad: x^T @ g accumulated over M tiles into a stationary block
# ---------------------------------------------------------------------------


def _wgrad_kernel(x_ref, g_ref, o_ref, *, m_total, block_m):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    # ragged edge: mask BOTH operands — out-of-bounds block rows are
    # unspecified (possibly NaN) and NaN * 0 = NaN would poison the
    # accumulator through either side of the dot
    rows = jax.lax.broadcasted_iota(jnp.int32, (x_ref.shape[0], 1), 0)
    ok = rows + i * block_m < m_total
    x = jnp.where(ok, x_ref[:], 0)
    g = jnp.where(ok, g_ref[:], 0)
    o_ref[:] += _dot(x, g, ((0,), (0,))).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _stream_wgrad(x, g, block_m, interpret):
    import jax.experimental.pallas as pl

    m, k = x.shape
    n = g.shape[1]
    bm = min(block_m, m)
    out = pl.pallas_call(
        functools.partial(_wgrad_kernel, m_total=m, block_m=bm),
        grid=(pl.cdiv(m, bm),),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((k, n), lambda i: (0, 0)),  # stationary
        out_shape=jax.ShapeDtypeStruct((k, n), jnp.float32),
        interpret=interpret,
    )(x, g)
    return out


def stream_wgrad(x, g, *, block_m: int = _BLOCK_M,
                 interpret: bool | None = None):
    """``x [M, K]^T @ g [M, N] -> [K, N]`` f32, M-streamed accumulate."""
    return _stream_wgrad(x, g, int(block_m), _interp(interpret))


# ---------------------------------------------------------------------------
# patches_matmul: stream_gemm with a Pallas VJP (conv1 fwd + dgrad + wgrad)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _patches_mm(x, w, block_m, interpret):
    return _stream_gemm(x, w, block_m, interpret)


def _patches_mm_fwd(x, w, block_m, interpret):
    return _patches_mm(x, w, block_m, interpret), (x, w)


def _patches_mm_bwd(block_m, interpret, res, g):
    x, w = res
    # dgrad is the same small-tile shape class ([M, N] @ [N, K]);
    # dead-code eliminated when x is a non-differentiated input
    # (conv1: the image layer needs no dx)
    dx = _stream_gemm(g, w.T, block_m, interpret).astype(x.dtype)
    dw = _stream_wgrad(x, g, block_m, interpret).astype(w.dtype)
    return dx, dw


_patches_mm.defvjp(_patches_mm_fwd, _patches_mm_bwd)


def patches_matmul(x, w, *, block_m: int = _BLOCK_M,
                   interpret: bool | None = None):
    """``x [M, K] @ w [K, N]`` (K, N ≤ 128) — Pallas fwd, dgrad and
    wgrad. The conv1 hot path: patches flattened to 2-D rows."""
    if x.ndim != 2 or w.ndim != 2:
        raise ValueError(f"2-D operands required, got {x.shape} @ {w.shape}")
    return _patches_mm(x, w, int(block_m), _interp(interpret))


# ---------------------------------------------------------------------------
# dense_bwd: fused dgrad + wgrad for y = x @ w (dense1 backward)
# ---------------------------------------------------------------------------


def _dense_bwd_kernel(g_ref, x_ref, w_ref, dx_ref, dw_ref):
    # g [B, H] stationary; x [B, TD], w [TD, H] stream over d_in.
    # Contractions run over full axes (B, H) — a ragged d_in edge only
    # produces garbage in output rows/columns the BlockSpec masks off
    # on write, so no operand masking is needed here.
    g = g_ref[:]
    dx_ref[:] = _dot(g, w_ref[:], ((1,), (1,))).astype(dx_ref.dtype)
    dw_ref[:] = _dot(x_ref[:], g, ((0,), (0,))).astype(dw_ref.dtype)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _dense_bwd(x, w, g, block_d, interpret):
    import jax.experimental.pallas as pl

    b, d_in = x.shape
    h = w.shape[1]
    bd = min(block_d, d_in)
    dx, dw = pl.pallas_call(
        _dense_bwd_kernel,
        grid=(pl.cdiv(d_in, bd),),
        in_specs=[
            pl.BlockSpec((b, h), lambda i: (0, 0)),  # cotangent stationary
            pl.BlockSpec((b, bd), lambda i: (0, i)),
            pl.BlockSpec((bd, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((b, bd), lambda i: (0, i)),
            pl.BlockSpec((bd, h), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, d_in), x.dtype),
            jax.ShapeDtypeStruct((d_in, h), w.dtype),
        ],
        interpret=interpret,
    )(g, x, w)
    return dx, dw


def dense_bwd(x, w, g, *, block_d: int = _BLOCK_D,
              interpret: bool | None = None):
    """Fused backward of ``y = x @ w``: ``(dx, dw)`` from one pass
    over x and w (cotangent ``g`` VMEM-stationary)."""
    return _dense_bwd(x, w, g, int(block_d), _interp(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _dense_mm(x, w, block_d, interpret):
    # forward stays XLA — it sits near its floor (§6.2); only the
    # backward is over-floor enough to pay for a kernel
    return _dot(x, w, ((1,), (0,))).astype(x.dtype)


def _dense_mm_fwd(x, w, block_d, interpret):
    return _dense_mm(x, w, block_d, interpret), (x, w)


def _dense_mm_bwd(block_d, interpret, res, g):
    x, w = res
    dx, dw = _dense_bwd(x, w, g.astype(x.dtype), block_d=block_d,
                        interpret=interpret)
    return dx, dw


_dense_mm.defvjp(_dense_mm_fwd, _dense_mm_bwd)


def dense_matmul(x, w, *, block_d: int = _BLOCK_D,
                 interpret: bool | None = None):
    """``x [B, D] @ w [D, H]`` — XLA forward, fused Pallas backward."""
    if x.ndim != 2 or w.ndim != 2:
        raise ValueError(f"2-D operands required, got {x.shape} @ {w.shape}")
    return _dense_mm(x, w, int(block_d), _interp(interpret))


# ---------------------------------------------------------------------------
# conv2_matmul: stream_gemm fwd + stream_wgrad, XLA dgrad (conv2 hot path)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _conv2_mm(x, w, block_m, interpret):
    return _stream_gemm(x, w, block_m, interpret)


def _conv2_mm_fwd(x, w, block_m, interpret):
    return _conv2_mm(x, w, block_m, interpret), (x, w)


def _conv2_mm_bwd(block_m, interpret, res, g):
    x, w = res
    # dgrad stays XLA: §6.2 measures conv2's dgrad AT its derived
    # floor (2.0 ms vs 2.0), so a kernel has nothing to win there —
    # only fwd (5.9 vs 4.9) and wgrad (7.3 vs 4.9) are over-floor
    dx = _dot(g, w, ((1,), (1,))).astype(x.dtype)
    dw = _stream_wgrad(x, g, block_m, interpret).astype(w.dtype)
    return dx, dw


_conv2_mm.defvjp(_conv2_mm_fwd, _conv2_mm_bwd)


def conv2_matmul(x, w, *, block_m: int = _BLOCK_M,
                 interpret: bool | None = None):
    """``x [M, K] @ w [K, N]`` for the conv2 shape class (K up to
    ~1024 — one stationary VMEM tile pair, e.g. the LEAF CNN's
    ``[M, 800] @ [800, 64]``): Pallas fwd and wgrad, XLA dgrad."""
    if x.ndim != 2 or w.ndim != 2:
        raise ValueError(f"2-D operands required, got {x.shape} @ {w.shape}")
    return _conv2_mm(x, w, int(block_m), _interp(interpret))


# ---------------------------------------------------------------------------
# sgd_accum: fused SGD(+momentum) update + optional weighted accumulate
# ---------------------------------------------------------------------------


def _decayed_trace(m_ref, momentum):
    # replicate optax.sgd's promotion order exactly: ``decay * trace``
    # is a trace-dtype multiply (numpy weak typing casts the Python
    # float down), THEN the f32 grad add promotes. Pallas evaluates
    # narrow-dtype arithmetic in f32 WITHOUT the intermediate rounding,
    # so round the product back to the trace dtype by hand — a
    # bf16*bf16 product fits f32 exactly, making round-once identical
    # to a native bf16 multiply.
    decay = jnp.asarray(momentum, m_ref.dtype).astype(jnp.float32)
    return (decay * m_ref[:].astype(jnp.float32)).astype(m_ref.dtype)


def _sgd_kernel(p_ref, m_ref, g_ref, lr_ref, p_out, m_out, *, momentum):
    # the accumulator-dtype cast applies to the STORED state only; the
    # param update consumes the uncast f32 trace (optax semantics)
    m_new = g_ref[:] + _decayed_trace(m_ref, momentum)
    p_out[:] = (p_ref[:] + m_new * -lr_ref[0, 0]).astype(p_out.dtype)
    m_out[:] = m_new.astype(m_out.dtype)


def _sgd_accum_kernel(p_ref, m_ref, g_ref, lr_ref, acc_ref, w_ref,
                      p_out, m_out, acc_out, *, momentum):
    m_new = g_ref[:] + _decayed_trace(m_ref, momentum)
    p_new = (p_ref[:] + m_new * -lr_ref[0, 0]).astype(p_out.dtype)
    p_out[:] = p_new
    m_out[:] = m_new.astype(m_out.dtype)
    acc_out[:] = acc_ref[:] + w_ref[0, 0] * p_new.astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _sgd(p, m, g, lr, momentum, block_m, interpret):
    import jax.experimental.pallas as pl

    rows, cols = p.shape
    bm = min(block_m, rows)
    tile = pl.BlockSpec((bm, cols), lambda i: (i, 0))
    one = pl.BlockSpec((1, 1), lambda i: (0, 0))
    # elementwise over rows: a ragged last tile only reads garbage into
    # output rows the BlockSpec masks off on write — nothing crosses
    # rows, so no operand masking is needed (unlike the wgrad reduce)
    return pl.pallas_call(
        functools.partial(_sgd_kernel, momentum=momentum),
        grid=(pl.cdiv(rows, bm),),
        in_specs=[tile, tile, tile, one],
        out_specs=[tile, tile],
        out_shape=[
            jax.ShapeDtypeStruct(p.shape, p.dtype),
            jax.ShapeDtypeStruct(m.shape, m.dtype),
        ],
        interpret=interpret,
    )(p, m, g, lr)


@functools.partial(jax.jit, static_argnums=(6, 7, 8))
def _sgd_acc(p, m, g, lr, acc, w, momentum, block_m, interpret):
    import jax.experimental.pallas as pl

    rows, cols = p.shape
    bm = min(block_m, rows)
    tile = pl.BlockSpec((bm, cols), lambda i: (i, 0))
    one = pl.BlockSpec((1, 1), lambda i: (0, 0))
    return pl.pallas_call(
        functools.partial(_sgd_accum_kernel, momentum=momentum),
        grid=(pl.cdiv(rows, bm),),
        in_specs=[tile, tile, tile, one, tile, one],
        out_specs=[tile, tile, tile],
        out_shape=[
            jax.ShapeDtypeStruct(p.shape, p.dtype),
            jax.ShapeDtypeStruct(m.shape, m.dtype),
            jax.ShapeDtypeStruct(acc.shape, jnp.float32),
        ],
        interpret=interpret,
    )(p, m, g, lr, acc, w)


def _as2d(a):
    return a.reshape(-1, a.shape[-1]) if a.ndim >= 2 else a.reshape(1, -1)


def sgd_accum(p, m, g, lr_gate, *, momentum: float,
              acc=None, weight=None, block_m: int = _BLOCK_M,
              interpret: bool | None = None):
    """Fused ``optax.sgd`` step — and optionally the FedAvg
    contribution — in one streaming pass over the leaf.

    ``m_new = g + momentum * m``; ``p_new = p + m_new * -lr_gate``;
    with ``acc``/``weight`` given, also ``acc_new = acc + weight *
    p_new`` (f32) so the optimizer step and the aggregation
    contribution read the params once. ``lr_gate`` is the learning
    rate pre-multiplied by the federation's update gate (1.0/0.0):
    a gated-off leaf adds exactly ±0.0, i.e. keeps its params
    bit-exactly while its momentum decays — the learner's ``where``
    gate semantics. Returns ``(p_new, m_stored)`` or ``(p_new,
    m_stored, acc_new)``; arbitrary-rank leaves are streamed as
    ``[prod(shape[:-1]), shape[-1]]``.
    """
    shape = p.shape
    p2, m2, g2 = _as2d(p), _as2d(m), _as2d(g)
    lr2 = jnp.asarray(lr_gate, jnp.float32).reshape(1, 1)
    itp = _interp(interpret)
    if acc is None:
        p_new, m_new = _sgd(p2, m2, g2, lr2, float(momentum),
                            int(block_m), itp)
        return p_new.reshape(shape), m_new.reshape(m.shape)
    w2 = jnp.asarray(weight, jnp.float32).reshape(1, 1)
    acc2 = _as2d(acc)
    p_new, m_new, acc_new = _sgd_acc(p2, m2, g2, lr2, acc2, w2,
                                     float(momentum), int(block_m), itp)
    return (p_new.reshape(shape), m_new.reshape(m.shape),
            acc_new.reshape(acc.shape))


def fedavg_accum(p, acc, weight, block_m: int = _BLOCK_M,
                 interpret: bool | None = None):
    """FedAvg accumulate as a *null* ``sgd_accum`` step (round 20):
    ``acc_new = acc + weight * p`` (f32) in one streaming pass, sharing
    the ``_sgd_accum_kernel`` the learner's fused optimizer uses — and
    therefore the same measured ``choose("sgd_accum", ...)`` decision.

    The optimizer half runs with ``g = 0``, ``momentum = 0``,
    ``lr_gate = 0``: ``m_new = 0``, ``p_new = (p + 0 * -0).astype(
    p.dtype) = p`` — the param stream passes through untouched (the
    ``+0.0`` can at most flip a ``-0.0`` to ``+0.0``, inert inside the
    weighted sum), so only the accumulate line does work. This is how
    the cross-device round's fit-epilogue accumulate
    (``parallel/federated.py``) rides the kernel without a second
    kernel body to parity-test. ``acc`` must match ``p``'s streamed 2-D
    shape ``[prod(shape[:-1]), shape[-1]]``. Returns ``acc_new`` only.
    """
    z = jnp.zeros_like(p)
    _, _, acc_new = sgd_accum(p, z, z, 0.0, momentum=0.0, acc=acc,
                              weight=weight, block_m=block_m,
                              interpret=interpret)
    return acc_new


# ---------------------------------------------------------------------------
# measured auto-select gate
# ---------------------------------------------------------------------------

_decisions: dict[str, dict] = {}
_nodes_hint: int = 1


def set_nodes_hint(n: int) -> None:
    """Tell the gate how wide the federation's node vmap is — the
    microbenchmark measures the batched shape actually run. Called by
    ``parallel.federated.init_federation``; defaults to 1 (single
    learner)."""
    global _nodes_hint
    _nodes_hint = max(int(n), 1)


def decisions() -> dict[str, dict]:
    """JSON-able copy of every gate decision this process made
    (impl, measured ms per variant, forcing). Exported by bench.py."""
    return {k: dict(v) for k, v in _decisions.items()}


def clear_cache() -> None:
    _decisions.clear()


def _slope_ms(fn, args, r1: int = 2, r2: int = 6) -> float:
    """Per-call ms net of dispatch/sync overhead: time a scan of r2
    repeats minus a scan of r1 repeats over (r2 - r1) — the
    scripts/exp_ceiling.py scan-slope methodology."""

    def repeat(reps):
        @jax.jit
        def run(x0, *rest):
            def body(x, _):
                out = fn(x, *rest)
                first = jax.tree.leaves(out)[0]
                # fold one element back into the carry so scan cannot
                # hoist or elide the repeated call
                return x + (first.reshape(-1)[0] * 0).astype(x.dtype), None

            return jax.lax.scan(body, x0, None, length=reps)[0]

        run(*args).block_until_ready()  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            run(*args).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    return max((repeat(r2) - repeat(r1)) / (r2 - r1) * 1e3, 0.0)


def _measure(kind: str, key: str, pallas_fn, xla_fn, args) -> str:
    try:
        p_ms = _slope_ms(pallas_fn, args)
        x_ms = _slope_ms(xla_fn, args)
    except Exception as e:  # Mosaic lowering/launch failure -> XLA
        _decisions[key] = {"kind": kind, "impl": "xla", "forced": False,
                           "error": f"{type(e).__name__}: {e}"}
        return "xla"
    impl = "pallas" if p_ms < x_ms else "xla"
    _decisions[key] = {"kind": kind, "impl": impl, "forced": False,
                       "pallas_ms": round(p_ms, 4), "xla_ms": round(x_ms, 4)}
    return impl


def choose(kind: str, shapes: tuple, dtype) -> str:
    """Pick "pallas" or "xla" for one op instance.

    ``kind``: "patches" (conv1 fwd+bwd GEMM), "dense_bwd" (dense1
    fused backward), "conv2" (big-contraction conv as patches stream
    vs grouped conv — ``shapes`` carries ``((M, K), (K, N), x_4d,
    (kh, kw))`` so the measurement can rebuild the whole conv, patch
    formation included), or "sgd_accum" (fused optimizer stream).
    ``shapes``: the per-node operand shapes as seen at the call site.
    Measured decisions are cached per (kind, shapes, dtype, nodes,
    backend); env/backend forcings are recorded too so the bench
    table shows WHY a path ran.
    """
    backend = jax.default_backend()
    dt = jnp.dtype(dtype).name
    n = _nodes_hint
    key = f"{kind} n{n} {'x'.join(map(str, shapes[0]))}@" \
          f"{'x'.join(map(str, shapes[1]))} {dt} {backend}"
    cached = _decisions.get(key)
    if cached is not None:
        return cached["impl"]

    env = os.environ.get(ENV_KNOB, "auto").strip().lower()
    if env in ("off", "0", "xla", "false"):
        _decisions[key] = {"kind": kind, "impl": "xla", "forced": True,
                           "reason": f"{ENV_KNOB}={env}"}
    elif env in ("on", "1", "pallas", "true"):
        _decisions[key] = {"kind": kind, "impl": "pallas", "forced": True,
                           "reason": f"{ENV_KNOB}={env}"}
    elif backend != "tpu":
        # interpret-mode Pallas is for parity testing, never for speed
        _decisions[key] = {"kind": kind, "impl": "xla", "forced": True,
                           "reason": f"backend={backend}"}
    elif _flops(kind, shapes) * n < _MIN_GATE_FLOPS:
        # don't burn measurement time on trivial instances (model.init
        # traces with batch 1; tiny eval shapes) — XLA is fine there
        _decisions[key] = {"kind": kind, "impl": "xla", "forced": True,
                           "reason": "below measurement threshold"}
    else:
        return _measure_kind(kind, key, shapes, dtype, n)
    return _decisions[key]["impl"]


_MIN_GATE_FLOPS = 1e8  # per-instance GEMM flops worth measuring


def _flops(kind, shapes) -> float:
    (m, k) = shapes[0]
    if kind == "sgd_accum":
        # memory-bound elementwise stream: score by elements moved,
        # not GEMM flops (which would never clear the threshold)
        return 8.0 * m * k
    (_, n_out) = shapes[1]
    mult = 2.0 if kind == "dense_bwd" else 1.0  # bwd = two GEMMs
    return 2.0 * m * k * n_out * mult


def _measure_kind(kind: str, key: str, shapes, dtype, n) -> str:
    if kind == "patches":
        (m, k), (_, out_n) = shapes
        x = jnp.zeros((n, m, k), dtype)
        w = jnp.zeros((n, k, out_n), dtype)

        def pallas_fn(x, w):
            f = lambda a, b: patches_matmul(a, b)
            return _grad_through(jax.vmap(f))(x, w)

        def xla_fn(x, w):
            f = lambda a, b: _dot(a, b, ((1,), (0,))).astype(a.dtype)
            return _grad_through(jax.vmap(f))(x, w)

        return _measure(kind, key, pallas_fn, xla_fn, (x, w))
    if kind == "dense_bwd":
        (b, d_in), (_, h) = shapes
        x = jnp.zeros((n, b, d_in), dtype)
        w = jnp.zeros((n, d_in, h), dtype)

        def pallas_fn(x, w):
            f = lambda a, b: dense_matmul(a, b)
            return _grad_through(jax.vmap(f))(x, w)

        def xla_fn(x, w):
            f = lambda a, b: _dot(a, b, ((1,), (0,))).astype(a.dtype)
            return _grad_through(jax.vmap(f))(x, w)

        return _measure(kind, key, pallas_fn, xla_fn, (x, w))
    if kind == "conv2":
        (_, kk), (_, f_out) = shapes[0], shapes[1]
        b, hh, ww, cin = shapes[2]
        kh, kw = shapes[3]
        x = jnp.zeros((n, b, hh, ww, cin), dtype)
        kern = jnp.zeros((n, kh, kw, cin, f_out), dtype)

        def pallas_fn(x, kern):
            def one(a, kr):
                patches = jax.lax.conv_general_dilated_patches(
                    a, (kh, kw), (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                wf = kr.transpose(2, 0, 1, 3).reshape(kk, f_out)
                return conv2_matmul(patches.reshape(-1, kk), wf)

            return _grad_through(jax.vmap(one))(x, kern)

        def xla_fn(x, kern):
            # the incumbent is the grouped-conv lowering, NOT an XLA
            # patches matmul: patch materialization at K=800 is a 25x
            # memory inflation (scripts/exp_im2col.py), so the fair
            # fight is end-to-end conv vs end-to-end patches+kernel
            def one(a, kr):
                return jax.lax.conv_general_dilated(
                    a, kr, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )

            return _grad_through(jax.vmap(one))(x, kern)

        return _measure(kind, key, pallas_fn, xla_fn, (x, kern))
    if kind == "sgd_accum":
        (m_rows, cols) = shapes[0]
        p = jnp.zeros((n, m_rows, cols), dtype)
        mom = jnp.zeros((n, m_rows, cols), dtype)
        g = jnp.zeros((n, m_rows, cols), dtype)
        lr = jnp.full((n,), 0.1, jnp.float32)

        def pallas_fn(p, mom, g, lr):
            f = lambda a, b, c, l: sgd_accum(a, b, c, l, momentum=0.9)
            return jax.vmap(f)(p, mom, g, lr)

        def xla_fn(p, mom, g, lr):
            def f(a, b, c, l):
                m_new = c + 0.9 * b
                return a + m_new * -l, m_new.astype(b.dtype)

            return jax.vmap(f)(p, mom, g, lr)

        return _measure(kind, key, pallas_fn, xla_fn, (p, mom, g, lr))
    raise ValueError(f"unknown gate kind: {kind!r}")


def _grad_through(f):
    """Measure fwd+bwd together — the gate's question is the round's
    train step, which always differentiates these ops."""

    def g(x, w):
        loss = lambda a, b: jnp.sum(f(a, b).astype(jnp.float32))
        dx, dw = jax.grad(loss, argnums=(0, 1))(x, w)
        return dx

    return g
