"""Federation control plane: the host-side round state machine.

The reference runs its control plane as a thread soup — heartbeater,
gossiper, per-connection readers, lock-as-condition-variable idioms
(SURVEY.md §5.2). Here it is an explicit, deterministic state machine:
membership (heartbeats/eviction), SDFL leadership rotation, fault
injection, and checkpointing advance round-by-round on the host, and
each round hands fixed-shape arrays (mixing matrix, adopt vector,
alive mask) to the jitted dataplane in p2pfl_tpu.parallel.
"""

from p2pfl_tpu.federation.events import Events, Observable, Observer
from p2pfl_tpu.federation.membership import Membership
from p2pfl_tpu.federation.sampling import sample_clients
from p2pfl_tpu.federation.checkpoint import load_checkpoint, save_checkpoint
from p2pfl_tpu.federation.scenario import (
    CrossDeviceScenario,
    Scenario,
    ScenarioResult,
)

__all__ = [
    "Events",
    "Observable",
    "Observer",
    "Membership",
    "sample_clients",
    "load_checkpoint",
    "save_checkpoint",
    "CrossDeviceScenario",
    "Scenario",
    "ScenarioResult",
]
