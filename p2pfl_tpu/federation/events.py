"""In-process event bus.

Parity with fedstellar/utils/observer.py (Events/Observable/Observer,
16 event types, synchronous fan-out :125-137), with the event set
reduced to what survives the synchronous-dataplane redesign: transport
events (BEAT/CONNECT...) that existed to glue threads together are
replaced by round-lifecycle events the observability layer subscribes
to.
"""

from __future__ import annotations

import enum
from typing import Any, Callable


class Events(enum.Enum):
    ROUND_STARTED = "round_started"
    TRAIN_FINISHED = "train_finished"
    AGGREGATION_FINISHED = "aggregation_finished"  # observer.py:34 analog
    ROUND_FINISHED = "round_finished"
    NODE_DIED = "node_died"  # heartbeat eviction (heartbeater.py:88-101)
    NODE_RECOVERED = "node_recovered"
    # round 11 elasticity: a node entered through the live join
    # handshake (CONNECT hello + checkpoint-format model fetch)
    NODE_JOINED = "node_joined"
    LEADERSHIP_TRANSFERRED = "leadership_transferred"  # node.py:676-686
    LEARNING_FINISHED = "learning_finished"
    METRICS_REPORTED = "metrics_reported"  # REPORT_STATUS analog
    CHECKPOINT_SAVED = "checkpoint_saved"
    # round 14 partition tolerance: a scripted (or netem-scheduled)
    # partition severed the link sets between cohort groups / healed
    # them again; heal is the amnesty trigger for sticky evictions
    LINK_PARTITIONED = "link_partitioned"
    LINK_HEALED = "link_healed"
    # round 14 crash consistency: a node came back through the
    # checkpoint-resume path (vs NODE_JOINED's fresh STATE_SYNC join)
    NODE_RESTARTED = "node_restarted"


class Observer:
    """Receives events. Parity with observer.py's Observer interface."""

    def update(self, event: Events, payload: Any = None) -> None:
        raise NotImplementedError


class Observable:
    """Synchronous fan-out to registered observers (observer.py:125-137).

    Callables are accepted as observers too: ``obs(event, payload)``.
    """

    def __init__(self):
        self._observers: list[Observer | Callable] = []

    def add_observer(self, obs: Observer | Callable) -> None:
        self._observers.append(obs)

    def get_observers(self) -> list:
        return list(self._observers)

    def notify(self, event: Events, payload: Any = None) -> None:
        for obs in self._observers:
            if isinstance(obs, Observer):
                obs.update(event, payload)
            else:
                obs(event, payload)
