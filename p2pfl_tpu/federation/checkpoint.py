"""Round-boundary checkpoint / resume.

The reference has NO checkpointing (lightninglearner.py:190 disables
it; a restarted node cannot rejoin — SURVEY.md §5.4). Here the whole
federation state (stacked params + opt state + rngs + round + alive
mask) serializes to one msgpack file at round boundaries, and a run
can resume exactly where it stopped.
"""

from __future__ import annotations

import os
import pathlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization as flax_ser

from p2pfl_tpu.obs import flight
from p2pfl_tpu.parallel.federated import FederatedState

_SUFFIX = ".ckpt.msgpack"


def checkpoint_path(directory: str | pathlib.Path, round_num: int) -> pathlib.Path:
    return pathlib.Path(directory) / f"round_{round_num:05d}{_SUFFIX}"


def node_checkpoint_path(directory: str | pathlib.Path,
                         node_idx: int) -> pathlib.Path:
    """A socket node's private periodic checkpoint (round 14). One
    file per node, atomically replaced each save — the newest state
    always wins and the directory never grows with the run."""
    return pathlib.Path(directory) / f"node_{node_idx:03d}{_SUFFIX}"


def _atomic_write_bytes(path: pathlib.Path, blob: bytes) -> None:
    """Crash-consistent publish: tmp + flush + fsync + ``os.replace``,
    then fsync the directory so the rename itself survives a power
    cut. A reader can observe the old file or the new file, never a
    torn one."""
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dir_fd = os.open(path.parent, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: rename is best-effort
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def _restore_blob(path: str | pathlib.Path) -> Any:
    """msgpack_restore with torn-file detection: a truncated or
    corrupt checkpoint fails loudly NAMING THE FILE instead of leaking
    a bare msgpack unpack error from deep inside flax."""
    blob = pathlib.Path(path).read_bytes()
    try:
        return flax_ser.msgpack_restore(blob)
    except Exception as e:
        raise ValueError(
            f"checkpoint {path} is truncated or corrupt "
            f"({len(blob)} bytes): {e!r}"
        ) from e


# ---- wire transfer (round 11: live join handshake) ---------------------

def pack_model(params: Any, round_num: int) -> bytes:
    """One params tree + its round as a checkpoint-format msgpack blob
    — the payload an established node ships to a live joiner (p2p
    STATE_SYNC). Same serialization as the on-disk checkpoint
    (``to_state_dict`` -> ``msgpack_serialize``), so the join path and
    the restart-from-disk path cannot drift."""
    host = jax.tree.map(np.asarray, params)
    return flax_ser.msgpack_serialize(
        {"round": int(round_num), "params": flax_ser.to_state_dict(host)}
    )


def unpack_model(blob: bytes, template: Any) -> tuple[Any, int]:
    """Restore a ``pack_model`` blob into the structure of
    ``template``; returns ``(params, round)``. Leaves are copied
    (non-owning msgpack views must never back donated buffers — see
    ``load_checkpoint``) and dtype-conformed to the template."""
    return _model_from_obj(flax_ser.msgpack_restore(blob), template)


def _model_from_obj(obj: Any, template: Any) -> tuple[Any, int]:
    try:
        restored = flax_ser.from_state_dict(template, obj["params"])
    except Exception as e:
        raise ValueError(f"state blob does not match model: {e}") from e
    flat_t, treedef = jax.tree.flatten(template)
    flat_r = jax.tree.leaves(restored)
    conformed = []
    for t, r in zip(flat_t, flat_r):
        r = np.array(r, copy=True)
        if r.shape != np.shape(t):
            raise ValueError(
                f"state blob leaf shape {r.shape} != expected {np.shape(t)}"
            )
        conformed.append(r.astype(np.asarray(t).dtype))
    return jax.tree.unflatten(treedef, conformed), int(obj.get("round", 0))


def save_checkpoint(directory: str | pathlib.Path, fed: FederatedState) -> pathlib.Path:
    """Write the federation state; returns the file path.

    Multi-host (jax.distributed): node-sharded leaves are only
    partially addressable per process, so every process joins an
    allgather and process 0 writes the file; a barrier afterwards
    guarantees the checkpoint exists before any process moves on
    (e.g. to a restart that would resume from it)."""
    directory = pathlib.Path(directory)
    multi = jax.process_count() > 1
    if multi:
        # fetch_global also covers processes that own no device of the
        # federation submesh (replicated leaves have no local shard
        # there — the 4-process/6-node test shape)
        from p2pfl_tpu.parallel.mesh import fetch_global

        host = jax.tree.map(fetch_global, fed)
    else:
        host = jax.tree.map(np.asarray, fed)
    path = checkpoint_path(directory, int(host.round))
    if not multi or jax.process_index() == 0:
        directory.mkdir(parents=True, exist_ok=True)
        # to_state_dict turns namedtuple opt states / dataclasses into
        # plain nested dicts that msgpack can carry
        blob = flax_ser.msgpack_serialize(flax_ser.to_state_dict(host))
        # atomic publish: a crash mid-write must never leave a truncated
        # round_NNNNN file for latest_checkpoint to pick up
        _atomic_write_bytes(path, blob)
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"p2pfl-ckpt-{int(host.round)}")
    flight.record("checkpoint.save", round=int(host.round),
                  path=str(path))
    return path


def save_node_checkpoint(directory: str | pathlib.Path, node_idx: int,
                         params: Any, round_num: int) -> pathlib.Path:
    """Periodic per-node atomic checkpoint (round 14, socket plane):
    the node's current params + round in the SAME msgpack format the
    STATE_SYNC join handshake ships (``pack_model``), so a relaunched
    node can adopt whichever of (own disk state, peer sync) is newer
    without a second deserializer."""
    path = node_checkpoint_path(directory, node_idx)
    _atomic_write_bytes(path, pack_model(params, round_num))
    flight.record("checkpoint.node_save", node=int(node_idx),
                  round=int(round_num), path=str(path))
    return path


def load_node_checkpoint(directory: str | pathlib.Path, node_idx: int,
                         template: Any) -> tuple[Any, int] | None:
    """Restore a node's private checkpoint; ``None`` when the node has
    never saved one. A torn/corrupt file raises ValueError naming the
    file (``_restore_blob``)."""
    path = node_checkpoint_path(directory, node_idx)
    if not path.is_file():
        return None
    obj = _restore_blob(path)
    flight.record("checkpoint.node_load", node=int(node_idx),
                  path=str(path))
    try:
        return _model_from_obj(obj, template)
    except ValueError as e:
        raise ValueError(f"checkpoint {path}: {e}") from e


def all_checkpoints(directory: str | pathlib.Path) -> list[pathlib.Path]:
    """Checkpoint files, oldest first."""
    directory = pathlib.Path(directory)
    if not directory.is_dir():
        return []
    return sorted(directory.glob(f"round_*{_SUFFIX}"))


def latest_checkpoint(directory: str | pathlib.Path) -> pathlib.Path | None:
    ckpts = all_checkpoints(directory)
    return ckpts[-1] if ckpts else None


def load_checkpoint(path: str | pathlib.Path, template: FederatedState) -> FederatedState:
    """Restore into the structure of ``template`` (shape/dtype checked
    by flax's from_bytes-style restore against the template leaves)."""
    obj = _restore_blob(path)
    flight.record("checkpoint.load", path=str(path))
    try:
        restored = flax_ser.from_state_dict(template, obj)
    except Exception as e:
        raise ValueError(f"checkpoint does not match federation: {e}") from e
    # conform leaf dtypes and check shapes against the template
    flat_t, treedef = jax.tree.flatten(template)
    flat_r = jax.tree.leaves(restored)
    conformed = []
    for t, r in zip(flat_t, flat_r):
        # copy=True: msgpack_restore leaves are non-owning views of the
        # blob bytes, and jnp.asarray/device_put zero-copy numpy on CPU —
        # a resumed FederatedState must OWN its buffers because the
        # round fn donates them (transport.compile_round
        # donate_argnums=(0,)); donating externally-backed memory reads
        # back stale or freed data once the source is collected.
        r = jnp.array(r, copy=True)
        if r.shape != t.shape:
            raise ValueError(
                f"checkpoint leaf shape {r.shape} != expected {t.shape}"
            )
        conformed.append(r.astype(t.dtype))
    return jax.tree.unflatten(treedef, conformed)
