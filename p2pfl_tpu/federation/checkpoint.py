"""Round-boundary checkpoint / resume.

The reference has NO checkpointing (lightninglearner.py:190 disables
it; a restarted node cannot rejoin — SURVEY.md §5.4). Here the whole
federation state (stacked params + opt state + rngs + round + alive
mask) serializes to one msgpack file at round boundaries, and a run
can resume exactly where it stopped.
"""

from __future__ import annotations

import os
import pathlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization as flax_ser

from p2pfl_tpu.obs import flight
from p2pfl_tpu.parallel.federated import FederatedState

_SUFFIX = ".ckpt.msgpack"


def checkpoint_path(directory: str | pathlib.Path, round_num: int) -> pathlib.Path:
    return pathlib.Path(directory) / f"round_{round_num:05d}{_SUFFIX}"


# ---- wire transfer (round 11: live join handshake) ---------------------

def pack_model(params: Any, round_num: int) -> bytes:
    """One params tree + its round as a checkpoint-format msgpack blob
    — the payload an established node ships to a live joiner (p2p
    STATE_SYNC). Same serialization as the on-disk checkpoint
    (``to_state_dict`` -> ``msgpack_serialize``), so the join path and
    the restart-from-disk path cannot drift."""
    host = jax.tree.map(np.asarray, params)
    return flax_ser.msgpack_serialize(
        {"round": int(round_num), "params": flax_ser.to_state_dict(host)}
    )


def unpack_model(blob: bytes, template: Any) -> tuple[Any, int]:
    """Restore a ``pack_model`` blob into the structure of
    ``template``; returns ``(params, round)``. Leaves are copied
    (non-owning msgpack views must never back donated buffers — see
    ``load_checkpoint``) and dtype-conformed to the template."""
    obj = flax_ser.msgpack_restore(blob)
    try:
        restored = flax_ser.from_state_dict(template, obj["params"])
    except Exception as e:
        raise ValueError(f"state blob does not match model: {e}") from e
    flat_t, treedef = jax.tree.flatten(template)
    flat_r = jax.tree.leaves(restored)
    conformed = []
    for t, r in zip(flat_t, flat_r):
        r = np.array(r, copy=True)
        if r.shape != np.shape(t):
            raise ValueError(
                f"state blob leaf shape {r.shape} != expected {np.shape(t)}"
            )
        conformed.append(r.astype(np.asarray(t).dtype))
    return jax.tree.unflatten(treedef, conformed), int(obj.get("round", 0))


def save_checkpoint(directory: str | pathlib.Path, fed: FederatedState) -> pathlib.Path:
    """Write the federation state; returns the file path.

    Multi-host (jax.distributed): node-sharded leaves are only
    partially addressable per process, so every process joins an
    allgather and process 0 writes the file; a barrier afterwards
    guarantees the checkpoint exists before any process moves on
    (e.g. to a restart that would resume from it)."""
    directory = pathlib.Path(directory)
    multi = jax.process_count() > 1
    if multi:
        # fetch_global also covers processes that own no device of the
        # federation submesh (replicated leaves have no local shard
        # there — the 4-process/6-node test shape)
        from p2pfl_tpu.parallel.mesh import fetch_global

        host = jax.tree.map(fetch_global, fed)
    else:
        host = jax.tree.map(np.asarray, fed)
    path = checkpoint_path(directory, int(host.round))
    if not multi or jax.process_index() == 0:
        directory.mkdir(parents=True, exist_ok=True)
        # to_state_dict turns namedtuple opt states / dataclasses into
        # plain nested dicts that msgpack can carry
        blob = flax_ser.msgpack_serialize(flax_ser.to_state_dict(host))
        # atomic publish: a crash mid-write must never leave a truncated
        # round_NNNNN file for latest_checkpoint to pick up
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_bytes(blob)
        os.replace(tmp, path)
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"p2pfl-ckpt-{int(host.round)}")
    flight.record("checkpoint.save", round=int(host.round),
                  path=str(path))
    return path


def all_checkpoints(directory: str | pathlib.Path) -> list[pathlib.Path]:
    """Checkpoint files, oldest first."""
    directory = pathlib.Path(directory)
    if not directory.is_dir():
        return []
    return sorted(directory.glob(f"round_*{_SUFFIX}"))


def latest_checkpoint(directory: str | pathlib.Path) -> pathlib.Path | None:
    ckpts = all_checkpoints(directory)
    return ckpts[-1] if ckpts else None


def load_checkpoint(path: str | pathlib.Path, template: FederatedState) -> FederatedState:
    """Restore into the structure of ``template`` (shape/dtype checked
    by flax's from_bytes-style restore against the template leaves)."""
    obj = flax_ser.msgpack_restore(pathlib.Path(path).read_bytes())
    flight.record("checkpoint.load", path=str(path))
    try:
        restored = flax_ser.from_state_dict(template, obj)
    except Exception as e:
        raise ValueError(f"checkpoint does not match federation: {e}") from e
    # conform leaf dtypes and check shapes against the template
    flat_t, treedef = jax.tree.flatten(template)
    flat_r = jax.tree.leaves(restored)
    conformed = []
    for t, r in zip(flat_t, flat_r):
        # copy=True: msgpack_restore leaves are non-owning views of the
        # blob bytes, and jnp.asarray/device_put zero-copy numpy on CPU —
        # a resumed FederatedState must OWN its buffers because the
        # round fn donates them (transport.compile_round
        # donate_argnums=(0,)); donating externally-backed memory reads
        # back stale or freed data once the source is collected.
        r = jnp.array(r, copy=True)
        if r.shape != t.shape:
            raise ValueError(
                f"checkpoint leaf shape {r.shape} != expected {t.shape}"
            )
        conformed.append(r.astype(t.dtype))
    return jax.tree.unflatten(treedef, conformed)
