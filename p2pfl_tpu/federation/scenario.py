"""Scenario: build + run a whole federation.

The successor of the reference's deploy-and-train path (Controller.
load_configurations_and_start_nodes → N processes → Node.
set_start_learning → per-node round loops, SURVEY.md §3.1-3.3),
collapsed into one host object driving one jitted round program:

    scenario = Scenario(ScenarioConfig(...))
    result = scenario.run()

Per round the host: (1) applies scheduled fault events and advances
the virtual membership clock (heartbeat eviction), (2) rotates SDFL
leadership among alive nodes, (3) recomputes the round plan if
membership/leadership changed, (4) invokes the compiled SPMD round,
(5) periodically evaluates, logs, and checkpoints. There are no grace
sleeps — the reference's 30 s + 5 s/neighbor startup dead time
(node_start.py:106,112) is replaced by compile time, which is cached
after the first round.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.adversary import (
    AttackSpec,
    ReputationMonitor,
    flip_labels,
    malicious_indices,
)
from p2pfl_tpu.config.schema import ScenarioConfig
from p2pfl_tpu.core.aggregators import FedAvg, get_aggregator
from p2pfl_tpu.datasets import CrossDeviceData, FederatedDataset
from p2pfl_tpu.federation.checkpoint import (
    all_checkpoints,
    load_checkpoint,
    save_checkpoint,
)
from p2pfl_tpu.federation.events import Events, Observable
from p2pfl_tpu.federation.membership import Membership
from p2pfl_tpu.federation.sampling import sample_clients, sample_cohorts
from p2pfl_tpu.learning.learner import make_step_fns
from p2pfl_tpu.models.base import build_model
from p2pfl_tpu.parallel.federated import (
    FederatedState,
    build_cross_device_stream_fns,
    build_eval_fn,
    build_round_fn,
    build_round_fn_cross_device,
    build_round_fn_sparse,
    cross_device_wn,
    init_federation,
    make_round_plan,
    round_flops,
    staleness_scale,
    with_staged_buffer,
)
from p2pfl_tpu.parallel.mesh import cohort_shard_mesh
from p2pfl_tpu.obs import devprof, flight
from p2pfl_tpu.obs import trace as obs_trace
from p2pfl_tpu.parallel.transport import MeshTransport, edge_offsets
from p2pfl_tpu.topology.topology import generate_topology
from p2pfl_tpu.utils.metrics import MetricsLogger
from p2pfl_tpu.utils.monitor import publish_status
from p2pfl_tpu.utils.telemetry import resource_snapshot


@dataclasses.dataclass
class ScenarioResult:
    """What a run produces (the reference's equivalent is TB/W&B logs
    plus the SQLite scenario row)."""

    final_accuracy: float  # mean over alive nodes, central test set
    per_node_accuracy: list[float]
    rounds_run: int
    round_times_s: list[float]
    history: list[dict]  # metric records
    rounds_to_target: int | None = None  # first round hitting target_acc
    min_accuracy: float = 0.0  # min over ALIVE nodes (dead excluded)


class Scenario(Observable):
    """Build and drive a federation from a ScenarioConfig."""

    def __init__(self, config: ScenarioConfig, dataset: FederatedDataset | None = None):
        super().__init__()
        if config.cross_device.active:
            raise ValueError(
                "config.cross_device is active — Scenario drives one "
                "live row per node; use CrossDeviceScenario for the "
                "sampled K-of-N regime"
            )
        if config.privacy.secagg:
            # the sparse-transport × attack precedent: the SPMD round
            # has no per-pair wire to mask — every row reads the stacked
            # params directly, so "secure aggregation" here would be
            # theater. Fail loud; secagg is a socket-plane feature.
            raise ValueError(
                "privacy.secagg is a socket-plane feature (pairwise "
                "masks ride the PARAMS wire); the SPMD Scenario shares "
                "one device array and has nothing to mask — run the "
                "socket plane (p2p.launch) instead"
            )
        self.config = config
        n = config.n_nodes
        self.dataset = dataset or FederatedDataset.make(config.data, n)
        self.model = build_model(config.model)
        if config.lora.active:
            # adapter-only federation: the wrapped model trains (and
            # federates) the adapter subtree over a frozen base derived
            # deterministically from (model config, seed) — the SAME
            # derivation every socket node process uses, so the planes
            # share one base bit-exactly
            from p2pfl_tpu.learning.lora import maybe_wrap_lora

            self.model = maybe_wrap_lora(
                self.model, config,
                jnp.asarray(self.dataset.nodes[0].x[:1]),
            )
        self.fns = make_step_fns(
            self.model,
            objective=config.model.objective,
            optimizer=config.training.optimizer,
            learning_rate=config.training.learning_rate,
            momentum=config.training.momentum,
            weight_decay=config.training.weight_decay,
            momentum_dtype=config.training.momentum_dtype,
            batch_size=config.data.batch_size,
        )
        self.topology = generate_topology(
            config.topology, n, **config.topology_kwargs
        )
        self.aggregator = get_aggregator(
            config.aggregator, **config.aggregator_kwargs
        )
        self.roles = [nc.role for nc in config.nodes]
        self.membership = Membership(n, config.protocol)
        # multi-host (jax.distributed) job: every process runs the same
        # host trajectory (deterministic from config.seed), but only
        # process 0 owns the log/status/profile artifacts
        self._proc0 = jax.process_index() == 0
        self.logger = MetricsLogger(config.log_dir if self._proc0 else None,
                                    config.name,
                                    tensorboard=config.tensorboard,
                                    wandb=config.wandb and self._proc0)
        if self.logger.dir is not None:
            # topology render next to the metrics (controller.py:301 /
            # monitoring-map analog) — best-effort: a render/save
            # failure must never abort the run for an optional PNG
            try:
                from p2pfl_tpu.utils.draw import draw_topology

                draw_topology(self.topology,
                              self.logger.dir / "topology.png",
                              roles=self.roles)
            except Exception:
                pass
            try:
                # 3-D/geo topology export for the dashboard map
                # (topologymanager.py:151-173 + 320-355) — atomic: the
                # webapp map tails this file while the run is live
                import json as _json

                from p2pfl_tpu.utils.fsio import atomic_write_text

                atomic_write_text(
                    self.logger.dir / "topology_3d.json",
                    _json.dumps(self.topology.to_3d(seed=config.seed)),
                )
            except Exception:
                pass
        self.transport = MeshTransport(n)
        self.leader = next(
            (i for i, nc in enumerate(config.nodes)
             if nc.role in ("aggregator", "server")),
            0,
        )
        self._rng = np.random.default_rng(config.seed)
        self._faults_by_round: dict[int, list] = {}
        for f in config.faults:
            self._faults_by_round.setdefault(f.round, []).append(f)
        self._base_trains = np.array(
            [r in ("trainer", "aggregator", "server") for r in self.roles]
        )

        # ---- adversary wiring: the malicious cohort, the update
        # transform, and the trust monitor all derive from config alone,
        # so the SPMD and socket paths agree on who attacks and how
        adv = config.adversary
        self.malicious = (
            malicious_indices(n, adv.fraction, adv.seed, tuple(adv.nodes))
            if adv.active else np.zeros(n, bool)
        )
        self.attack = (
            AttackSpec(kind=adv.kind, scale=adv.scale, seed=adv.seed)
            if adv.active else None
        )
        self.reputation = (
            ReputationMonitor(n, alpha=adv.reputation_alpha,
                              cutoff=adv.reputation_cutoff)
            if adv.reputation else None
        )

        # ---- privacy wiring (round 21): every training node's
        # outgoing update is clipped + noised in-jit, keyed by
        # (config.seed, node, round) — the same streams the socket
        # plane draws, so the planes privatize bit-identically. The
        # accountant's ε is a pure function of rounds completed, so
        # every process reads the same spend from config alone.
        priv = config.privacy
        self.dp_spec = None
        self.accountant = None
        if priv.dp:
            from p2pfl_tpu.privacy.dp import DPSpec, PrivacyAccountant

            self.dp_spec = DPSpec(
                clip_norm=priv.clip_norm,
                noise_multiplier=priv.noise_multiplier,
                seed=config.seed,
            )
            self.accountant = PrivacyAccountant(
                priv.noise_multiplier, delta=priv.delta
            )
        self.dp_mask = (
            self._base_trains.copy() if priv.dp else np.zeros(n, bool)
        )

        # ---- elasticity wiring (round 11): in async mode a straggler
        # of compute class k delivers updates ~k-1 rounds stale, and
        # the SPMD twin of the socket session's entry-weight discount
        # is the SAME host-side f32 formula applied as a COLUMN scale
        # on the mixing matrix (the reputation pattern: w = mix *
        # n_samples, so scaling column j reweights node j's
        # contribution in every aggregate — no round-fn change, no
        # recompile). Static across rounds, so it composes with the
        # plan cache.
        el = config.elastic
        self._stale_scale: np.ndarray | None = None
        if el.async_aggregation and el.staleness_beta > 0.0:
            stale_rounds = np.asarray(
                [nc.fit_slowdown - 1.0 for nc in config.nodes], np.float32
            )
            if np.any(stale_rounds > 0.0):
                self._stale_scale = staleness_scale(
                    stale_rounds, el.staleness_beta
                )

        # ---- device-side setup
        x, y, smask, nsamp = self.dataset.stacked()
        if self.attack is not None and self.attack.kind == "labelflip":
            # data poisoning happens at the shard, not the update: flip
            # the malicious rows of the stacked train labels (identical
            # math to the socket path flipping its per-node shard)
            y = np.array(y, copy=True)
            for i in np.flatnonzero(self.malicious):
                y[i] = flip_labels(y[i], self.dataset.num_classes)
        tr = self.transport
        self._data_args = tuple(
            tr.put_stacked(jnp.asarray(a)) for a in (x, y, smask, nsamp)
        )
        self._x_test = tr.put_replicated(jnp.asarray(self.dataset.x_test))
        self._y_test = tr.put_replicated(jnp.asarray(self.dataset.y_test))
        self.sparse_transport = self._choose_sparse()
        # ONE wire-precision knob (config.wire_dtype) across planes:
        # on the SPMD plane the exchange is device math, so bf16 is the
        # hardware-native reduced precision; int8 (a socket-plane
        # encoding with per-leaf scales) falls back to bf16 here
        self._exchange_dtype = (
            jnp.bfloat16 if config.wire_dtype in ("bf16", "int8") else None
        )
        if self.sparse_transport:
            round_fn = build_round_fn_sparse(
                self.fns, self.topology, tr.mesh,
                epochs=config.training.epochs_per_round,
                exchange_dtype=self._exchange_dtype,
                exchange_overlap=config.exchange_overlap,
            )
        else:
            # one shared robust aggregate when every aggregating row is
            # identical (single-leader CFL/SDFL; fully-connected DFL):
            # the per-row path is O(n) redundant aggregations there
            adj = self.topology.adjacency
            fully = bool(
                np.all(adj | np.eye(n, dtype=bool))
            )
            shared = (
                config.federation in ("CFL", "SDFL")
                or (config.federation == "DFL" and fully)
            )
            round_fn = build_round_fn(
                self.fns, aggregator=self.aggregator,
                epochs=config.training.epochs_per_round,
                exchange_dtype=self._exchange_dtype,
                shared_aggregate=shared,
                # DFL plans always adopt their own row (make_round_plan)
                # -> the agg[adopt] whole-stack gather pass is elided;
                # CFL/SDFL adopt the leader's row and keep it
                identity_adopt=config.federation == "DFL",
                attack=self.attack,
                malicious=self.malicious,
                update_stats=self.reputation is not None,
                exchange_overlap=config.exchange_overlap,
                dp=self.dp_spec,
                dp_mask=self.dp_mask,
            )
        self._round_fn = tr.compile_round(round_fn)
        self._eval_fn = tr.compile_eval(build_eval_fn(self.fns))
        fed0 = init_federation(self.fns, jnp.asarray(x[0, :1]), n,
                               seed=config.seed)
        if config.exchange_overlap == "staged":
            # seed the double buffer at zero weight: staged round 0
            # reduces to pure local training (with_staged_buffer)
            fed0 = with_staged_buffer(fed0)
        self.fed = tr.put_stacked(fed0)
        self._maybe_resume()
        self._steps_per_round = (
            max(x.shape[1] // config.data.batch_size, 1)
            * config.training.epochs_per_round
        )
        # resumed runs continue the FL-aware global-step x-axis
        self.global_step = (
            int(self._node_host(self.fed.round)) * self._steps_per_round
        )
        self._plan_cache: dict[tuple, tuple] = {}
        # devprof round gauges (MFU/TFLOPs/HBM), refreshed per round
        # when P2PFL_DEVPROF is on and splatted into status records.
        # False = round FLOPs not probed yet (None = probed, uncounted)
        self.devprof_last: dict[str, Any] = {}
        self._devprof_flops: float | None | bool = False

    # ------------------------------------------------------------------
    def _node_host(self, x) -> np.ndarray:
        """Device array (node-sharded or replicated) -> full host copy
        on every process. Multi-host fetches route through
        ``mesh.fetch_global`` — which also serves processes owning no
        device of the federation submesh; single-process is a plain
        transfer."""
        if jax.process_count() > 1:
            from p2pfl_tpu.parallel.mesh import fetch_global

            return fetch_global(x)
        return np.asarray(x)

    def _choose_sparse(self) -> bool:
        """Pick the collective schedule for weight exchange.

        The ppermute path is legal only for DFL (identity adopt) with
        FedAvg and one node per mesh slot. Bandwidth model: the stacked
        all-gather moves (n-1) x |params| through each ICI link; each
        ppermute moves |params| — so sparse wins when #offsets < n-1
        (ring: 2 vs n-1). At equality the all-gather's single fused
        collective has better latency, so prefer dense.
        """
        cfg = self.config
        legal = (
            cfg.federation == "DFL"
            and self.transport.n_devices == cfg.n_nodes
            and type(self.aggregator) is FedAvg
            # the ppermute path never materializes the full params
            # stack, so there is no pre-exchange hook for update
            # poisoning, DP privatization, or trust_obs reputation
            and not (self.attack is not None and self.attack.poisons_updates)
            and self.reputation is None
            and self.dp_spec is None
        )
        if cfg.transport == "dense":
            return False
        if cfg.transport == "sparse":
            if not legal:
                raise ValueError(
                    "transport='sparse' needs DFL + FedAvg + one node "
                    "per device, and no update-poisoning adversary, "
                    "reputation, or DP privatization "
                    f"(n_nodes={cfg.n_nodes}, "
                    f"n_devices={self.transport.n_devices}, "
                    f"federation={cfg.federation})"
                )
            return True
        return legal and len(edge_offsets(self.topology)) < cfg.n_nodes - 1

    def _maybe_resume(self) -> None:
        if not self.config.checkpoint_dir:
            return
        restored = None
        # newest first, falling back past any corrupt/truncated file
        for path in reversed(all_checkpoints(self.config.checkpoint_dir)):
            try:
                restored = load_checkpoint(path, self.fed)
                break
            except ValueError:
                continue
        if restored is None:
            return
        self.fed = self.transport.put_stacked(restored)
        # replay the host trajectory through the checkpointed rounds —
        # identical fault application, clock advancement AND leadership
        # rotation (advancing self._rng through the same draw sequence)
        # as the uninterrupted run, so eviction timing, the leader, and
        # every subsequent mix weight match exactly
        start_round = int(self._node_host(self.fed.round))
        for r in range(start_round):
            alive = self._advance_membership(r, replay=True)
            self._rotate_leader(alive, replay=True)

    def _sync_join_row(self, node: int, round_num: int) -> None:
        """SPMD twin of the socket STATE_SYNC half of a live join: the
        joining row adopts the current leader row's params (the
        federation's "current global model"), so a mid-run joiner
        re-enters from the cohort's state instead of whatever its row
        drifted to while dead. Joins are rare, so the eager row copy
        (one gather+scatter across the stacked params) is fine."""
        src = self.leader
        if src == node:
            src = next(
                (i for i in self.membership.get_nodes() if i != node), None
            )
            if src is None:
                return
        params = jax.tree.map(
            lambda x: x.at[node].set(x[src]), self.fed.states.params
        )
        self.fed = self.fed.replace(
            states=self.fed.states.replace(params=params)
        )
        self.notify(Events.NODE_JOINED, {"node": node, "round": round_num})

    def _advance_membership(self, round_num: int,
                            replay: bool = False) -> np.ndarray:
        for fault in self._faults_by_round.get(round_num, []):
            self.membership.apply_fault(fault)
            # replayed rounds (checkpoint resume) skip the row copy:
            # the restored state already CONTAINS the post-join params,
            # and re-copying today's leader row would diverge from the
            # uninterrupted trajectory
            if fault.kind == "join" and not replay:
                self._sync_join_row(fault.node, round_num)
        # one round advances the virtual clock by one heartbeat period —
        # eviction after node_timeout_s therefore takes
        # ceil(timeout/period) rounds of silence, like the reference's
        # 20 s timeout at 4 s beats
        t = self.membership.clock + self.membership.protocol.heartbeat_period_s
        return self.membership.advance_to(t)

    def _rotate_leader(self, alive: np.ndarray, replay: bool = False) -> None:
        if self.config.federation == "SDFL":
            candidates = [
                i for i in np.flatnonzero(alive)
                if self.roles[i] in ("aggregator", "trainer")
            ]
            if candidates:
                new = int(self._rng.choice(candidates))
                if new != self.leader and not replay:
                    self.notify(Events.LEADERSHIP_TRANSFERRED,
                                {"from": self.leader, "to": new})
                self.leader = new
        elif not alive[self.leader] and self.config.federation == "CFL":
            # dead server: fail over to the lowest-index alive node
            alive_idx = np.flatnonzero(alive)
            if len(alive_idx):
                self.leader = int(alive_idx[0])

    def _voted_trains(self, alive: np.ndarray,
                      round_num: int = 0) -> np.ndarray | None:
        """Train-set vote, collapsed to its deterministic fixed point.

        The socket path floods per-node ballots (each node vouches for
        the trainable part of its live neighborhood) and elects the
        ``train_set_size`` best-vouched candidates. On the host every
        voter sees the same alive set, so the tally is computable
        directly: score[j] = #alive nodes adjacent to j (plus j
        itself), with a round-ROTATING index tie-break so a binding cap
        still covers every node's data over rounds. Returns None when
        the cap doesn't bind (the plan's static ``trains`` stands).
        """
        k = self.config.protocol.train_set_size
        n = self.config.n_nodes
        eligible = [
            i for i in np.flatnonzero(alive)
            if self.roles[i] in ("trainer", "aggregator", "server")
        ]
        if k <= 0 or k >= len(eligible):
            return None
        adj = self.topology.adjacency
        score = {
            j: 1 + int(np.sum(adj[np.flatnonzero(alive), j]))
            for j in eligible
        }
        winners = sorted(
            score, key=lambda j: (-score[j], (j - round_num) % n)
        )[:k]
        win = set(winners)
        if self.config.federation in ("CFL", "SDFL") and alive[self.leader]:
            if self.leader not in win:
                win.discard(winners[-1])
                win.add(self.leader)
        trains = np.zeros(self.config.n_nodes, bool)
        trains[sorted(win)] = True
        return trains

    def _plan_args(self, trains_override: np.ndarray | None = None):
        """Device arrays for the current round plan. Liveness is folded
        in on-device from ``fed.alive``, so the plan depends only on the
        leader and the voted train set — cached to avoid per-round
        host→device transfers."""
        if self.reputation is not None:
            # reputation-weighted FedAvg without touching the round fn:
            # w = mix * n_samples * contrib, so scaling mix COLUMN j by
            # node j's trust is exactly a per-contributor reweighting —
            # and a zeroed column is a masked row for the robust
            # aggregators. Trust changes every round, so this path
            # skips the plan cache (one [n,n] host->device put/round).
            plan = make_round_plan(
                self.topology, self.roles, self.config.federation,
                self.leader,
            )
            trains = (
                plan.trains if trains_override is None else trains_override
            )
            mix = (
                plan.mix.astype(np.float32)
                * self.reputation.weights_vector()[None, :]
            )
            if self._stale_scale is not None:
                mix = mix * self._stale_scale[None, :]
            tr = self.transport
            return (
                tr.put_stacked(jnp.asarray(mix)),
                tr.put_stacked(jnp.asarray(plan.adopt)),
                tr.put_stacked(jnp.asarray(trains)),
            )
        key = (
            self.leader,
            None if trains_override is None else trains_override.tobytes(),
        )
        if key not in self._plan_cache:
            # bounded LRU: a binding rotating vote cap mints a fresh
            # trains vector per round per leader, which would grow the
            # cache without limit over a long scenario
            while len(self._plan_cache) >= 64:
                self._plan_cache.pop(next(iter(self._plan_cache)))
            plan = make_round_plan(
                self.topology, self.roles, self.config.federation, self.leader
            )
            trains = plan.trains if trains_override is None else trains_override
            mix = plan.mix
            if self._stale_scale is not None:
                mix = mix.astype(np.float32) * self._stale_scale[None, :]
            tr = self.transport
            self._plan_cache[key] = (
                tr.put_stacked(jnp.asarray(mix)),
                tr.put_stacked(jnp.asarray(plan.adopt)),
                tr.put_stacked(jnp.asarray(trains)),
            )
        else:
            self._plan_cache[key] = self._plan_cache.pop(key)  # LRU touch
        return self._plan_cache[key]

    def _publish_statuses(self, r: int, alive: np.ndarray,
                          train_loss: np.ndarray, ev: dict | None) -> None:
        """Per-node live status for ``python -m p2pfl_tpu.monitor``
        (the node→controller heartbeat POST analog, node.py:916-937)."""
        if self.logger.dir is None:
            return
        status_dir = self.logger.dir / "status"
        n_alive = int(alive.sum())
        times = sorted(getattr(self, "round_times_s", []))
        p95 = (
            round(times[min(len(times) - 1, int(0.95 * len(times)))], 4)
            if times else None
        )
        for i in range(self.config.n_nodes):
            if not alive[i]:
                continue  # dead nodes go silent, like a crashed process
            publish_status(
                status_dir, i,
                {
                    "role": self.roles[i],
                    "round": r + 1,
                    "round_p95_s": p95,
                    "loss": float(train_loss[i]),
                    "accuracy": (
                        float(ev["per_node_accuracy"][i]) if ev else None
                    ),
                    "peers": n_alive - 1,
                    "leader": self.leader,
                    "trust": (
                        round(float(self.reputation.trust[i]), 4)
                        if self.reputation is not None else None
                    ),
                    "dp_epsilon": (
                        round(self.accountant.epsilon, 4)
                        if self.accountant is not None else None
                    ),
                    "dp_epsilon_budget": (
                        self.config.privacy.epsilon_budget
                        if self.accountant is not None else None
                    ),
                    "recompiles": obs_trace.xla_recompiles(),
                    # one SPMD program serves every node, so the
                    # devprof gauges (utilization/memory) are shared
                    **self.devprof_last,
                },
            )

    def evaluate(self) -> dict[str, Any]:
        metrics = self._eval_fn(self.fed, self._x_test, self._y_test)
        acc = self._node_host(metrics["accuracy"]).astype(np.float64)
        loss = self._node_host(metrics["loss"]).astype(np.float64)
        alive = self._node_host(self.fed.alive)
        mean_acc = float(acc[alive].mean()) if alive.any() else 0.0
        return {
            "per_node_accuracy": [float(a) for a in acc],
            "per_node_loss": [float(l) for l in loss],
            "mean_accuracy": mean_acc,
            "min_accuracy": float(acc[alive].min()) if alive.any() else 0.0,
        }

    def run(self, rounds: int | None = None,
            target_accuracy: float | None = None) -> ScenarioResult:
        cfg = self.config
        rounds = rounds if rounds is not None else cfg.training.rounds
        # obs: recompile counter + span tracer (P2PFL_TRACE env gate).
        # The listener is idempotent and the tracer a no-op when off;
        # a mid-run recompile storm (perf.md §7b) shows up as
        # xla/backend_compiles > 0 over the steady-state rounds.
        obs_trace.install_xla_listener()
        tracer = obs_trace.configure_from_env(
            default_dir=(self.logger.dir / "trace")
            if self.logger.dir else None,
        )
        if self.logger.dir is not None:
            flight.configure(dump_dir=self.logger.dir / "flight")
        round_times: list[float] = []
        self.round_times_s = round_times  # _publish_statuses reads p95
        rounds_to_target = None
        ev = None
        ev_round = -1  # round the last evaluation reflects
        start_round = int(self._node_host(self.fed.round))
        # profile ONE steady-state round (the second of the run when
        # there is one — the first carries compile time); SURVEY §5.1's
        # jax.profiler hook. try/finally: an exception mid-profiled-
        # round must not leave the tracer running.
        profile_round = None
        if cfg.profile_dir and self._proc0:
            profile_round = start_round + (1 if rounds > 1 else 0)
        tracing = False
        try:
            for r in range(start_round, start_round + rounds):
                t0 = time.monotonic()
                if r == profile_round:
                    jax.profiler.start_trace(cfg.profile_dir)
                    tracing = True
                self.notify(Events.ROUND_STARTED, {"round": r})
                alive = self._advance_membership(r)
                self._rotate_leader(alive)
                self.fed = self.fed.replace(
                    alive=self.transport.put_stacked(jnp.asarray(alive))
                )
                trains_vote = self._voted_trains(alive, r)
                with tracer.span("scenario.round", args={"round": r}):
                    self.fed, metrics = self._round_fn(
                        self.fed, *self._data_args,
                        *self._plan_args(trains_vote),
                    )
                    jax.block_until_ready(self.fed.states.params)
                if tracing:
                    jax.profiler.stop_trace()
                    tracing = False
                self.notify(Events.AGGREGATION_FINISHED, {"round": r})
                dt = time.monotonic() - t0
                round_times.append(dt)
                if devprof.enabled():
                    # the FLOP probe lowers the round program once per
                    # run (shapes are fixed), AFTER dt is read so its
                    # compile never bills itself to a round time
                    if self._devprof_flops is False:
                        self._devprof_flops = round_flops(
                            self._round_fn, self.fed, *self._data_args,
                            *self._plan_args(trains_vote))
                    self.devprof_last = devprof.round_gauges(
                        self._devprof_flops, dt, self.transport.n_devices)
                self.global_step += self._steps_per_round

                train_loss = self._node_host(
                    metrics["train_loss"]).astype(np.float64)
                if self.accountant is not None:
                    # ε is a pure function of rounds completed, so a
                    # resumed run re-reads the same spend (r counts
                    # from the checkpoint's round, not zero)
                    self.accountant.steps = r + 1
                if self.reputation is not None and "trust_obs" in metrics:
                    # round r ran on trust from round r-1 (one-round
                    # lag); fold in this round's scores for the next.
                    # Silent nodes (not training or dead) keep their
                    # trust — absence is not evidence.
                    contrib = np.logical_and(
                        self._base_trains if trains_vote is None
                        else trains_vote,
                        alive,
                    )
                    self.reputation.observe(
                        self._node_host(metrics["trust_obs"]).astype(
                            np.float64),
                        contrib,
                    )
                for i in range(cfg.n_nodes):
                    rec = {"Train/loss": float(train_loss[i]),
                           "Train/round_time_s": dt}
                    if self.reputation is not None:
                        rec["Trust/score"] = float(self.reputation.trust[i])
                    self.logger.log_metrics(
                        rec, step=self.global_step, round=r, node=i,
                    )
                self._publish_statuses(r, alive, train_loss, ev)
                if cfg.training.eval_every and (r + 1) % cfg.training.eval_every == 0:
                    ev = self.evaluate()
                    ev_round = r
                    for i, (a, l) in enumerate(
                        zip(ev["per_node_accuracy"], ev["per_node_loss"])
                    ):
                        self.logger.log_metrics(
                            {"Test/accuracy": a, "Test/loss": l},
                            step=self.global_step, round=r, node=i,
                        )
                    self.logger.log_metrics(
                        {"Test/mean_accuracy": ev["mean_accuracy"],
                         "Test/min_accuracy": ev["min_accuracy"]},
                        step=self.global_step, round=r,
                    )
                    if (target_accuracy is not None
                            and rounds_to_target is None
                            and ev["mean_accuracy"] >= target_accuracy):
                        rounds_to_target = r + 1
                self.logger.log_metrics(resource_snapshot(),
                                        step=self.global_step, round=r)
                self.logger.round_marker(r, self.global_step)
                if cfg.checkpoint_every and (r + 1) % cfg.checkpoint_every == 0:
                    if cfg.checkpoint_dir:
                        path = save_checkpoint(cfg.checkpoint_dir, self.fed)
                        self.notify(Events.CHECKPOINT_SAVED,
                                    {"path": str(path)})
                self.notify(Events.ROUND_FINISHED, {"round": r, "time_s": dt})
        finally:
            if tracing:  # exception mid-profiled-round
                jax.profiler.stop_trace()
            if tracer.enabled and self._proc0:
                tracer.export(process_name=f"scenario[{cfg.name}]")

        last_round = start_round + rounds - 1
        if ev is None or ev_round != last_round:  # don't report stale eval
            ev = self.evaluate()
            if (target_accuracy is not None and rounds_to_target is None
                    and ev["mean_accuracy"] >= target_accuracy):
                rounds_to_target = last_round + 1
        self.notify(Events.LEARNING_FINISHED, {})
        return ScenarioResult(
            final_accuracy=ev["mean_accuracy"],
            per_node_accuracy=ev["per_node_accuracy"],
            rounds_run=rounds,
            round_times_s=round_times,
            history=self.logger.history,
            rounds_to_target=rounds_to_target,
            min_accuracy=ev["min_accuracy"],
        )

    def close(self) -> None:
        self.logger.close()


class CrossDeviceScenario(Observable):
    """Sampled K-of-N cross-device driver (round 13).

    A client here is NOT a live row of the federation: it is an index
    into a lazy :class:`ClientPartition` (CrossDeviceData). Per round
    the host (1) applies scheduled faults and advances the SAME
    ``membership.py`` virtual clock Scenario uses — but over ALL
    ``n_clients`` virtual clients, so churn composes with sampling,
    (2) draws K clients (seeded by ``(cross_device.seed, round)``,
    replacement-free, optionally data-size-weighted), (3) reshapes them
    into ``cohort_size`` cohorts of ``n_slots`` and materializes their
    shards at the fixed shard size, (4) invokes the compiled
    cohort-scan round (``build_round_fn_cross_device``): one program,
    fixed shapes, zero steady-state recompiles regardless of which
    clients were drawn. A sampled-but-dead client simply rides through
    with zero training gate and zero aggregation weight.

    The mesh is ``n_slots = clients_per_round / cohort_size`` wide —
    an 8-slot dev mesh at cohort_size=32 simulates 256 participants
    per round out of a 10k–1M population.
    """

    def __init__(self, config: ScenarioConfig,
                 dataset: CrossDeviceData | None = None):
        super().__init__()
        cd = config.cross_device
        if not cd.active:
            raise ValueError(
                "CrossDeviceScenario needs config.cross_device.n_clients"
                " > 0"
            )
        self.config = config
        self.cd = cd
        self.data = dataset or CrossDeviceData.make(config.data,
                                                    cd.n_clients)
        self.model = build_model(config.model)
        self.fns = make_step_fns(
            self.model,
            objective=config.model.objective,
            optimizer=config.training.optimizer,
            learning_rate=config.training.learning_rate,
            momentum=config.training.momentum,
            weight_decay=config.training.weight_decay,
            momentum_dtype=config.training.momentum_dtype,
            batch_size=config.data.batch_size,
        )
        # the virtual clock spans every VIRTUAL client — the same
        # heartbeat/eviction law as the per-node plane, just wider
        self.membership = Membership(cd.n_clients, config.protocol)
        self._faults_by_round: dict[int, list] = {}
        for f in config.faults:
            self._faults_by_round.setdefault(f.round, []).append(f)
        self._sample_weights = (
            self.data.client_sizes.astype(np.float64)
            if cd.sampling == "weighted" else None
        )
        self._proc0 = jax.process_index() == 0
        self.logger = MetricsLogger(
            config.log_dir if self._proc0 else None, config.name,
            tensorboard=config.tensorboard,
            wandb=config.wandb and self._proc0,
        )
        # round-20 device scaling: with cohort_shards > 1 and enough
        # devices, the round runs the shard_map arm over a cohort mesh;
        # with too few devices it silently falls back to the chunked
        # single-device arm. Chunk structure is part of the round's
        # semantics, placement is not: within one device topology the
        # arms are bit-identical (pinned by tests/test_cross_device.py),
        # but a DIFFERENT topology (e.g. the fallback firing on a
        # 1-device host) may fuse the training body differently and
        # drift ~1 ulp — same reassociation caveat as perf.md §19.1.
        # The slot transport is rebuilt over the SAME device set as the
        # cohort mesh — one jit must not see two device orders.
        self._cohort_mesh = None
        if cd.cohort_shards > 1 and cd.cohort_shards <= jax.device_count():
            self._cohort_mesh = cohort_shard_mesh(cd.cohort_shards)
            self.transport = MeshTransport(cd.n_slots,
                                           n_devices=cd.cohort_shards)
        else:
            self.transport = MeshTransport(cd.n_slots)
        self._exchange_dtype = (
            jnp.bfloat16 if config.wire_dtype in ("bf16", "int8") else None
        )
        self._stream = cd.prefetch == "stream"
        if self._stream:
            # streamed arm (round 20): the round is driven step-by-step
            # so cohort t+1's host gather + device_put overlaps cohort
            # t's compute — see _run_streamed_round
            init_carry, step_fn, finalize = build_cross_device_stream_fns(
                self.fns,
                epochs=config.training.epochs_per_round,
                exchange_dtype=self._exchange_dtype,
                fused_accumulate=cd.accumulate == "fused",
            )
            self._stream_init_carry = init_carry
            self._stream_step = jax.jit(step_fn, donate_argnums=(1,))
            self._stream_finalize = jax.jit(finalize)
            self._wn_fn = jax.jit(cross_device_wn)
            self._stream_bufs = None  # two cohort_buffers: the double buffer
            self._round_fn = None
        else:
            round_fn = build_round_fn_cross_device(
                self.fns,
                epochs=config.training.epochs_per_round,
                exchange_dtype=self._exchange_dtype,
                fused_accumulate=cd.accumulate == "fused",
                cohort_shards=cd.cohort_shards,
                cohort_mesh=self._cohort_mesh,
            )
            self._round_fn = self.transport.compile_round(round_fn)
        self._eval_fn = self.transport.compile_eval(build_eval_fn(self.fns))
        sample_x = jnp.zeros((1,) + self.data.input_shape, jnp.float32)
        fed0 = init_federation(self.fns, sample_x, cd.n_slots,
                               seed=config.seed)
        # the mesh arm replicates the federation state (every device
        # scans ALL slots for its chunk); otherwise the slot axis
        # shards as before
        self.fed = (self.transport.put_replicated(fed0)
                    if self._cohort_mesh is not None
                    else self.transport.put_stacked(fed0))
        # live gauges for the monitor/launch status plumbing (round 20):
        # refreshed per round, splatted into status records
        self.crossdev_last: dict[str, Any] = {}
        self.devprof_last: dict[str, Any] = {}
        self._devprof_flops: float | None | bool = False
        self._x_test = self.transport.put_replicated(
            jnp.asarray(self.data.x_test))
        self._y_test = self.transport.put_replicated(
            jnp.asarray(self.data.y_test))
        # test introspection: the last round's draw and its liveness
        self.last_sampled: np.ndarray | None = None
        self.last_cohorts: np.ndarray | None = None
        self.last_cohort_alive: np.ndarray | None = None

    def _advance_membership(self, round_num: int) -> np.ndarray:
        for fault in self._faults_by_round.get(round_num, []):
            # join == recover here: clients are stateless between
            # rounds, so there is no row to state-sync
            self.membership.apply_fault(fault)
        t = (self.membership.clock
             + self.membership.protocol.heartbeat_period_s)
        return self.membership.advance_to(t)

    def _run_streamed_round(self, cohorts: np.ndarray,
                            c_alive: np.ndarray) -> dict[str, Any]:
        """One round through the double-buffered prefetch seam (round
        18): while the device runs cohort step t, the host gathers
        cohort t+1's shards into the OTHER of two reused host buffers
        and ``device_put``s them — at most two cohorts of client data
        resident (host or device) at any instant, for any N. The steps
        run the same ``_cross_device_body`` as the monolithic scan in
        the same order with the same globally-normalized weights, so a
        streamed round is bit-identical to ``prefetch="off"``.

        Gauges recorded into ``crossdev_last``:
        ``crossdev_prefetch_mb`` — host→device bytes shipped this
        round; ``crossdev_prefetch_stall_s`` — wall time blocked on
        gather + transfer completion (an upper bound on the stall the
        prefetch failed to hide; the gather itself runs while the
        device computes)."""
        cd = self.cd
        data = self.data
        c = cd.cohort_size
        if self._stream_bufs is None:
            self._stream_bufs = (data.cohort_buffers(cd.n_slots),
                                 data.cohort_buffers(cd.n_slots))
        # FedAvg weights need sizes only — host metadata, no client data
        sizes = data.cohort_sizes(cohorts)
        wn, got_any = self._wn_fn(jnp.asarray(sizes),
                                  jnp.asarray(c_alive))
        alive_dev = self.transport.put_replicated(jnp.asarray(c_alive))
        prefetch_bytes = 0
        stall_s = 0.0
        sh = self.transport.replicated

        def gather_put(t):
            nonlocal prefetch_bytes, stall_s
            t0 = time.monotonic()
            x, y, m, _ = data.cohort_batch(cohorts[t],
                                           out=self._stream_bufs[t % 2])
            # the sanctioned per-round-loop device_put: THE prefetch
            # seam (everywhere else fedlint's recompile-hazard rule
            # flags puts inside round loops)
            dev = tuple(
                jax.device_put(a, sh)  # fedlint: disable=recompile-hazard
                for a in (x, y, m)
            )
            # wait for the DMA (not the compute) before the host buffer
            # may be rewritten two steps from now
            jax.block_until_ready(dev)
            stall_s += time.monotonic() - t0
            return dev

        buf = gather_put(0)
        prefetch_bytes = sum(a.nbytes for a in buf) * c
        params0 = self.fed.states.params
        carry = jax.tree.map(jnp.copy, self._stream_init_carry(self.fed))
        losses = []
        for t in range(c):
            x_t, y_t, m_t = buf
            # async dispatch: the host returns before the step finishes,
            # so the next gather below overlaps this step's compute
            carry, loss_t = self._stream_step(
                params0, carry, x_t, y_t, m_t, alive_dev[t], wn[t])
            if t + 1 < c:
                buf = gather_put(t + 1)
            losses.append(loss_t)
        self.fed = self._stream_finalize(self.fed, carry, got_any)
        self.crossdev_last["crossdev_prefetch_mb"] = round(
            prefetch_bytes / 1e6, 2)
        self.crossdev_last["crossdev_prefetch_stall_s"] = round(
            stall_s, 4)
        return {
            "train_loss": np.stack([np.asarray(l) for l in losses]),
            "alive": self.fed.alive,
        }

    def _publish_crossdev_status(self, r: int, mean_loss: float) -> None:
        """One status record for the whole cross-device driver (there
        are no per-node processes to speak for themselves) — the
        monitor/webapp throughput pane reads the crossdev_* gauges."""
        if self.logger.dir is None:
            return
        publish_status(
            self.logger.dir / "status", 0,
            {
                "role": "crossdev",
                "round": r + 1,
                "loss": mean_loss,
                "peers": self.cd.n_slots - 1,
                "recompiles": obs_trace.xla_recompiles(),
                **self.crossdev_last,
                **self.devprof_last,
            },
        )

    def evaluate(self) -> dict[str, Any]:
        """Central-test-set quality of the global model. Every slot
        holds the same aggregate post-round, so slot metrics agree; the
        mean is reported for symmetry with Scenario.evaluate."""
        metrics = self._eval_fn(self.fed, self._x_test, self._y_test)
        acc = np.asarray(metrics["accuracy"]).astype(np.float64)
        loss = np.asarray(metrics["loss"]).astype(np.float64)
        return {
            "per_node_accuracy": [float(a) for a in acc],
            "per_node_loss": [float(l) for l in loss],
            "mean_accuracy": float(acc.mean()),
            "min_accuracy": float(acc.min()),
        }

    def run(self, rounds: int | None = None,
            target_accuracy: float | None = None) -> ScenarioResult:
        cfg = self.config
        cd = self.cd
        rounds = rounds if rounds is not None else cfg.training.rounds
        obs_trace.install_xla_listener()
        round_times: list[float] = []
        rounds_to_target = None
        ev = None
        ev_round = -1
        start_round = int(np.asarray(self.fed.round))
        tr = self.transport
        for r in range(start_round, start_round + rounds):
            t0 = time.monotonic()
            self.notify(Events.ROUND_STARTED, {"round": r})
            alive = self._advance_membership(r)
            # row-major cohorts: cohort step t runs clients
            # sampled[t*n_slots:(t+1)*n_slots] (sample_cohorts pins the
            # assignment shared by every arm)
            sampled, cohorts = sample_cohorts(
                cd.n_clients, cd.clients_per_round, cd.cohort_size, r,
                seed=cd.seed, weights=self._sample_weights,
            )
            c_alive = alive[cohorts]
            if self._stream:
                metrics = self._run_streamed_round(cohorts, c_alive)
            else:
                x, y, mask, sizes = self.data.cohort_batch(sampled)
                shape2 = (cd.cohort_size, cd.n_slots)
                # leading axis is the SCAN axis (cohort_size), not the
                # slot axis — replicate; the per-slot split happens
                # inside the compiled round
                args = tuple(
                    tr.put_replicated(jnp.asarray(a.reshape(
                        shape2 + a.shape[1:])))
                    for a in (x, y, mask, sizes)
                ) + (tr.put_replicated(jnp.asarray(c_alive)),)
                self.fed, metrics = self._round_fn(self.fed, *args)
            jax.block_until_ready(self.fed.states.params)
            dt = time.monotonic() - t0
            round_times.append(dt)
            if devprof.enabled():
                # streamed rounds have no single round program to cost
                # (per-step dispatch) — their gauges carry wall + memory
                # watermarks only; the monolithic scan costs once
                if self._devprof_flops is False:
                    self._devprof_flops = (
                        round_flops(self._round_fn, self.fed, *args)
                        if not self._stream else None
                    )
                self.devprof_last = devprof.round_gauges(
                    self._devprof_flops, dt, tr.n_devices)
            self.last_sampled = sampled
            self.last_cohorts = cohorts
            self.last_cohort_alive = c_alive
            self.notify(Events.AGGREGATION_FINISHED, {"round": r})

            losses = np.asarray(metrics["train_loss"]).astype(np.float64)
            live = c_alive.astype(bool)
            mean_loss = float(losses[live].mean()) if live.any() else 0.0
            # live throughput gauges (round 20): the monitor's cl/s and
            # prefetch columns; prefetch keys exist only on streamed
            # rounds (renderers show "-" when absent)
            self.crossdev_last["crossdev_clients_per_s"] = round(
                len(sampled) / dt, 2) if dt > 0 else None
            self._publish_crossdev_status(r, mean_loss)
            self.logger.log_metrics(
                {"Train/loss": mean_loss,
                 "Train/round_time_s": dt,
                 "CrossDev/clients_sampled": int(len(sampled)),
                 "CrossDev/clients_alive": int(live.sum())},
                step=r, round=r,
            )
            if cfg.training.eval_every and (r + 1) % cfg.training.eval_every == 0:
                ev = self.evaluate()
                ev_round = r
                self.logger.log_metrics(
                    {"Test/mean_accuracy": ev["mean_accuracy"]},
                    step=r, round=r,
                )
                if (target_accuracy is not None
                        and rounds_to_target is None
                        and ev["mean_accuracy"] >= target_accuracy):
                    rounds_to_target = r + 1
            self.notify(Events.ROUND_FINISHED, {"round": r, "time_s": dt})

        last_round = start_round + rounds - 1
        if ev is None or ev_round != last_round:
            ev = self.evaluate()
            if (target_accuracy is not None and rounds_to_target is None
                    and ev["mean_accuracy"] >= target_accuracy):
                rounds_to_target = last_round + 1
        self.notify(Events.LEARNING_FINISHED, {})
        return ScenarioResult(
            final_accuracy=ev["mean_accuracy"],
            per_node_accuracy=ev["per_node_accuracy"],
            rounds_run=rounds,
            round_times_s=round_times,
            history=self.logger.history,
            rounds_to_target=rounds_to_target,
            min_accuracy=ev["min_accuracy"],
        )

    def close(self) -> None:
        self.logger.close()
