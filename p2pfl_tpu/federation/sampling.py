"""K-of-N client sampling for the cross-device regime (round 13).

Cross-device FL never has all N clients in a round: each round draws a
cohort of K participants (FedJAX's sampled-client idiom, PAPERS.md).
The draw must be

- **seeded + round-keyed**: every process that knows ``(seed, round)``
  reproduces the same cohort, so a restarted or remote driver agrees
  with the bench record without any coordination message;
- **replacement-free**: a client appears at most once per round, so
  FedAvg's example-count weights are well defined;
- **optionally data-weighted**: clients holding more examples are
  sampled proportionally more often (the classic unbiased-FedAvg
  configuration when combined with uniform aggregation weights).

Dead clients are NOT filtered here — fault composition happens at the
cohort level (a sampled-but-dead client's slot is masked out of
training and aggregation by the ``membership.py`` alive vector), so the
sample stream itself stays independent of churn history and therefore
reproducible from ``(seed, round)`` alone.
"""

from __future__ import annotations

import numpy as np

# Domain-separation constant folded into the per-round generator key so
# cohort draws never collide with other consumers of the scenario seed
# (data shuffles use seed*100003+cid, membership uses raw seed).
_SAMPLER_DOMAIN = 0x5A3C


def sample_clients(
    n_clients: int,
    k: int,
    round_num: int,
    seed: int = 0,
    weights: np.ndarray | None = None,
) -> np.ndarray:
    """Draw K of N client ids for ``round_num`` — deterministic in
    ``(seed, round_num)``, without replacement.

    ``weights`` (e.g. per-client data sizes) biases the draw; they are
    normalized here and need not sum to 1. Zero-weight clients are
    never drawn, so there must be at least ``k`` positive weights.
    """
    if k < 1 or k > n_clients:
        raise ValueError(f"cannot sample k={k} of n_clients={n_clients}")
    rng = np.random.default_rng([seed, round_num, _SAMPLER_DOMAIN])
    p = None
    if weights is not None:
        w = np.asarray(weights, np.float64)
        if w.shape != (n_clients,):
            raise ValueError(
                f"weights shape {w.shape} != ({n_clients},)"
            )
        if np.any(w < 0) or not np.all(np.isfinite(w)):
            raise ValueError("sampling weights must be finite and >= 0")
        total = w.sum()
        if total <= 0:
            raise ValueError("sampling weights sum to zero")
        if np.count_nonzero(w) < k:
            raise ValueError(
                f"only {np.count_nonzero(w)} clients have positive "
                f"weight; cannot draw k={k} without replacement"
            )
        p = w / total
    return rng.choice(n_clients, size=k, replace=False, p=p).astype(np.int64)


def sample_cohorts(
    n_clients: int,
    clients_per_round: int,
    cohort_size: int,
    round_num: int,
    seed: int = 0,
    weights: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """One round's draw already shaped for the cohort scan:
    ``(sampled [K], cohorts [cohort_size, n_slots])`` row-major —
    cohort step t runs clients ``sampled[t*n_slots:(t+1)*n_slots]``.

    Centralizing the reshape (round 20) keeps every consumer — the
    monolithic scan, the sharded chunk arms, and the streamed prefetch
    driver, which materializes one cohort ROW at a time — on the
    IDENTICAL client-to-slot assignment for a given ``(seed,
    round_num)``. Prefetch-order determinism under resampling is a
    property of this function, pinned by tests/test_cross_device.py.
    """
    if clients_per_round % cohort_size:
        raise ValueError(
            f"clients_per_round={clients_per_round} must be a multiple "
            f"of cohort_size={cohort_size}")
    sampled = sample_clients(n_clients, clients_per_round, round_num,
                             seed=seed, weights=weights)
    n_slots = clients_per_round // cohort_size
    return sampled, sampled.reshape(cohort_size, n_slots)
