"""Membership: heartbeats, timeouts, eviction — on a virtual clock.

Parity with fedstellar/heartbeater.py (BEAT every HEARTBEAT_PERIOD=4 s,
eviction after NODE_TIMEOUT=20 s of silence :88-101) re-designed for
determinism: time is a virtual clock advanced by the round loop, so a
"node died at round r" fault produces byte-identical runs. In DCN mode
the same class runs on wall-clock time fed by real heartbeat receipts.
"""

from __future__ import annotations

import numpy as np

from p2pfl_tpu.config.schema import FaultEvent, ProtocolConfig
from p2pfl_tpu.federation.events import Events, Observable


class Membership(Observable):
    """Tracks {node: last_seen}; derives the alive mask.

    ``beat(i, t)`` = a heartbeat from node i at time t (heartbeater
    add_node analog). ``advance_to(t)`` evicts nodes silent for longer
    than ``node_timeout_s`` and fires NODE_DIED (clear_nodes analog).
    Fault injection (FaultEvent crash/recover) simply stops/resumes a
    node's heartbeats.
    """

    def __init__(self, n_nodes: int, protocol: ProtocolConfig | None = None,
                 virtual: bool = True):
        """``virtual=True`` (simulation): the clock synthesizes beats
        for nodes whose ``beating`` flag is set, so liveness is fully
        scripted by FaultEvents. ``virtual=False`` (DCN/real mode):
        only explicit :meth:`beat` calls count, and a silent remote
        node is evicted after the timeout."""
        super().__init__()
        self.protocol = protocol or ProtocolConfig()
        self.n = n_nodes
        self.virtual = virtual
        self.last_seen = np.zeros(n_nodes, np.float64)
        self.beating = np.ones(n_nodes, bool)  # currently emitting beats
        self.alive = np.ones(n_nodes, bool)  # membership view
        self.departed = np.zeros(n_nodes, bool)  # explicit STOP leavers
        self.clock = 0.0

    def beat(self, node: int, t: float | None = None) -> None:
        if self.departed[node]:
            # a straggler heartbeat (in flight when the STOP flood
            # landed) must not resurrect an explicitly departed node —
            # only a recover fault / rejoin clears the flag
            return
        t = self.clock if t is None else t
        self.last_seen[node] = t
        if not self.alive[node]:
            self.alive[node] = True
            self.notify(Events.NODE_RECOVERED, {"node": node, "t": t})

    def apply_fault(self, fault: FaultEvent) -> None:
        if fault.kind == "crash":
            self.beating[fault.node] = False
        elif fault.kind == "recover":
            self.departed[fault.node] = False
            self.beating[fault.node] = True
            self.beat(fault.node)
        else:
            raise ValueError(f"unknown fault kind {fault.kind!r}")

    def advance_to(self, t: float) -> np.ndarray:
        """Advance the virtual clock: beating nodes emit heartbeats at
        heartbeat_period_s cadence; silent nodes past node_timeout_s
        are evicted. Returns the alive mask."""
        period = self.protocol.heartbeat_period_s
        if self.virtual:
            # synthesize the beats scripted nodes emitted in (clock, t];
            # never move last_seen backwards past a real beat() call
            for node in range(self.n):
                if self.beating[node]:
                    self.last_seen[node] = max(
                        self.last_seen[node], (t // period) * period
                    )
        self.clock = t
        timeout = self.protocol.node_timeout_s
        for node in range(self.n):
            if self.alive[node] and t - self.last_seen[node] > timeout:
                self.alive[node] = False
                self.notify(Events.NODE_DIED, {"node": node, "t": t})
        return self.alive.copy()

    def evict(self, node: int) -> None:
        """Explicit departure (a STOP announcement): immediate eviction
        instead of waiting out the heartbeat timeout, sticky against
        straggler beats."""
        self.departed[node] = True
        self.beating[node] = False
        if self.alive[node]:
            self.alive[node] = False
            self.notify(Events.NODE_DIED, {"node": node, "t": self.clock})

    def get_nodes(self) -> list[int]:
        """Current members (heartbeater.get_nodes analog)."""
        return [int(i) for i in np.flatnonzero(self.alive)]
