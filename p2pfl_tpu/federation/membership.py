"""Membership: heartbeats, timeouts, eviction — on a virtual clock.

Parity with fedstellar/heartbeater.py (BEAT every HEARTBEAT_PERIOD=4 s,
eviction after NODE_TIMEOUT=20 s of silence :88-101) re-designed for
determinism: time is a virtual clock advanced by the round loop, so a
"node died at round r" fault produces byte-identical runs. In DCN mode
the same class runs on wall-clock time fed by real heartbeat receipts.
"""

from __future__ import annotations

import numpy as np

from p2pfl_tpu.config.schema import FaultEvent, ProtocolConfig
from p2pfl_tpu.federation.events import Events, Observable
from p2pfl_tpu.obs import flight


class Membership(Observable):
    """Tracks {node: last_seen}; derives the alive mask.

    ``beat(i, t)`` = a heartbeat from node i at time t (heartbeater
    add_node analog). ``advance_to(t)`` evicts nodes silent for longer
    than ``node_timeout_s`` and fires NODE_DIED (clear_nodes analog).
    Fault injection (FaultEvent crash/recover/join) simply stops/
    resumes a node's heartbeats.

    Round 11 adds the suspect/probe state machine the socket plane
    wires to ACTUAL peer-death detection: a node whose heartbeats time
    out becomes SUSPECT (``NODE_DIED`` fires — the existing timeout
    semantics are unchanged); the owner then probes a reconnect under
    exponential backoff (``backoff_base_s * 2^k``, capped), and after
    ``retry_limit`` failed probes ``evict()`` makes the departure
    sticky. A heartbeat at any point before final eviction clears the
    suspicion (``NODE_RECOVERED``).
    """

    def __init__(self, n_nodes: int, protocol: ProtocolConfig | None = None,
                 virtual: bool = True, retry_limit: int = 3,
                 backoff_base_s: float = 0.5, backoff_max_s: float = 8.0):
        """``virtual=True`` (simulation): the clock synthesizes beats
        for nodes whose ``beating`` flag is set, so liveness is fully
        scripted by FaultEvents. ``virtual=False`` (DCN/real mode):
        only explicit :meth:`beat` calls count, and a silent remote
        node is evicted after the timeout."""
        super().__init__()
        self.protocol = protocol or ProtocolConfig()
        self.n = n_nodes
        self.virtual = virtual
        self.last_seen = np.zeros(n_nodes, np.float64)
        self.beating = np.ones(n_nodes, bool)  # currently emitting beats
        self.alive = np.ones(n_nodes, bool)  # membership view
        self.departed = np.zeros(n_nodes, bool)  # explicit STOP leavers
        self.clock = 0.0
        # suspect/probe bookkeeping (socket plane death detection)
        self.retry_limit = int(retry_limit)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.probe_failures = np.zeros(n_nodes, np.int64)
        self.next_probe = np.full(n_nodes, np.inf, np.float64)

    def beat(self, node: int, t: float | None = None) -> None:
        if self.departed[node]:
            # a straggler heartbeat (in flight when the STOP flood
            # landed) must not resurrect an explicitly departed node —
            # only a recover fault / rejoin clears the flag
            return
        t = self.clock if t is None else t
        self.last_seen[node] = t
        self.probe_failures[node] = 0
        self.next_probe[node] = np.inf
        if not self.alive[node]:
            self.alive[node] = True
            flight.record("membership.recover", node=node, t=t)
            self.notify(Events.NODE_RECOVERED, {"node": node, "t": t})

    def apply_fault(self, fault: FaultEvent) -> None:
        if fault.kind == "crash":
            self.beating[fault.node] = False
        elif fault.kind in ("recover", "join", "restart"):
            # "join" is recover at this layer; the state transfer
            # (checkpoint-format model fetch) is the caller's job.
            # "restart" is the same except the caller resumes from the
            # node's own checkpoint instead of a peer's state
            self.departed[fault.node] = False
            self.beating[fault.node] = True
            self.beat(fault.node)
            if fault.kind == "join":
                flight.record("membership.join", node=fault.node,
                              t=self.clock)
                self.notify(Events.NODE_JOINED,
                            {"node": fault.node, "t": self.clock})
            elif fault.kind == "restart":
                flight.record("membership.restart", node=fault.node,
                              t=self.clock)
                self.notify(Events.NODE_RESTARTED,
                            {"node": fault.node, "t": self.clock})
        elif fault.kind == "partition":
            # the cut itself lives in the transport (netem / node
            # sever sets); membership only records + fans out the event
            flight.record("membership.partition", groups=fault.groups,
                          t=self.clock)
            self.notify(Events.LINK_PARTITIONED,
                        {"groups": fault.groups, "t": self.clock})
        elif fault.kind == "heal":
            # the heal observation IS the amnesty trigger: every sticky
            # departure re-enters the probe machine (satellite: the
            # round-11 dead end where a healed partition's peers stayed
            # departed forever once retry_limit was exhausted)
            for node in np.flatnonzero(self.departed):
                self.amnesty(int(node))
            flight.record("membership.heal", t=self.clock)
            self.notify(Events.LINK_HEALED, {"t": self.clock})
        else:
            raise ValueError(f"unknown fault kind {fault.kind!r}")

    def amnesty(self, node: int, t: float | None = None) -> None:
        """Clear a sticky departure on a heal observation — keyed on
        the HEAL, not on the retry budget: the budget stays exhausted
        until this runs, which is exactly the round-11 dead end. The
        node is NOT declared alive; it re-enters as a suspect with a
        fresh probe budget and an immediately-due probe, so the
        existing probe machinery (or its next heartbeat) resurrects it
        if and only if it is actually reachable again."""
        t = self.clock if t is None else t
        if not self.departed[node] and self.alive[node]:
            return  # nothing to forgive
        self.departed[node] = False
        self.probe_failures[node] = 0
        self.next_probe[node] = t
        flight.record("membership.amnesty", node=node, t=t)

    # -- suspect/probe state machine (socket plane) ----------------------
    def probes_due(self, t: float | None = None) -> list[int]:
        """Suspect nodes whose next reconnect probe is due at ``t``:
        dead (heartbeat timeout) but not yet finally evicted, with
        retry budget remaining."""
        t = self.clock if t is None else t
        return [
            int(i) for i in range(self.n)
            if (not self.alive[i] and not self.departed[i]
                and self.probe_failures[i] < self.retry_limit
                and t >= self.next_probe[i])
        ]

    def probe_failed(self, node: int, t: float | None = None) -> bool:
        """Record one failed reconnect probe; schedule the next under
        exponential backoff. Returns True when the retry budget is
        exhausted — the caller should ``evict`` (and tear down lanes).
        """
        t = self.clock if t is None else t
        self.probe_failures[node] += 1
        k = int(self.probe_failures[node])
        flight.record("membership.probe_failed", node=node, k=k,
                      final=k >= self.retry_limit)
        if k >= self.retry_limit:
            return True
        delay = min(self.backoff_base_s * (2.0 ** k), self.backoff_max_s)
        self.next_probe[node] = t + delay
        return False

    def advance_to(self, t: float) -> np.ndarray:
        """Advance the virtual clock: beating nodes emit heartbeats at
        heartbeat_period_s cadence; silent nodes past node_timeout_s
        are evicted. Returns the alive mask."""
        period = self.protocol.heartbeat_period_s
        if self.virtual:
            # synthesize the beats scripted nodes emitted in (clock, t];
            # never move last_seen backwards past a real beat() call.
            # Vectorized (round 13): the cross-device clock covers every
            # VIRTUAL client, so this runs at n=10k+ per round
            self.last_seen = np.where(
                self.beating,
                np.maximum(self.last_seen, (t // period) * period),
                self.last_seen,
            )
        self.clock = t
        timeout = self.protocol.node_timeout_s
        died = np.flatnonzero(self.alive & (t - self.last_seen > timeout))
        if len(died):
            self.alive[died] = False
            # open the suspect window: first reconnect probe due one
            # backoff base from the detected timeout
            self.probe_failures[died] = 0
            self.next_probe[died] = t + self.backoff_base_s
            for node in died:  # per-node events, in index order as before
                flight.record("membership.suspect", node=int(node), t=t)
                self.notify(Events.NODE_DIED, {"node": int(node), "t": t})
        return self.alive.copy()

    def evict(self, node: int) -> None:
        """Explicit departure (a STOP announcement): immediate eviction
        instead of waiting out the heartbeat timeout, sticky against
        straggler beats."""
        self.departed[node] = True
        self.beating[node] = False
        self.next_probe[node] = np.inf  # no further reconnect probes
        flight.record("membership.evict", node=node, t=self.clock)
        if self.alive[node]:
            self.alive[node] = False
            self.notify(Events.NODE_DIED, {"node": node, "t": self.clock})

    def get_nodes(self) -> list[int]:
        """Current members (heartbeater.get_nodes analog)."""
        return [int(i) for i in np.flatnonzero(self.alive)]
