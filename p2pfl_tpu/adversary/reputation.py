"""Per-peer trust from round-wise update statistics.

The defense side of the adversary subsystem: every round, each node's
update delta (trained params minus the round-start reference) is
scored against the cohort, the scores feed an EWMA trust state, and
trust rescales the ``weights`` argument of ``Aggregator.aggregate`` —
a reputation-weighted FedAvg that needs NO new aggregator math, only
weight shaping (which also composes with the robust aggregators: a
zeroed weight is a masked row for Krum/TrimmedMean/FedMedian too).

Scoring (``cohort_scores``) combines two Krum-flavored statistics,
both computed from one ``[k, d]`` flattened-delta matrix:

- **direction**: cosine of each delta to the cohort's mean UNIT
  direction. Normalizing before averaging matters: an amplified
  attack (sign-flip at scale 10) dominates a raw mean, making the
  honest majority look anti-aligned; unit-normalizing caps every
  node's pull on the consensus direction at 1.
- **magnitude**: ``min(|d|, med)/max(|d|, med)`` against the cohort
  median norm — both a 10x-amplified update and a free-rider's ~zero
  delta are implausible, and cosine alone cannot see either (the
  free-rider's direction is undefined, the scaled attack's is honest).

The same formula runs in jnp inside the jitted SPMD round fn (scores
returned as round metrics) and in numpy inside the socket session
(entry counts vary with gossip timing — eager jnp here would recompile
per distinct shape, the exact failure the round-7 numpy fast path
removed). ``xp`` parametrizes the namespace so there is ONE formula.

What reputation does and does not defend is documented in
docs/architecture.md (threat model): it is an UNWEIGHTED-majority
heuristic — it assumes the honest cohort agrees directionally, so it
degrades under extreme non-IID shards and offers nothing against
attacks inside the plausibility envelope (small-scale poisoning,
colluding majorities).
"""

from __future__ import annotations

import numpy as np

from p2pfl_tpu.obs import flight

try:  # jnp is optional at import time: the monitor itself is numpy-only
    import jax.numpy as jnp
except Exception:  # pragma: no cover - jax is a hard dep of the repo
    jnp = None


def cohort_scores(deltas, present=None, xp=np):
    """Score each row of a ``[k, d]`` delta matrix in ``[0, 1]``.

    ``present`` (optional ``[k]`` bool) masks rows out of BOTH the
    consensus statistics and the output (absent rows score 0). Works
    under jit with ``xp=jnp`` (fixed shapes, ``where``-masked) and
    eagerly with ``xp=np``.
    """
    eps = 1e-12
    deltas = deltas.astype(xp.float32)
    k = deltas.shape[0]
    pm = (
        xp.ones((k,), xp.float32) if present is None
        else present.astype(xp.float32)
    )
    norms = xp.sqrt(xp.sum(deltas * deltas, axis=1))
    # a non-finite delta (diverged / overflowed params) is the worst
    # possible evidence: drop the row from the consensus AND score it
    # 0, instead of letting one NaN poison every node's statistics
    finite = xp.isfinite(norms)
    pm = pm * finite.astype(xp.float32)
    norms = xp.where(finite, norms, 0.0)
    deltas = xp.where(finite[:, None], deltas, 0.0)
    unit = deltas / (norms + eps)[:, None]
    # cohort consensus: mean of present UNIT deltas (see module doc)
    direction = xp.sum(unit * pm[:, None], axis=0) / xp.maximum(
        xp.sum(pm), 1.0
    )
    dnorm = xp.sqrt(xp.sum(direction * direction)) + eps
    cos = unit @ (direction / dnorm)
    # magnitude plausibility vs the present-median norm
    if xp is np:  # numpy: explicit selection (nanmedian warns on
        vals = norms[pm > 0]  # all-NaN, and shapes may vary anyway)
        med = np.float32(np.median(vals)) if vals.size else np.float32(0.0)
    else:  # jnp: fixed-shape nan-masked median, jit-safe
        med = xp.nanmedian(xp.where(pm > 0, norms, xp.nan))
        med = xp.where(xp.isnan(med), xp.float32(0.0), med)
    ratio = (xp.minimum(norms, med) + eps) / (xp.maximum(norms, med) + eps)
    score = xp.clip(cos, 0.0, 1.0) * ratio
    return xp.where(pm > 0, score, 0.0)


def spmd_trust_obs(params_stacked, ref_stacked, present):
    """The SPMD round fn's per-node score: flatten each node's delta
    and score the cohort. jnp, fixed-shape, jit-safe — returned as a
    round metric and EWMA-folded on the host (ReputationMonitor)."""
    import jax

    n = jax.tree.leaves(params_stacked)[0].shape[0]
    deltas = jnp.concatenate(
        [
            (p.astype(jnp.float32) - r.astype(jnp.float32)).reshape(n, -1)
            for p, r in zip(
                jax.tree.leaves(params_stacked), jax.tree.leaves(ref_stacked)
            )
        ],
        axis=1,
    )
    return cohort_scores(deltas, present=present, xp=jnp)


class ReputationMonitor:
    """Host-side EWMA trust state, shared by both execution paths.

    - SPMD: ``observe(scores, mask)`` with the round metric; the
      scenario multiplies ``weights_vector()`` into the mixing
      matrix's columns for the NEXT round (trust acts with one round
      of lag — round 0 is uniform).
    - socket: ``observe_entries(reference, entries)`` scores a
      session's stored models at aggregation time (numpy — see module
      doc), attributing multi-contributor partial aggregates to every
      contributor; ``entry_scales(keys)`` rescales entry weights.

    ``cutoff`` hard-zeroes the weight of nodes whose trust fell below
    it: for FedAvg that excludes them from the mean; for robust
    aggregators a zero weight is a masked row.
    """

    def __init__(self, n_nodes: int, alpha: float = 0.7,
                 cutoff: float = 0.15):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.n_nodes = n_nodes
        self.alpha = float(alpha)
        self.cutoff = float(cutoff)
        self.trust = np.ones(n_nodes, np.float32)
        # first observation REPLACES the optimistic prior instead of
        # EWMA-blending with it: blending from 1.0 gives an attacker
        # scoring ~0 a trust of 1-alpha after round 0 — above any
        # sane cutoff, so two poisoned aggregates land before
        # exclusion, which at sign-flip scale 10 is fatal
        self._seen = np.zeros(n_nodes, bool)
        # nodes caught red-handed by DIRECT evidence: a singleton
        # entry (one contributor, nothing to hide behind) scoring
        # below the cutoff. Only these anchor the explaining-away in
        # observe_entries — keying it on low *trust* latched onto
        # false positives (an honest node whose round-0 appearance was
        # merged with the attacker), permanently shielding the real
        # attacker behind the mislabeled node.
        self._confirmed_bad = np.zeros(n_nodes, bool)
        #: per-round trust snapshots (monitor/webapp export)
        self.history: list[list[float]] = []

    # -- observations ---------------------------------------------------
    def observe(self, scores: np.ndarray, mask: np.ndarray | None = None):
        """EWMA-fold one round of per-node scores. ``mask`` selects
        which nodes were actually observed (absent nodes keep their
        trust — silence is not evidence)."""
        scores = np.asarray(scores, np.float32)
        scores = np.where(np.isfinite(scores), scores, 0.0)
        obs = (
            np.ones(self.n_nodes, bool) if mask is None
            else np.asarray(mask, bool)
        )
        a = self.alpha
        before = set(self.suspects())
        blended = np.where(self._seen, (1.0 - a) * self.trust + a * scores,
                           scores)
        self.trust = np.where(obs, blended, self.trust).astype(np.float32)
        self._seen = self._seen | obs
        after = set(self.suspects())
        for node in sorted(after - before):
            flight.record("reputation.exclude", node=node,
                          trust=float(self.trust[node]),
                          cutoff=self.cutoff)
        for node in sorted(before - after):
            flight.record("reputation.restore", node=node,
                          trust=float(self.trust[node]))
        self.history.append([float(t) for t in self.trust])

    def observe_entries(self, reference, entries) -> None:
        """Socket-path observation: ``entries`` is
        ``[(contributor_frozenset, params_tree), ...]`` from one
        session; ``reference`` is the round-start params the session's
        owner trained from. Each entry's delta is scored; an entry's
        score becomes an observation of every ATTRIBUTED contributor
        (a partial aggregate containing an attacker is itself
        anomalous — its honest co-contributors take a transient hit
        and recover via the EWMA, while the attacker is hit every
        round), with evidence weight ``1/|attributed|``: a singleton
        entry is direct evidence about one node, a k-way merged
        partial only says *someone* in it misbehaved. Attribution is
        sharpened by explaining-away anchored on DIRECT evidence —
        see the loop comments for why both halves (the singleton
        anchor, the redirect) are load-bearing."""
        import jax

        ref_flat = np.concatenate(
            [np.asarray(l, np.float32).ravel()
             for l in jax.tree.leaves(reference)]
        )
        keys = [k for k, _ in entries]
        deltas = np.stack(
            [
                np.concatenate(
                    [np.asarray(l, np.float32).ravel()
                     for l in jax.tree.leaves(p)]
                ) - ref_flat
                for _, p in entries
            ]
        )
        scores = cohort_scores(deltas, xp=np)
        # pass 1: singleton entries are DIRECT evidence — one scoring
        # below the cutoff confirms its contributor as bad (sticky:
        # an honest node's own update essentially never scores that
        # low, and an attacker alternating good rounds should not be
        # able to launder its merged partials)
        for key, s in zip(keys, scores):
            ids = [c for c in key if 0 <= c < self.n_nodes]
            if len(ids) == 1 and float(s) < self.cutoff:
                self._confirmed_bad[ids[0]] = True
        obs_sum = np.zeros(self.n_nodes, np.float64)
        obs_cnt = np.zeros(self.n_nodes, np.float64)
        for key, s in zip(keys, scores):
            ids = [c for c in key if 0 <= c < self.n_nodes]
            if not ids:
                continue
            # explaining-away, anchored on CONFIRMED culprits only: an
            # entry containing a caught-red-handed node says nothing
            # new about its other contributors — the low score is
            # fully explained by the known-bad model merged in.
            # Attributing such entries to everyone let the attacker's
            # partials keep dragging honest co-contributors down every
            # round — gossip timing could leave an honest node ranked
            # BELOW the attacker at the end (the measured ~1/3 flake
            # of the 4-node socket recovery test).
            bad = [c for c in ids if self._confirmed_bad[c]]
            targets = bad or ids
            ev = 1.0 / max(len(targets), 1)
            for c in targets:
                obs_sum[c] += float(s) * ev
                obs_cnt[c] += ev
        mask = obs_cnt > 0
        per_node = np.where(mask, obs_sum / np.maximum(obs_cnt, 1e-9), 0.0)
        self.observe(per_node.astype(np.float32), mask)

    # -- weight shaping --------------------------------------------------
    def weights_vector(self) -> np.ndarray:
        """Per-node weight multipliers: trust, hard-zeroed below the
        cutoff."""
        return np.where(self.trust < self.cutoff, 0.0, self.trust).astype(
            np.float32
        )

    def entry_scales(self, keys) -> np.ndarray:
        """Per-entry weight multipliers for a session's stored models:
        the MIN trust multiplier over each entry's contributors (an
        unknown/empty contributor set is left at 1.0 — no evidence,
        no penalty). Min, not mean: contamination is not additive — a
        partial merged with a zero-trust sign-flipper is poisoned
        through and through, and averaging it in at half weight still
        wrecks the aggregate at attack scale 10. Better to drop the
        honest contributions trapped in it than to admit the poison."""
        wv = self.weights_vector()
        out = []
        for key in keys:
            ids = [c for c in key if 0 <= c < self.n_nodes]
            out.append(float(np.min(wv[ids])) if ids else 1.0)
        return np.asarray(out, np.float32)

    def suspects(self) -> list[int]:
        """Nodes currently below the trust cutoff (status export)."""
        return [int(i) for i in np.flatnonzero(self.trust < self.cutoff)]
