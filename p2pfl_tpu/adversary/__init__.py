"""Adversary & trust subsystem: attack injection + reputation.

``attacks``    pure pytree transforms on outgoing updates, applied
               identically (bit-for-bit) by the SPMD round fn and the
               socket node — see attacks.py.
``reputation`` per-peer EWMA trust from round-wise update statistics,
               feeding reputation-weighted aggregation on both paths —
               see reputation.py.
"""

from p2pfl_tpu.adversary.attacks import (
    ATTACKS,
    MODEL_ATTACKS,
    AttackSpec,
    attack_key,
    flip_labels,
    malicious_indices,
    poison_stacked,
    poison_update,
)
from p2pfl_tpu.adversary.reputation import (
    ReputationMonitor,
    cohort_scores,
    spmd_trust_obs,
)

__all__ = [
    "ATTACKS",
    "MODEL_ATTACKS",
    "AttackSpec",
    "attack_key",
    "flip_labels",
    "malicious_indices",
    "poison_stacked",
    "poison_update",
    "ReputationMonitor",
    "cohort_scores",
    "spmd_trust_obs",
]
