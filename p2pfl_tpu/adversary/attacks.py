"""Adversarial transforms on outgoing model updates.

Fedstellar's defining workload is federations *under attack*
(fedstellar/attacks/aggregation.py: label flipping, sample poisoning,
model poisoning; SURVEY §3.6) — this module is its TPU-native
re-design. Every model-level attack is ONE pure, jit-compatible pytree
transform ``poison_update(params, ref, node_idx, round_num, spec)``:

- the SPMD simulation path applies it inside the jitted round fn to
  the rows of the stacked params selected by a STATIC malicious mask
  (``poison_stacked`` below — a trace-time Python loop over the
  malicious indices, so the math per node is literally the same
  function call the socket path makes);
- the socket path applies it on the host (CPU backend) to the
  learner's trained params before they enter the node's own session
  and every ``_send_params``.

Same seed + same (node, round) ⇒ **bit-identical** poisoned leaves on
both paths — pinned by tests/test_adversary.py with tolerance 0. That
parity is what makes a robustness number measured on the fast SPMD
path transferable to the socket deployment.

``ref`` is the params the node started the round from (the previous
aggregate it trained on): delta-space attacks (sign-flip, scaled
poisoning, free-riding) are defined against it. The label-flip data
poisoning acts at the learner level instead (``flip_labels``) and
leaves the update transform as identity.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = Any

#: model-level update transforms + the learner-level data attack
ATTACKS = ("none", "signflip", "scale", "noise", "freerider", "labelflip")

#: attacks that transform the outgoing update (vs poisoning the data)
MODEL_ATTACKS = ("signflip", "scale", "noise", "freerider")


@dataclasses.dataclass(frozen=True)
class AttackSpec:
    """What a malicious node does to its outgoing update.

    ``kind``  one of :data:`ATTACKS`.
    ``scale`` delta amplification factor (signflip/scale) or the
              noise standard deviation multiplier (noise).
    ``seed``  PRNG root for stochastic attacks; combined with
              (node_idx, round_num) via ``fold_in`` so every node and
              round draws distinct — but path-independent — noise.
    """

    kind: str = "none"
    scale: float = 10.0
    seed: int = 0

    def __post_init__(self):
        if self.kind not in ATTACKS:
            raise ValueError(
                f"unknown attack kind {self.kind!r}; have {ATTACKS}"
            )

    @property
    def poisons_updates(self) -> bool:
        return self.kind in MODEL_ATTACKS


def attack_key(seed: int, node_idx, round_num) -> jax.Array:
    """Deterministic per-(node, round) key — identical on both paths.
    ``node_idx``/``round_num`` may be traced ints (SPMD path folds in
    ``fed.round``)."""
    key = jax.random.PRNGKey(seed)
    key = jax.random.fold_in(key, node_idx)
    return jax.random.fold_in(key, round_num)


def poison_update(params: Params, ref: Params, node_idx, round_num,
                  spec: AttackSpec) -> Params:
    """Transform ONE node's outgoing update. Pure and jit-compatible;
    preserves every leaf's shape and dtype.

    - ``signflip``   send ``ref - scale * (params - ref)``: the
      training delta reversed and amplified — the classic
      sign-flipping model poisoning.
    - ``scale``      send ``ref + scale * (params - ref)``: honest
      direction, amplified — drags the average past the optimum.
    - ``noise``      add Gaussian noise with std ``scale * std(delta)``
      per leaf (relative sizing keeps the attack meaningful across
      layers with very different weight magnitudes).
    - ``freerider``  send ``ref`` unchanged: a stale echo of the model
      the node received, contributing nothing while collecting the
      aggregate (weight-unit free-riding).
    - ``none``/``labelflip``  identity (labelflip poisons the DATA).
    """
    kind = spec.kind
    if kind in ("none", "labelflip"):
        return params
    if kind == "freerider":
        return jax.tree.map(lambda r, p: r.astype(p.dtype), ref, params)
    if kind == "signflip":
        s = jnp.float32(spec.scale)
        return jax.tree.map(
            lambda p, r: (r.astype(jnp.float32)
                          - s * (p.astype(jnp.float32)
                                 - r.astype(jnp.float32))).astype(p.dtype),
            params, ref,
        )
    if kind == "scale":
        s = jnp.float32(spec.scale)
        return jax.tree.map(
            lambda p, r: (r.astype(jnp.float32)
                          + s * (p.astype(jnp.float32)
                                 - r.astype(jnp.float32))).astype(p.dtype),
            params, ref,
        )
    if kind == "noise":
        key = attack_key(spec.seed, node_idx, round_num)
        leaves, treedef = jax.tree.flatten(params)
        ref_leaves = jax.tree.leaves(ref)
        out = []
        # per-leaf fold_in by POSITION: the same leaf order falls out
        # of the same pytree on both paths (serialize round-trips keep
        # leaf order), so the noise bits match exactly
        for i, (p, r) in enumerate(zip(leaves, ref_leaves)):
            lk = jax.random.fold_in(key, i)
            d = p.astype(jnp.float32) - r.astype(jnp.float32)
            std = jnp.sqrt(jnp.mean(d * d) + 1e-12)
            noise = jax.random.normal(lk, p.shape, jnp.float32)
            out.append(
                (p.astype(jnp.float32)
                 + jnp.float32(spec.scale) * std * noise).astype(p.dtype)
            )
        return jax.tree.unflatten(treedef, out)
    raise ValueError(f"unknown attack kind {kind!r}")


def poison_stacked(stacked: Params, ref_stacked: Params,
                   malicious: np.ndarray, round_num,
                   spec: AttackSpec) -> Params:
    """Apply :func:`poison_update` to the rows of a ``[n, ...]``-stacked
    params tree selected by a STATIC boolean ``malicious`` mask.

    The mask must be a host array (compile-time constant): only
    malicious rows are touched, via a trace-time loop of
    ``.at[i].set(poison_update(row_i))`` — each poisoned row is the
    EXACT same per-node computation the socket path runs, which is
    what makes the two paths bit-identical (vmapping the transform
    could legally reassociate the arithmetic).
    """
    if spec.kind in ("none", "labelflip"):
        return stacked
    malicious = np.asarray(malicious, bool)
    out = stacked
    for i in np.flatnonzero(malicious):
        i = int(i)
        row = jax.tree.map(lambda x: x[i], stacked)
        ref = jax.tree.map(lambda x: x[i], ref_stacked)
        poisoned = poison_update(row, ref, i, round_num, spec)
        out = jax.tree.map(lambda o, v: o.at[i].set(v), out, poisoned)
    return out


def flip_labels(y: np.ndarray, num_classes: int) -> np.ndarray:
    """Label-flip data poisoning: ``y -> (C - 1) - y`` (the reference's
    ``labelFlipping`` attack permutes targets; the involution keeps it
    deterministic and dataset-agnostic). Applied to a malicious node's
    TRAIN shard only — identical math on the socket path (per-node
    shard) and the SPMD path (stacked rows), so the two simulations
    train on the same poisoned bits."""
    return (num_classes - 1 - np.asarray(y)).astype(np.asarray(y).dtype)


def malicious_indices(n_nodes: int, fraction: float, seed: int = 0,
                      nodes: tuple[int, ...] | list[int] = ()) -> np.ndarray:
    """The deterministic malicious cohort as a ``[n]`` bool mask.

    Explicit ``nodes`` win; otherwise ``floor(fraction * n)`` nodes are
    drawn from a seeded permutation — both paths (and both processes of
    a multi-process federation) compute the same cohort from config
    alone."""
    mask = np.zeros(n_nodes, bool)
    if nodes:
        mask[list(int(i) for i in nodes)] = True
        return mask
    k = int(fraction * n_nodes)
    if k <= 0:
        return mask
    order = np.random.default_rng(seed).permutation(n_nodes)
    mask[order[:k]] = True
    return mask
