from p2pfl_tpu.config.schema import (
    DataConfig,
    FaultEvent,
    ModelConfig,
    NodeConfig,
    ProtocolConfig,
    ScenarioConfig,
    TrainingConfig,
)

__all__ = [
    "DataConfig",
    "FaultEvent",
    "ModelConfig",
    "NodeConfig",
    "ProtocolConfig",
    "ScenarioConfig",
    "TrainingConfig",
]
