from p2pfl_tpu.config.schema import (
    DataConfig,
    FaultEvent,
    LoraConfig,
    ModelConfig,
    NodeConfig,
    ProtocolConfig,
    ScenarioConfig,
    TrainingConfig,
)

__all__ = [
    "DataConfig",
    "FaultEvent",
    "LoraConfig",
    "ModelConfig",
    "NodeConfig",
    "ProtocolConfig",
    "ScenarioConfig",
    "TrainingConfig",
]
